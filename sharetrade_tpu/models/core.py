"""Model interface shared by every policy network.

The reference has exactly one network — the TF graph built inline in
``QDecisionPolicyActor.scala:38-50`` — and its "interface" is the actor's
message protocol. Here the interface is three pure functions, so any model
slots under ``vmap`` (agent batches), ``lax.scan`` (time), and ``shard_map``
(devices) without special cases:

- ``init(key) -> params``              parameter pytree
- ``apply(params, obs, carry) -> (ModelOut, carry)``   one observation
- ``init_carry() -> carry``            recurrent state seed (``()`` if none)

``ModelOut.logits`` doubles as Q-values for value-based agents (a Q-head's
outputs and a policy head's logits occupy the same slot); ``ModelOut.value``
is the critic estimate for actor-critic agents (zeros for plain Q/PG heads,
keeping the pytree structure uniform across model kinds).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class ModelOut(NamedTuple):
    logits: jax.Array  # (num_actions,) action preferences / Q-values
    value: jax.Array   # scalar critic estimate (0.0 for valueless heads)
    # Auxiliary regularizer the forward pass wants added to the training
    # loss — the MoE load-balance term (parallel/moe.py), without which a
    # capacity-dispatch gate can collapse onto one expert and silently drop
    # overflowing tokens. 0.0 for models with no such term; losses weight it
    # by LearnerConfig.aux_loss_coef.
    aux: jax.Array | float = 0.0


@dataclass(frozen=True)
class Model:
    """A policy network as a bundle of pure functions (stateless module)."""

    init: Callable[[jax.Array], Any]
    apply: Callable[[Any, jax.Array, Any], tuple[ModelOut, Any]]
    init_carry: Callable[[], Any] = field(default=lambda: ())
    obs_dim: int = 0
    num_actions: int = 3
    name: str = "model"
    # Optional native batched forward (params, (B, obs_dim), carry_batch) ->
    # (ModelOut with leading B, carry_batch). Models whose hot path benefits
    # from an explicit batch dimension (the transformer folds the agent batch
    # into the flash kernel's batch*heads grid) provide this; everyone else
    # gets vmap of `apply` via `apply_batched`.
    apply_batch: Callable[[Any, jax.Array, Any], tuple[ModelOut, Any]] | None = None
    # Optional whole-unroll training forward (params, (T, B, obs_dim) obs,
    # unroll-start carry_batch) -> (logits (T, B, A), values (T, B), aux).
    # Models that can replay a trajectory more cheaply than T per-step
    # forwards provide this (the episode-mode transformer runs ONE banded
    # pass over the unroll's tick sequence); rollout.replay_forward
    # dispatches to it.
    apply_unroll: Callable[[Any, jax.Array, Any],
                           tuple[jax.Array, jax.Array, jax.Array]] | None = None
    # Optional PRECOMPUTED-ROLLOUT pair. Models whose heavy trunk depends
    # only on action-independent inputs (the episode transformer attends
    # over price ticks alone; the agent's wallet enters at the head) provide
    # these, and rollout.collect_rollout then computes the whole unroll's
    # trunk in ONE parallel pass instead of T sequential cache-attention
    # steps — the measured 70% of the flagship chunk
    # (benchmarks/profile_flagship.py).
    #
    # apply_rollout_trunk(params, obs (B, obs_dim), future_ticks (B, T),
    #                     carry) -> (hn_base (B, T+1, d), carry after T) —
    #   row i is the trunk output for env step t0+i; row T serves the
    #   bootstrap value.
    # apply_rollout_head(params, hn_base_row (B, d), obs (B, obs_dim))
    #   -> ModelOut (batched) — the tiny state-dependent head, applied
    #   per-step inside the sequential env loop.
    apply_rollout_trunk: Callable[[Any, jax.Array, jax.Array, Any],
                                  tuple[jax.Array, Any]] | None = None
    apply_rollout_head: Callable[[Any, jax.Array, jax.Array],
                                 ModelOut] | None = None
    # Optional SHARED-TRUNK training replay: same signature and output as
    # apply_unroll, but exploiting the same agent-invariance as the
    # precomputed-rollout pair — every healthy agent's stored price series
    # is identical (lockstep batch over one shared series; quarantined rows
    # are zero-sanitized and loss-masked), so the banded trunk runs ONCE
    # for a representative row and only the portfolio head runs per agent.
    # Removes the factor-B trunk redundancy of apply_unroll from the PPO/
    # PG/A2C update phase (B=128 at the flagship shape — the update was the
    # measured 70% of the post-round-3 chunk). Gradients are exact, not
    # approximate: B identical trunk paths, each pulled back by its agent's
    # head cotangent, equal one shared path pulled back by their sum.
    # Provided only by models whose learners guarantee the lockstep
    # invariant (see agents/rollout.py agent-invariance notes).
    apply_unroll_shared: Callable[[Any, jax.Array, Any],
                                  tuple[jax.Array, jax.Array, jax.Array]] | None = None
    # Optional LINEARITY-FACTORED rollout head. When the head is affine in
    # (trunk output, portfolio features) — logits = dense(policy,
    # hn + dense(port, feats)) with no nonlinearity between — it splits
    # exactly into a trunk term, precomputable for the WHOLE unroll in one
    # batched matmul outside the env scan, plus a tiny (3 -> A) portfolio
    # term evaluated per step. The sequential loop's per-iteration matmuls
    # drop from three d-sized GEMMs to one 3-wide contraction — the round-4
    # measured bound at d=256 was exactly those per-iteration head matmuls.
    #
    # rollout_head_factored(params, hn_base (T+1, d)) ->
    #   (base_logits (T+1, A) f32, base_values (T+1,) f32,
    #    pf_fn(obs (B, obs_dim)) -> (dlogits (B, A) f32, dvalues (B,) f32))
    # with ModelOut-equivalent totals base + pf (pinned by
    # tests/test_models.py::test_factored_rollout_head_matches_exact).
    rollout_head_factored: Callable | None = None
    # Optional SERVING pair (serve/engine.py — the continuous-batching
    # inference tier). Models with a prefill/incremental split provide
    # both; stateless or simple-carry models need neither (the engine
    # runs ``apply_batched`` over slot-gathered carries, which imposes no
    # cross-row constraint).
    #
    # apply_prefill(params, obs (B, obs_dim)) -> (ModelOut batched,
    #   carry_batch) — the episode-start forward for a COLD batch (every
    #   row a fresh session). Rows are independent: unlike
    #   ``apply_batch``'s t[0] dispatch, no lockstep assumption.
    # apply_serve_batch(params, obs (B, obs_dim), carry_batch) ->
    #   (ModelOut batched, carry_batch) — one incremental step for a WARM
    #   batch whose rows sit at HETEROGENEOUS episode steps (per-row ring
    #   slots). This is exactly the invariant a serving batch violates in
    #   ``apply_batch``: training batches step in lockstep, user sessions
    #   don't.
    apply_prefill: Callable[[Any, jax.Array],
                            tuple[ModelOut, Any]] | None = None
    apply_serve_batch: Callable[[Any, jax.Array, Any],
                                tuple[ModelOut, Any]] | None = None
    # Optional precision hook: cast_carry(carry, compute_dtype) -> carry,
    # casting exactly the carry leaves the model's forward produces in the
    # compute dtype (K/V caches, recurrent cells). The precision policy
    # (precision.py cast_carry) calls this when the model provides it;
    # None means "every floating leaf follows the compute dtype". The
    # episode transformer needs the hook: its ``hist`` carry holds raw
    # PRICES that its forwards always rebuild in f32 — blanket-casting it
    # would both lose tick precision and destabilize the scan carry dtype.
    cast_carry: Callable[[Any, Any], Any] | None = None


def apply_batched(model: Model, params: Any, obs_batch: jax.Array,
                  carry_batch: Any) -> tuple[ModelOut, Any]:
    """Batched forward over agents — the one call site shape every learner
    uses (SURVEY.md §7.2: workers become a batch dimension, not actors)."""
    if model.apply_batch is not None:
        return model.apply_batch(params, obs_batch, carry_batch)
    return jax.vmap(
        lambda o, c: model.apply(params, o, c))(obs_batch, carry_batch)


_EPS = 1e-6


def compute_dtype(params: Any):
    """The dtype a forward pass should COMPUTE in: the floating dtype of
    the params it was handed. Models derive their activation-cast dtype
    from this instead of a build-time closure constant, so the SAME model
    object serves both halves of the precision policy (precision.py): the
    fp32 masters (eval, fp32 mode) and the bf16 compute copy the policy
    casts at each update boundary. Trace-time only (dtypes are static
    under jit). Falls back to f32 for paramless/empty subtrees."""
    for leaf in jax.tree.leaves(params):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            return leaf.dtype
    return jnp.float32


def rows_finite(tree: Any, batch: int) -> jax.Array:
    """(batch,) bool: True where every batched leaf row of ``tree`` is
    finite. THE row-finiteness predicate behind the fault-quarantine
    story — shared by the heal/election predicate
    (agents/base.election_health) and the shared-trunk replay's
    representative election (models/transformer_episode.apply_unroll_shared)
    so the two can never silently diverge. Leaves whose leading dim is not
    ``batch`` (unbatched scalars/tables) are ignored; integer leaves pass
    trivially (isfinite is all-True on ints)."""
    ok = jnp.ones((batch,), bool)
    for leaf in jax.tree.leaves(tree):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == batch:
            ok &= jnp.all(jnp.isfinite(leaf.reshape(batch, -1)), axis=-1)
    return ok


def tick_window_features(obs: jax.Array, window: int) -> jax.Array:
    """(B, obs_dim) observations -> (B, window, 3) scale-invariant per-tick
    features: price relative to the window's last price, log-return, and a
    zero channel (the window-mode transformer marks its portfolio token
    there). Shared by every tick-sequence policy (transformer window mode,
    TCN) so the tokenization cannot silently diverge between families."""
    prices = obs[:, :window].astype(jnp.float32)
    anchor = jnp.maximum(prices[:, -1:], _EPS)
    rel = prices / anchor - 1.0
    logp = jnp.log(jnp.maximum(prices, _EPS))
    log_ret = jnp.concatenate(
        [jnp.zeros_like(logp[:, :1]), logp[:, 1:] - logp[:, :-1]], axis=1)
    return jnp.stack([rel, log_ret, jnp.zeros_like(rel)], axis=-1)


def portfolio_features(budget: jax.Array, shares: jax.Array,
                       anchor: jax.Array) -> jax.Array:
    """(…,) scalars -> (…, 3) normalized portfolio features; ``anchor`` is
    the window's newest price. One definition for every policy head (window
    transformer's portfolio token, episode mode's head injection, TCN)."""
    anchor = jnp.maximum(anchor, _EPS)
    return jnp.stack([budget / (anchor * 100.0), shares / 100.0,
                      jnp.ones_like(budget)], axis=-1)


def dense_init(key: jax.Array, in_dim: int, out_dim: int, *,
               scale: float | None = None, dtype=jnp.float32) -> dict[str, jax.Array]:
    """Dense layer params. Default init is He-normal (std = sqrt(2/in)).

    ``scale`` overrides the stddev — the reference uses plain
    ``RandomNormalInitializer()`` (stddev 1.0) for both layers
    (QDecisionPolicyActor.scala:41,45); parity mode passes ``scale=1.0``.
    """
    std = jnp.sqrt(2.0 / in_dim) if scale is None else scale
    w = jax.random.normal(key, (in_dim, out_dim), dtype) * jnp.asarray(std, dtype)
    return {"w": w, "b": jnp.zeros((out_dim,), dtype)}


def dense(params: dict[str, jax.Array], x: jax.Array) -> jax.Array:
    # preferred_element_type keeps MXU accumulation in f32 even when
    # params/activations are bf16 (pallas_guide.md: "Missing preferred_element_type").
    return (
        jnp.dot(x, params["w"], preferred_element_type=jnp.float32).astype(x.dtype)
        + params["b"]
    )
