"""Episode-mode transformer: the tick stream IS the sequence.

The window-mode policy (models/transformer.py) re-embeds and re-attends the
full price window for every env step, so a T-step PPO replay reprocesses
T x (window+1) tokens per agent even though consecutive windows share all
but one tick. Episode mode is the TPU-first inversion: embed each tick
ONCE, run sliding-window (banded) flash attention over the episode's tick
sequence (ops/attention.py local_window), and read one output per env step
— an O(T + L*window) forward replaces T O(window) window forwards (~15-50x
fewer tokens for the BASELINE unrolls). This is also the long-context
story: the training pass handles long unrolls (the full 5,845-step MSFT
episode fits one banded pass) as ONE sequence instead of a stack of
windows; past ~512k K/V elements the kernel switches to streaming one K/V
block per grid step (ops/attention.py ``_STREAM_KV_ELEMS``), so sequence
length is bounded by HBM, not VMEM — 32k-token banded gradients compile
and run.

Architecture notes (deliberately different from window mode — this is a
redesign, not a re-tiling):

- Tokens carry step-invariant features only (log-return and its magnitude):
  keys must mean the same thing to every query that sees them, so the
  window-anchored price normalization of window mode cannot appear on the
  key side. Scale-invariance across decades of price levels is preserved —
  log-returns are dimensionless.
- Positions enter via rotary embeddings (RoPE) at ABSOLUTE tick indices:
  relative offsets inside each query's band are then position-exact
  regardless of where the band sits in the episode, and rollout/replay use
  the same indices so their numerics agree.
- The portfolio state (budget, shares) is injected on the head side: a
  learned projection added to the final-layer representation at each step's
  query position. Attention over prices does not depend on the agent's
  wallet; the decision head combines market context with it (the classic
  features+state actor-critic split). The reference folds budget/shares
  into the network input instead (QDecisionPolicyActor.scala:18, 203-dim
  x); window mode keeps that shape, episode mode redesigns it.

Rollout runs incrementally with a per-layer rolling K/V cache of exactly
``window`` entries (a Mistral-style sliding-window cache): one token's
qkv/mlp plus a 1 x window attention row per step. The training replay runs
the banded forward over [carried history | chunk ticks]. Both compute the
same function of the same tick series: the carry stores the
(L-1)*(window-1) ticks the deepest layer's receptive field reaches past
the chunk boundary, episode starts left-pad with the first price on both
paths, and RoPE uses absolute indices — so replayed logits match rollout
logits to numerical tolerance (tests/test_models.py::TestEpisodeMode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.config import ConfigError

from sharetrade_tpu.models.core import (
    Model, ModelOut, compute_dtype, dense, dense_init, portfolio_features,
    rows_finite)
from sharetrade_tpu.models.ffn import ffn_apply
from sharetrade_tpu.models.transformer import _layer_norm
from sharetrade_tpu.ops.attention import flash_attention

_EPS = 1e-6


def _rope(x: jax.Array, positions: jax.Array, *, base: float = 10000.0):
    """Rotary position embedding. x: (B, H, S, D) with D even; positions:
    (B, S) absolute indices (negative is fine — episode-start padding sits
    at negative ticks)."""
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[:, None, :, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _tick_features(series: jax.Array) -> jax.Array:
    """(B, S) prices -> (B, S, 3) step-invariant token features."""
    logp = jnp.log(jnp.maximum(series, _EPS))
    ret = jnp.concatenate(
        [jnp.zeros_like(logp[:, :1]), logp[:, 1:] - logp[:, :-1]], axis=1)
    return jnp.stack([ret, jnp.abs(ret), jnp.zeros_like(ret)], axis=-1)


def episode_transformer_policy(obs_dim: int = 203, num_actions: int = 3, *,
                               num_layers: int = 2, num_heads: int = 4,
                               head_dim: int = 64, mlp_ratio: int = 4,
                               dtype=jnp.float32,
                               use_pallas: bool | None = None,
                               attention_fn=None,
                               pp_mesh=None, pp_axis: str = "pp",
                               pp_batch_axis: str | None = None,
                               moe_experts: int = 0, ep_mesh=None,
                               ep_axis: str = "ep", moe_top_k: int = 0,
                               moe_capacity_factor: float = 1.25,
                               moe_dispatch: str = "psum",
                               remat_blocks: bool = False,
                               seam_mesh=None) -> Model:
    """Build the episode-mode policy (``ModelConfig.seq_mode="episode"``).

    ``attention_fn(q, k, v, window) -> out`` overrides the local banded
    flash kernel in the REPLAY pass — the sequence-parallel hook
    (``halo_banded_attention_sharded`` shards the tick sequence over an sp
    mesh axis, parallel/episode_sp.py). The rollout stays local regardless:
    the incremental path is a 1-token cache attention and the episode-start
    prefill pins the local kernel (its L*(window-1)+1 rows are too short to
    shard), so only the replay span constrains the sp size.

    ``moe_experts`` routes every block's FFN through the shared MoE
    dispatch (models/ffn.py): dense-mask top-1, capacity top-k, ep-sharded
    psum, or token-sharded all_to_all — the same variants window mode
    composes with. ``pp_mesh`` pipelines the banded blocks over its
    ``pp_axis`` (GPipe, parallel/pipeline.py; blocks stored stacked so
    stage i's slice shards onto pp-device i). Microbatches cut the agent
    batch when it divides the stage count; otherwise — the batch-of-1
    trunk/shared-replay passes — the SEQUENCE is cut into streamed chunks
    whose banded halo flows chunk-to-chunk through a stage-local pipeline
    carry (the sp halo-exchange trick, parallel/episode_sp.py, applied
    along the schedule), so those passes pipeline along time instead of
    idling (stages-1)/stages of the schedule; m=1 remains only for
    sequences shorter than two window-1 chunks. pp + MoE is rejected
    (nested shard_maps), as is pp + a non-local attention override.
    """
    if head_dim % 2:
        raise ConfigError(f"RoPE needs an even head_dim, got {head_dim}")
    window = obs_dim - 2                    # ticks per observation window
    hist_len = (num_layers - 1) * (window - 1)

    def _pin_hist(hist):
        # The carry→series seam (round 8, the MULTICHIP involuntary-remat
        # fix): the replay/trunk passes concatenate the carry's history
        # rows into the tick series, and on an sp/ep mesh the partitioned
        # attention's sequence-sharded (transposed-mesh) spec propagates
        # BACKWARD through that concat onto the dp-sharded
        # ``ts.carry['hist']`` program input — XLA then bridges the two
        # with a full replicate-and-repartition per step ("Involuntary
        # full rematerialization", the [4,1,2]→[1,2,4] warning in
        # MULTICHIP_r01..r05). Pinning the (B, hist_len) slice replicated
        # here — bytes, not megabytes — turns that into one planned,
        # warning-free all-gather and stops the backward propagation at
        # an explicit seam; the TrainState's own carry keeps its
        # canonical dp spec via the jit in/out shardings
        # (parallel/sharding.py).
        if seam_mesh is None:
            return hist
        from sharetrade_tpu.parallel.sharding import canonical_sharding
        return jax.lax.with_sharding_constraint(
            hist, canonical_sharding(seam_mesh))
    d_model = num_heads * head_dim
    sm_scale = head_dim ** -0.5
    def local_attention(q, k, v, w):
        return flash_attention(q, k, v, causal=True, sm_scale=sm_scale,
                               local_window=w, use_pallas=use_pallas)

    if attention_fn is None:
        attention_fn = local_attention
    if pp_mesh is not None:
        if pp_mesh.shape[pp_axis] != num_layers:
            raise ConfigError(
                f"pipeline_blocks needs num_layers == pp size "
                f"({num_layers} != {pp_mesh.shape[pp_axis]})")
        if moe_experts:
            raise ConfigError("pipeline_blocks + moe_experts is unsupported "
                             "(nested shard_maps); pick one partitioning")
        if attention_fn is not local_attention:
            raise ConfigError("pipeline_blocks requires the local banded "
                             "attention (no sp override inside a stage)")

    def block_ffn(blk, h):
        # batch_axis keeps the dp sharding of the token batch inside the
        # MoE's shard_map (a dp x ep mesh would otherwise all_gather the
        # batch — correct but silently losing the dp split window mode
        # keeps, models/transformer.py:157).
        return ffn_apply(
            blk, h, moe_experts=moe_experts, ep_mesh=ep_mesh,
            ep_axis=ep_axis, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor,
            moe_dispatch=moe_dispatch, batch_axis=pp_batch_axis)

    def init(key):
        keys = jax.random.split(key, 5 + 6 * num_layers)
        params = {
            "embed": dense_init(keys[0], 3, d_model, dtype=dtype),
            "port": dense_init(keys[1], 3, d_model, scale=0.02, dtype=dtype),
            "policy": dense_init(keys[2], d_model, num_actions, scale=0.01,
                                 dtype=dtype),
            "value": dense_init(keys[3], d_model, 1, dtype=dtype),
            "final_ln": {"scale": jnp.ones((d_model,), dtype),
                         "bias": jnp.zeros((d_model,), dtype)},
            "blocks": [],
        }
        for i in range(num_layers):
            k = keys[5 + 6 * i: 5 + 6 * (i + 1)]
            block = {
                "ln1": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
                "qkv": dense_init(k[0], d_model, 3 * d_model, dtype=dtype),
                "proj": dense_init(k[1], d_model, d_model,
                                   scale=0.02 / max(num_layers, 1), dtype=dtype),
                "ln2": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
            }
            if moe_experts:
                from sharetrade_tpu.parallel.moe import init_moe_params
                block["moe"] = init_moe_params(
                    k[2], moe_experts, d_model, mlp_ratio * d_model,
                    dtype=dtype)
            else:
                block["mlp_in"] = dense_init(
                    k[2], d_model, mlp_ratio * d_model, dtype=dtype)
                block["mlp_out"] = dense_init(
                    k[3], mlp_ratio * d_model, d_model,
                    scale=0.02 / max(num_layers, 1), dtype=dtype)
            params["blocks"].append(block)
        if pp_mesh is not None:
            # Stacked layout (leading dim = stages) so stage i's slice
            # lands on pp-device i through the pipeline shard_map.
            params["blocks"] = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *params["blocks"])
        return params

    def blocks_of(params):
        """Per-layer block list regardless of storage layout (list, or
        stacked (S, ...) leaves under pp — indexing the stacked leaves
        outside the pipeline shard_map lets XLA gather the slice, which
        only the small incremental/one-token paths do)."""
        if pp_mesh is None:
            return params["blocks"]
        return [jax.tree.map(lambda x: x[i], params["blocks"])
                for i in range(num_layers)]

    def block_apply(blk, x, positions, *, attn, kv_offset):
        """One banded pre-LN block over (B, S, d). Returns
        ``(x, (k_tail, v_tail), aux)`` — the rotated K/V of the cached
        window (always computed; a few window-length rows) and the FFN's
        MoE balance loss."""
        bsz, s_len = x.shape[0], x.shape[1]
        # Compute dtype follows the handed-in params (fp32 masters or the
        # precision policy's bf16 copy); the build ``dtype`` = master init.
        dtype = compute_dtype(blk)
        h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = dense(blk["qkv"], h).reshape(
            bsz, s_len, 3, num_heads, head_dim)
        q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
        q = _rope(q, positions)
        k = _rope(k, positions)
        x_attn = attn(q, k, v, window)
        lo = s_len - window - kv_offset
        kv_tail = (k[:, :, lo:lo + window], v[:, :, lo:lo + window])
        x_attn = x_attn.transpose(0, 2, 1, 3).reshape(
            bsz, s_len, d_model).astype(dtype)
        x = x + dense(blk["proj"], x_attn)
        h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        y, aux = block_ffn(blk, h)
        return x + y, kv_tail, aux

    def forward(params, series, positions, port_feats, *, want_kv=False,
                attn=None, kv_offset=0):
        """Banded forward over a (B, S) tick series.

        ``port_feats`` (B, S, 3) is zero except at query positions. Returns
        (logits (B, S, A), values (B, S), per-layer rotated (k, v) lists
        when ``want_kv``, post-final_ln hidden (B, S, d), aux scalar).
        ``kv_offset`` shifts the cached window ``offset`` ticks back from
        the series end (the precomputed-rollout trunk's last tick belongs
        to the bootstrap position, one step past where the cache should
        stop). ``attn`` overrides the attention implementation (the prefill
        pins the LOCAL kernel: its sequence is the fixed L*(window-1)+1
        rows, too short to shard).
        """
        bsz, s_len = series.shape
        dtype = compute_dtype(params)
        x = dense(params["embed"], _tick_features(series).astype(dtype))
        if pp_mesh is not None:   # overrides rejected at build: always local
            x, kv, aux = _forward_blocks_pipelined(
                params, x, positions, kv_offset)
        else:
            attn = attn or attention_fn
            blk_fn = block_apply
            if remat_blocks:
                # Block-granular rematerialization: the backward recomputes
                # each block's internals (qkv, attention, FFN activations)
                # from its input, so only the O(S·d) block boundaries are
                # stored — the FLOPs-for-HBM trade that lets the d≥1024
                # tier run long replays without learner.remat's coarser
                # whole-pass checkpoint. Functionally a no-op (pinned by
                # test_models.py::test_remat_blocks_matches_exact).
                def blk_fn(blk, x, positions, *, attn, kv_offset):
                    return jax.checkpoint(
                        lambda b, h, p: block_apply(
                            b, h, p, attn=attn, kv_offset=kv_offset)
                    )(blk, x, positions)
            kv, aux = [], jnp.float32(0.0)
            for blk in blocks_of(params):
                x, kv_tail, blk_aux = blk_fn(
                    blk, x, positions, attn=attn, kv_offset=kv_offset)
                kv.append(kv_tail)
                aux = aux + blk_aux
        hn = _layer_norm(x, params["final_ln"]["scale"],
                         params["final_ln"]["bias"])
        hn_port = hn + dense(params["port"], port_feats.astype(dtype))
        logits = dense(params["policy"], hn_port).astype(jnp.float32)
        values = dense(params["value"], hn_port).astype(jnp.float32)[..., 0]
        return logits, values, (kv if want_kv else []), hn, aux

    def _forward_blocks_pipelined(params, x, positions, kv_offset):
        """The block stack as a GPipe pipeline over ``pp_axis``.

        Positions ride the pipeline state as one extra f32 channel (every
        stage applies RoPE at the same absolute indices; a pipeline stage
        receives exactly one state array). K/V tails and the per-block aux
        escape as pipeline side outputs (pipeline_apply side_template).
        Microbatches cut the agent batch when it divides by the stage
        count; otherwise the SEQUENCE is cut into streamed chunks
        (_forward_blocks_pipelined_seq) — the batch-of-1 trunk/shared-
        replay passes pipeline along time instead of idling
        (stages-1)/stages of the schedule. m=1 (full bubble) remains only
        for sequences too short to chunk.
        """
        bsz, s_len = x.shape[0], x.shape[1]
        stages = num_layers
        if bsz % stages == 0:
            return _forward_blocks_pipelined_batch(
                params, x, positions, kv_offset, m=stages)
        plan = _seq_chunk_plan(s_len, kv_offset)
        if plan is not None:
            return _forward_blocks_pipelined_seq(
                params, x, positions, kv_offset, plan)
        return _forward_blocks_pipelined_batch(
            params, x, positions, kv_offset, m=1)

    def _forward_blocks_pipelined_batch(params, x, positions, kv_offset, m):
        """Microbatches cut the AGENT batch (independent rows)."""
        from jax.sharding import PartitionSpec as P
        from sharetrade_tpu.parallel.pipeline import pipeline_apply
        dtype = compute_dtype(params)
        bsz, s_len = x.shape[0], x.shape[1]
        mb_b = bsz // m
        state = jnp.concatenate(
            [x.astype(jnp.float32),
             positions[..., None].astype(jnp.float32)], axis=-1)
        mb = state.reshape((m, mb_b) + state.shape[1:])
        b_axis = pp_batch_axis
        if b_axis is not None and mb_b % pp_mesh.shape[b_axis]:
            b_axis = None       # odd microbatch: replicate

        def stage_fn(blk, st):
            xb = st[..., :d_model].astype(dtype)
            pos = st[..., d_model].astype(jnp.int32)
            xb, (k_t, v_t), aux = block_apply(
                blk, xb, pos, attn=local_attention, kv_offset=kv_offset)
            if b_axis is not None:
                # The K/V sides carry their own (sharded) rows; the scalar
                # aux must be made uniform across the batch axis to honor
                # its replicated side spec.
                aux = jax.lax.pmean(aux, b_axis)
            out = jnp.concatenate(
                [xb.astype(jnp.float32), st[..., d_model:]], axis=-1)
            return out, {"k": k_t, "v": v_t, "aux": aux}

        if remat_blocks:
            # Per-(stage, tick) remat: the backward recomputes the block's
            # internals from the tick's input state, so a stage stores only
            # its schedule-tick boundaries.
            stage_fn = jax.checkpoint(stage_fn)

        # Side templates use the per-device LOCAL batch shape; the K/V
        # sides declare the batch axis in their specs so each dp shard
        # contributes its own rows (a replicated spec would silently hand
        # one shard's K/V to every agent).
        b_shard = 1 if b_axis is None else pp_mesh.shape[b_axis]
        side_template = {
            "k": jnp.zeros((mb_b // b_shard, num_heads, window, head_dim),
                           dtype),
            "v": jnp.zeros((mb_b // b_shard, num_heads, window, head_dim),
                           dtype),
            "aux": jnp.float32(0.0),
        }
        side_specs = {"k": P(None, None, b_axis),
                      "v": P(None, None, b_axis), "aux": P()}
        mb_out, sides = pipeline_apply(
            stage_fn, params["blocks"], mb, pp_mesh, axis=pp_axis,
            mb_spec=P(None, b_axis), side_template=side_template,
            side_specs=side_specs)
        x = mb_out[..., :d_model].reshape(bsz, s_len, d_model).astype(dtype)
        # sides: leaves (S_stages, M, ...). Reassemble per-layer K/V over
        # the microbatched agent axis; aux sums over stages (each stage's
        # aux is identical across its microbatches' mean contributions, so
        # sum over M then divide by M keeps the per-token mean semantics).
        kv = [(sides["k"][l].reshape(bsz, num_heads, window, head_dim),
               sides["v"][l].reshape(bsz, num_heads, window, head_dim))
              for l in range(num_layers)]
        aux = jnp.sum(sides["aux"]) / m
        return x, kv, aux

    def _seq_chunk_plan(s_len, kv_offset):
        """(m, chunk_len, pad) for sequence-chunk pipelining, or None when
        the sequence is too short for >1 chunk. Constraints (all static):
        chunk_len >= window-1 (the banded halo fits in one predecessor
        chunk, and the chunk-0 exact-head pass needs window-1 local rows)
        and the cache-tail slice must start inside [halo | chunk]
        (chunk_len - 1 - kv_offset - pad >= 0). More chunks shrink the
        GPipe bubble (stages-1)/(m+stages-1); 4*stages chunks put it under
        ~20% with diminishing returns beyond."""
        halo = window - 1
        if halo < 1:
            return None   # window=1: no band to carry, nothing to pipeline
        for m in range(min(s_len // halo, 4 * num_layers), 1, -1):
            chunk_len = -(-s_len // m)
            pad = m * chunk_len - s_len
            if chunk_len >= halo and chunk_len - 1 - kv_offset - pad >= 0:
                return m, chunk_len, pad
        return None

    def _forward_blocks_pipelined_seq(params, x, positions, kv_offset,
                                      plan):
        """Microbatches cut the SEQUENCE: chunk m streams through the
        stages right behind chunk m-1, and each stage hands its banded-
        attention halo (its chunk's last window-1 roped K/V rows) to the
        next chunk through a stage-local pipeline carry
        (parallel/pipeline.py carry_template) — sequential microbatches,
        the pipeline analogue of the sp halo exchange
        (parallel/episode_sp.py), with the same chunk-0 correction: the
        first chunk's zero halo would take softmax weight, so its first
        window-1 queries (whose bands sit entirely in the local prefix)
        are answered by a small plain-causal pass. End padding rides
        behind every real row, so causality keeps it invisible; the
        cache-tail side slices around it (static offset)."""
        from jax.sharding import PartitionSpec as P
        from sharetrade_tpu.parallel.pipeline import pipeline_apply
        dtype = compute_dtype(params)
        bsz, s_len = x.shape[0], x.shape[1]
        m, chunk_len, pad = plan
        halo = window - 1
        state = jnp.concatenate(
            [x.astype(jnp.float32),
             positions[..., None].astype(jnp.float32)], axis=-1)
        if pad:
            state = jnp.pad(state, ((0, 0), (0, pad), (0, 0)))
        mb = state.reshape(bsz, m, chunk_len, d_model + 1).transpose(
            1, 0, 2, 3)
        # Chunk-index flag channel: stage_fn selects the chunk-0 head
        # correction from it (a pipeline stage sees only its state array).
        flags = jnp.broadcast_to(
            jnp.arange(m, dtype=jnp.float32).reshape(m, 1, 1, 1),
            (m, bsz, chunk_len, 1))
        mb = jnp.concatenate([mb, flags], axis=-1)
        b_axis = pp_batch_axis
        if b_axis is not None and bsz % pp_mesh.shape[b_axis]:
            b_axis = None       # odd batch (the B=1 passes): replicate
        b_shard = 1 if b_axis is None else pp_mesh.shape[b_axis]
        b_loc = bsz // b_shard
        lo = chunk_len - 1 - kv_offset - pad  # tail start in [halo|chunk]

        def stage_fn(blk, st, carry):
            xb = st[..., :d_model].astype(dtype)
            pos = st[..., d_model].astype(jnp.int32)
            first = st[0, 0, d_model + 1] == 0.0
            b, c = xb.shape[0], xb.shape[1]
            h = _layer_norm(xb, blk["ln1"]["scale"], blk["ln1"]["bias"])
            qkv = dense(blk["qkv"], h).reshape(b, c, 3, num_heads, head_dim)
            q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
            q = _rope(q, pos)
            k = _rope(k, pos)
            kv_k = jnp.concatenate([carry["k"], k], axis=2)
            kv_v = jnp.concatenate([carry["v"], v], axis=2)
            # Left-pad queries so q row j aligns with key row j; the pad
            # rows' outputs are sliced off (episode_sp.py alignment trick).
            qp = jnp.pad(q, [(0, 0), (0, 0), (halo, 0), (0, 0)])
            out = local_attention(qp, kv_k, kv_v, window)[:, :, halo:]
            head_exact = local_attention(
                q[:, :, :halo], k[:, :, :halo], v[:, :, :halo], window)
            head = jnp.where(first, head_exact, out[:, :, :halo])
            attn_out = jnp.concatenate([head, out[:, :, halo:]], axis=2)
            attn_out = attn_out.transpose(0, 2, 1, 3).reshape(
                b, c, d_model).astype(dtype)
            xb = xb + dense(blk["proj"], attn_out)
            h2 = _layer_norm(xb, blk["ln2"]["scale"], blk["ln2"]["bias"])
            y, aux = block_ffn(blk, h2)
            if b_axis is not None:
                aux = jax.lax.pmean(aux, b_axis)
            xb = xb + y
            side = {"k": kv_k[:, :, lo:lo + window],
                    "v": kv_v[:, :, lo:lo + window], "aux": aux}
            new_carry = {"k": k[:, :, -halo:], "v": v[:, :, -halo:]}
            out_st = jnp.concatenate(
                [xb.astype(jnp.float32), st[..., d_model:]], axis=-1)
            return out_st, side, new_carry

        if remat_blocks:
            stage_fn = jax.checkpoint(stage_fn)

        side_template = {
            "k": jnp.zeros((b_loc, num_heads, window, head_dim), dtype),
            "v": jnp.zeros((b_loc, num_heads, window, head_dim), dtype),
            "aux": jnp.float32(0.0),
        }
        side_specs = {"k": P(None, None, b_axis),
                      "v": P(None, None, b_axis), "aux": P()}
        carry_template = {
            "k": jnp.zeros((b_loc, num_heads, halo, head_dim), dtype),
            "v": jnp.zeros((b_loc, num_heads, halo, head_dim), dtype),
        }
        mb_out, sides = pipeline_apply(
            stage_fn, params["blocks"], mb, pp_mesh, axis=pp_axis,
            mb_spec=P(None, b_axis), side_template=side_template,
            side_specs=side_specs, carry_template=carry_template)
        x = mb_out[..., :d_model].transpose(1, 0, 2, 3).reshape(
            bsz, m * chunk_len, d_model)[:, :s_len].astype(dtype)
        # Cache tail: only the LAST chunk's side row is the real series
        # tail (earlier chunks' slices are discarded).
        kv = [(sides["k"][l, -1], sides["v"][l, -1])
              for l in range(num_layers)]
        aux = jnp.sum(sides["aux"]) / m
        return x, kv, aux

    _port_feats = portfolio_features  # shared head-side normalization

    def _prefill(params, obs):
        """Episode-start pass: [first-price pads | first window], caching
        the last ``window`` rotated K/Vs per layer."""
        bsz = obs.shape[0]
        win = obs[:, :window]
        pads = jnp.repeat(win[:, :1], hist_len, axis=1)
        series = jnp.concatenate([pads, win], axis=1)
        positions = jnp.broadcast_to(
            jnp.arange(-hist_len, window, dtype=jnp.int32)[None, :],
            series.shape)
        port = jnp.zeros(series.shape + (3,), jnp.float32)
        port = port.at[:, -1, :].set(
            _port_feats(obs[:, window], obs[:, window + 1], win[:, -1]))
        logits, values, kv, _hn, aux = forward(
            params, series, positions, port, want_kv=True,
            attn=local_attention)
        cache_k = jnp.stack([k for k, _ in kv], axis=1)  # (B, L, H, W, Dh)
        cache_v = jnp.stack([v for _, v in kv], axis=1)
        carry = {
            "k": cache_k, "v": cache_v,
            "hist": jnp.repeat(win[:, :1], hist_len, axis=1),
            "t": jnp.ones((bsz,), jnp.int32),
        }
        return ModelOut(logits=logits[:, -1], value=values[:, -1],
                        aux=aux), carry

    def _incremental(params, obs, carry):
        """One-token step against the CIRCULAR K/V cache.

        The cache is a ring, not a shift register: tick j lives at slot
        ``j mod window`` forever (the prefill writes ticks 0..window-1 at
        slots 0..window-1, and step t writes its new tick t+window-1 over
        the evicted tick t-1 — same slot mod window). One
        ``dynamic_update_slice`` per layer per K/V replaces the old
        implementation's full-buffer shift-and-restack, cutting per-step
        cache traffic from O(B·L·H·W·D) copies (~100 MB/step at the
        flagship shape — measured 70% of the whole training chunk,
        benchmarks/profile_flagship.py) to one written row. Attention over
        the ring needs no reordering: RoPE is applied at ABSOLUTE positions
        before caching and softmax attention is permutation-invariant over
        the key axis, so slot order never matters.
        """
        bsz = obs.shape[0]
        dtype = compute_dtype(params)
        new, prev = obs[:, window - 1], obs[:, window - 2]
        ret = (jnp.log(jnp.maximum(new, _EPS))
               - jnp.log(jnp.maximum(prev, _EPS)))
        tok = jnp.stack([ret, jnp.abs(ret), jnp.zeros_like(ret)], axis=-1)
        x = dense(params["embed"], tok.astype(dtype))[:, None, :]  # (B, 1, d)
        pos = (carry["t"] + window - 1).astype(jnp.int32)[:, None]  # (B, 1)
        # Ring slot of the evicted tick (lockstep batch: t[0] speaks for
        # all — the apply_batch invariant).
        slot = jnp.mod(carry["t"][0] - 1, window).astype(jnp.int32)

        k_cache, v_cache = carry["k"], carry["v"]     # (B, L, H, W, Dh)
        aux = jnp.float32(0.0)
        for li, blk in enumerate(blocks_of(params)):
            h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
            qkv = dense(blk["qkv"], h).reshape(bsz, 1, 3, num_heads, head_dim)
            q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
            q = _rope(q, pos)
            k = _rope(k, pos)
            zero = jnp.int32(0)
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k[:, None], (zero, jnp.int32(li), zero, slot, zero))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v[:, None], (zero, jnp.int32(li), zero, slot, zero))
            k_all, v_all = k_cache[:, li], v_cache[:, li]
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_all,
                           preferred_element_type=jnp.float32) * sm_scale
            probs = jax.nn.softmax(s, axis=-1).astype(v_all.dtype)
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v_all)
            attn = attn.transpose(0, 2, 1, 3).reshape(
                bsz, 1, d_model).astype(dtype)
            x = x + dense(blk["proj"], attn)
            h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
            y, blk_aux = block_ffn(blk, h)
            x = x + y
            aux = aux + blk_aux
        hn = _layer_norm(x[:, 0], params["final_ln"]["scale"],
                         params["final_ln"]["bias"])
        hn = hn + dense(params["port"], _port_feats(
            obs[:, window], obs[:, window + 1], new).astype(dtype))
        logits = dense(params["policy"], hn).astype(jnp.float32)
        values = dense(params["value"], hn).astype(jnp.float32)[..., 0]
        hist = carry["hist"]
        if hist_len:
            # Tick t (the window's oldest) leaves the window this step.
            hist = jnp.concatenate([hist[:, 1:], obs[:, :1]], axis=1)
        carry = {"k": k_cache, "v": v_cache,
                 "hist": hist, "t": carry["t"] + 1}
        return ModelOut(logits=logits, value=values, aux=aux), carry

    def _incremental_serve(params, obs, carry):
        """One-token step for a batch at HETEROGENEOUS episode steps — the
        serving batch (serve/engine.py). Same math as :func:`_incremental`
        (layer norm → qkv → RoPE at per-row absolute positions → ring
        write → cache attention → FFN → heads), with the ONE lockstep
        dependency removed: the ring slot is computed PER ROW
        (``mod(t_i - 1, window)``) and the cache write is a vmapped
        ``dynamic_update_slice``, so each session writes its own slot
        regardless of where its neighbors sit in their episodes. Kept as a
        separate function rather than generalizing ``_incremental``: the
        training path's scalar-slot write is part of the pinned fp32
        golden trajectory (tests/golden/), and a scatter-lowered write
        there would change the compiled program for zero training
        benefit. Every row must be WARM (t >= 1) — cold rows belong to
        the batched prefill."""
        bsz = obs.shape[0]
        dtype = compute_dtype(params)
        new, prev = obs[:, window - 1], obs[:, window - 2]
        ret = (jnp.log(jnp.maximum(new, _EPS))
               - jnp.log(jnp.maximum(prev, _EPS)))
        tok = jnp.stack([ret, jnp.abs(ret), jnp.zeros_like(ret)], axis=-1)
        x = dense(params["embed"], tok.astype(dtype))[:, None, :]
        pos = (carry["t"] + window - 1).astype(jnp.int32)[:, None]  # (B, 1)
        slots = jnp.mod(carry["t"] - 1, window).astype(jnp.int32)   # (B,)

        k_cache, v_cache = carry["k"], carry["v"]     # (B, L, H, W, Dh)
        aux = jnp.float32(0.0)
        for li, blk in enumerate(blocks_of(params)):
            h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
            qkv = dense(blk["qkv"], h).reshape(bsz, 1, 3, num_heads, head_dim)
            q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
            q = _rope(q, pos)
            k = _rope(k, pos)

            def write_ring(cache, row, slot, _li=li):
                # cache (L, H, W, Dh) one session; row (H, 1, Dh).
                zero = jnp.int32(0)
                return jax.lax.dynamic_update_slice(
                    cache, row[None], (jnp.int32(_li), zero, slot, zero))

            k_cache = jax.vmap(write_ring)(k_cache, k, slots)
            v_cache = jax.vmap(write_ring)(v_cache, v, slots)
            k_all, v_all = k_cache[:, li], v_cache[:, li]
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_all,
                           preferred_element_type=jnp.float32) * sm_scale
            probs = jax.nn.softmax(s, axis=-1).astype(v_all.dtype)
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v_all)
            attn = attn.transpose(0, 2, 1, 3).reshape(
                bsz, 1, d_model).astype(dtype)
            x = x + dense(blk["proj"], attn)
            h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
            y, blk_aux = block_ffn(blk, h)
            x = x + y
            aux = aux + blk_aux
        hn = _layer_norm(x[:, 0], params["final_ln"]["scale"],
                         params["final_ln"]["bias"])
        hn = hn + dense(params["port"], _port_feats(
            obs[:, window], obs[:, window + 1], new).astype(dtype))
        logits = dense(params["policy"], hn).astype(jnp.float32)
        values = dense(params["value"], hn).astype(jnp.float32)[..., 0]
        hist = carry["hist"]
        if hist_len:
            hist = jnp.concatenate([hist[:, 1:], obs[:, :1]], axis=1)
        out_carry = {"k": k_cache, "v": v_cache,
                     "hist": hist, "t": carry["t"] + 1}
        return ModelOut(logits=logits, value=values, aux=aux), out_carry

    def apply_batch(params, obs, carry):
        """Batched rollout step.

        INVARIANT: the whole batch must sit at the same episode step —
        prefill-vs-incremental dispatches on ``carry["t"][0]`` alone. This
        holds for every env in this framework (the batch resets and steps in
        lockstep; rollout.py freezes finished agents in place rather than
        resetting them), but an env with per-agent resets or a
        heterogeneously-restored carry would silently run the wrong path for
        some agents. Eager (non-traced) calls assert the uniformity."""
        t = carry["t"]
        if not isinstance(t, jax.core.Tracer):
            import numpy as _np
            tn = _np.asarray(t)
            if tn.size and (tn.min() != tn.max()):
                raise ValueError(
                    f"episode transformer requires a lockstep batch: carry "
                    f"t spans [{tn.min()}, {tn.max()}]")
        return jax.lax.cond(
            t[0] == 0,
            lambda c: _prefill(params, obs),
            lambda c: _incremental(params, obs, c),
            carry)

    def apply(params, obs, carry):
        carry_b = jax.tree.map(lambda x: x[None], carry)
        outs, new_c = apply_batch(params, obs[None], carry_b)
        return (ModelOut(logits=outs.logits[0], value=outs.value[0],
                         aux=outs.aux),
                jax.tree.map(lambda x: x[0], new_c))

    def apply_unroll(params, obs, carry):
        """Training replay: ONE banded pass over [history | chunk ticks].

        ``obs`` is the stored (T, B, obs_dim) trajectory; ``carry`` the
        batched episode carry at unroll START (PPO already threads exactly
        this for recurrent policies). Returns (logits (T, B, A),
        values (T, B), aux scalar).
        """
        t_len, bsz = obs.shape[0], obs.shape[1]
        first_win = obs[0, :, :window]                     # ticks t0..t0+W-1
        newer = obs[1:, :, window - 1].T                   # (B, T-1)
        t0 = carry["t"].astype(jnp.int32)                  # (B,)
        # At episode start the carry's history is the init_carry zeros the
        # prefill never saw; substitute the first-price padding the prefill
        # actually used so both paths read the same series.
        hist = _pin_hist(
            jnp.where((t0 == 0)[:, None], first_win[:, :1], carry["hist"]))
        series = jnp.concatenate([hist, first_win, newer], axis=1)
        s_len = hist_len + window + t_len - 1
        positions = (t0[:, None] - hist_len
                     + jnp.arange(s_len, dtype=jnp.int32)[None, :])
        q_pos = hist_len + window - 1 + jnp.arange(t_len)  # static indices
        anchor = obs[:, :, window - 1]                     # (T, B)
        feats = _port_feats(obs[:, :, window], obs[:, :, window + 1], anchor)
        port = jnp.zeros((bsz, s_len, 3), jnp.float32)
        port = port.at[:, q_pos, :].set(feats.swapaxes(0, 1))
        logits, values, _kv, _hn, aux = forward(
            params, series, positions, port)
        return (logits[:, q_pos].swapaxes(0, 1),
                values[:, q_pos].swapaxes(0, 1), aux)

    def _head_fold(params):
        """The (3 -> A)/(3 -> 1) folded portfolio-head matrices of the
        factored head (f32): shared by rollout_head_factored AND the
        shared replay so their op order — and thus their bf16 rounding —
        can never diverge. Differentiable (the folds stay in the graph)."""
        # precision-cast-ok (x4): deliberate f32 UPCASTS for the folded
        # head matrices — the fold must not compound bf16 rounding, and an
        # upcast of compute-copy leaves never touches the master contract.
        wp = params["port"]["w"].astype(jnp.float32)      # precision-cast-ok
        bp = params["port"]["b"].astype(jnp.float32)      # precision-cast-ok
        wl = params["policy"]["w"].astype(jnp.float32)    # precision-cast-ok
        wv = params["value"]["w"].astype(jnp.float32)     # precision-cast-ok
        return wp @ wl, bp @ wl, (wp @ wv)[:, 0], (bp @ wv)[0]

    def apply_unroll_shared(params, obs, carry):
        """Training replay with the trunk's factor-B agent redundancy
        removed: every healthy agent's price series is IDENTICAL (the
        lockstep-batch agent-invariance of agents/rollout.py), so the
        banded pass of ``apply_unroll`` runs ONCE for a representative row
        and only the portfolio-feature head runs per agent. Same signature
        and outputs as ``apply_unroll``; gradients are exact (B identical
        trunk paths each pulled back by one agent's head cotangent equal
        one shared path pulled back by their sum).

        The representative must be a live row at EVERY step of the unroll:
        a quarantined agent's stored observation is zero-sanitized (prices
        are strictly positive), and a row quarantined MID-unroll — the
        normal fault timing — has real early steps but a zeroed tail, so
        electing on step 0 alone could pick a row whose tail feeds
        eps-clamped garbage into every healthy agent's trunk. Electing the
        row with the MOST healthy steps (anchor price real) dominates both
        edge cases: a fully-healthy row wins outright (count T), and when
        every row is partially quarantined the longest-healthy row
        corrupts the fewest unmasked steps — an all-steps predicate would
        instead fall back to row 0, which could be a fully-zeroed row.
        Rows whose unroll-start carry is non-finite are excluded outright
        (the rollout election's carry term, agents/base.election_health):
        a NaN carry['hist']/['t'] would poison the ONE shared banded pass
        for every agent. If every carry is poisoned, row 0 wins and the
        non-finite loss escalates to the orchestrator's restore — correct
        when the whole batch is beyond a row-level heal.
        """
        t_len, bsz = obs.shape[0], obs.shape[1]
        counts = jnp.sum(obs[:, :, window - 1] > 0, axis=0)
        carry_ok = rows_finite(carry, bsz)
        rep = jnp.argmax(jnp.where(carry_ok, counts, -1)).astype(jnp.int32)
        obs1 = jax.lax.dynamic_index_in_dim(obs, rep, 1, keepdims=True)
        carry1 = jax.tree.map(
            lambda x: jax.lax.dynamic_index_in_dim(x, rep, 0, keepdims=True),
            carry)
        first_win = obs1[0, :, :window]                 # (1, W)
        newer = obs1[1:, :, window - 1].T               # (1, T-1)
        t0 = carry1["t"].astype(jnp.int32)              # (1,)
        hist = _pin_hist(jnp.where((t0 == 0)[:, None], first_win[:, :1],
                                   carry1["hist"]))
        series = jnp.concatenate([hist, first_win, newer], axis=1)
        s_len = hist_len + window + t_len - 1
        positions = (t0[:, None] - hist_len
                     + jnp.arange(s_len, dtype=jnp.int32)[None, :])
        port = jnp.zeros((1, s_len, 3), jnp.float32)
        _logits, _values, _kv, hn, aux = forward(
            params, series, positions, port)
        q_pos = hist_len + window - 1 + jnp.arange(t_len)
        hn_q = hn[0, q_pos]                             # (T, d)
        # Per-agent head, in the same FACTORED form as the rollout's
        # (rollout_head_factored): base projections over the T shared
        # trunk rows + the 3-wide portfolio term per agent-step. Keeping
        # the op order identical to the rollout head makes stored logp and
        # replayed logp agree to rounding even at bf16 (split forms
        # diverge by ~bf16 eps, which would bias the PPO ratios at epoch
        # 1), and drops the replay's per-agent d-sized head GEMMs.
        base_l = dense(params["policy"], hn_q).astype(jnp.float32)  # (T, A)
        base_v = dense(params["value"], hn_q).astype(jnp.float32)[..., 0]
        w_pl, b_pl, w_pv, b_pv = _head_fold(params)
        anchor = obs[:, :, window - 1]                  # (T, B)
        feats = _port_feats(obs[:, :, window], obs[:, :, window + 1],
                            anchor).astype(jnp.float32)
        logits = base_l[:, None] + feats @ w_pl + b_pl
        values = base_v[:, None] + feats @ w_pv + b_pv
        return logits, values, aux

    def apply_rollout_trunk(params, obs, future_ticks, carry):
        """Whole-unroll trunk in ONE banded pass (the precomputed-rollout
        path, models/core.py): attention sees only price ticks, and prices
        are action-independent, so the trunk for every future step of the
        unroll is computable ahead of the env loop — the same series
        construction as ``apply_unroll``, plus one extra position for the
        bootstrap value. Replaces T sequential cache-attention steps
        (measured 70% of the flagship chunk) with one replay-shaped pass.

        ``future_ticks`` (B, T): the tick that enters the window at each of
        the next T env steps. Returns (hn_base (B, T+1, d), carry after T
        steps — ring-layout K/V refreshed so a later incremental ``apply``
        continues seamlessly).
        """
        bsz, t_len = future_ticks.shape
        t0 = carry["t"].astype(jnp.int32)
        first_win = obs[:, :window]
        # Episode start: substitute the prefill's first-price padding for
        # the init_carry zeros (same rule as apply_unroll).
        hist = _pin_hist(
            jnp.where((t0 == 0)[:, None], first_win[:, :1], carry["hist"]))
        series = jnp.concatenate(
            [hist, first_win, future_ticks.astype(jnp.float32)], axis=1)
        s_len = hist_len + window + t_len
        positions = (t0[:, None] - hist_len
                     + jnp.arange(s_len, dtype=jnp.int32)[None, :])
        port = jnp.zeros((bsz, s_len, 3), jnp.float32)
        _logits, _values, kv, hn, _aux = forward(
            params, series, positions, port, want_kv=True, kv_offset=1)
        q_pos = hist_len + window - 1 + jnp.arange(t_len + 1)
        hn_base = hn[:, q_pos]
        # Carry after T steps. The cached window (kv_offset=1) is ticks
        # [t_end-1, t_end+window-2] in series order; the ring layout stores
        # tick j at slot j mod window, so roll by (t_end-1) mod window.
        t_end = t0 + t_len
        shift = jnp.mod(t_end[0] - 1, window)   # lockstep batch invariant
        cache_k = jnp.roll(jnp.stack([k for k, _ in kv], axis=1),
                           shift, axis=3)
        cache_v = jnp.roll(jnp.stack([v for _, v in kv], axis=1),
                           shift, axis=3)
        hist_next = (series[:, t_len:t_len + hist_len] if hist_len
                     else carry["hist"])
        return hn_base, {"k": cache_k, "v": cache_v,
                         "hist": hist_next, "t": t_end}

    def apply_rollout_head(params, hn_row, obs):
        """The state-dependent remainder of the forward: inject the
        portfolio features and read the policy/value heads — a few
        (B, d)-sized ops per env step."""
        dtype = compute_dtype(params)
        hn = hn_row.astype(dtype) + dense(params["port"], _port_feats(
            obs[:, window], obs[:, window + 1],
            obs[:, window - 1]).astype(dtype))
        logits = dense(params["policy"], hn).astype(jnp.float32)
        values = dense(params["value"], hn).astype(jnp.float32)[..., 0]
        return ModelOut(logits=logits, value=values, aux=jnp.float32(0.0))

    def rollout_head_factored(params, hn_base):
        """The rollout head with its linearity exploited (models/core.py
        field doc): dense(policy, hn + dense(port, feats)) ==
        [dense(policy, hn)] + [feats @ (Wp Wl) + bp Wl]. The first term is
        one (T+1, d) x (d, A) matmul over the whole unroll's precomputed
        trunk; the second is a (3 -> A) contraction per step — removing
        the d-sized per-iteration GEMMs that bound the d=256 flagship
        scan (BASELINE.md round-5 section). Exact up to float
        reassociation; the combined matrices are folded in f32."""
        dtype = compute_dtype(params)
        base_logits = dense(params["policy"],
                            hn_base.astype(dtype)).astype(jnp.float32)
        base_values = dense(params["value"],
                            hn_base.astype(dtype)).astype(jnp.float32)[..., 0]
        w_pl, b_pl, w_pv, b_pv = _head_fold(params)

        def pf_fn(obs):
            feats = _port_feats(obs[:, window], obs[:, window + 1],
                                obs[:, window - 1]).astype(jnp.float32)
            return feats @ w_pl + b_pl, feats @ w_pv + b_pv

        return base_logits, base_values, pf_fn

    def init_carry():
        return {
            "k": jnp.zeros((num_layers, num_heads, window, head_dim), dtype),
            "v": jnp.zeros((num_layers, num_heads, window, head_dim), dtype),
            "hist": jnp.zeros((hist_len,), jnp.float32),
            "t": jnp.int32(0),
        }

    def cast_carry_fn(carry, to_dtype):
        """Precision-policy carry cast (models/core.py Model.cast_carry):
        the K/V cache follows the compute dtype — every forward writes
        rotated keys/values in that dtype, so a mismatched cache is a
        dynamic_update_slice/cond aval error, not a slowdown — while
        ``hist`` stays f32: it holds raw PRICES that prefill/trunk always
        rebuild from f32 observations (casting it would flip the scan
        carry dtype mid-episode AND quantize the tick stream)."""
        out = dict(carry)
        out["k"] = carry["k"].astype(to_dtype)  # precision-cast-ok: policy hook
        out["v"] = carry["v"].astype(to_dtype)  # precision-cast-ok: policy hook
        return out

    return Model(init=init, apply=apply, apply_batch=apply_batch,
                 apply_unroll=apply_unroll, init_carry=init_carry,
                 cast_carry=cast_carry_fn,
                 apply_prefill=_prefill,
                 apply_serve_batch=_incremental_serve,
                 apply_unroll_shared=apply_unroll_shared,
                 apply_rollout_trunk=apply_rollout_trunk,
                 apply_rollout_head=apply_rollout_head,
                 rollout_head_factored=rollout_head_factored,
                 obs_dim=obs_dim, num_actions=num_actions,
                 name="transformer_episode")
