"""The train→serve→train flywheel's client half: served sessions
journal their observed transitions.

Production traffic becomes training data through the SAME data plane
the disaggregated actors use (PR 9's framed journal + PR 12's
feed-driven ingest): a load source (the fleet soak, ``cli fleet``'s
driver, a real client integration) wraps its sessions in
:class:`JournalingSession`, whose every served action lands one
``(obs, action, reward, next_obs)`` row — reward is the session's own
observed portfolio-value change, exactly the env's reward definition
(env/trading.py: ``reward = new_portfolio - current_portfolio``) — in a
:class:`SessionTransitionJournal`: a per-writer CRC-framed, segment-
rotated journal under ``distrib.actor_dir/<writer_id>/``, stamped with
a monotone per-writer row counter recovered from the journal tail at
boot (restarts never reuse a stamp — the ingest-cursor contract).

The learner half already exists: ``Orchestrator.ingest_actor_feeds``
re-discovers the journal set from the filesystem each tick, so a
session journal IS an actor journal as far as the learner is concerned
(``distrib.ingest_without_pool`` opens the gate when no ActorPool runs
in the learner process). The loop closes through the existing weight
path: the learner republishes ``tag_best``, every engine's
``WeightSwapWatcher`` hot-swaps it in, and every response's
``params_step`` names the checkpoint that produced it — the soak's
propagation proof.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from sharetrade_tpu.serve.driver import SessionSim
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.flywheel")

TRANSITIONS_FILE = "transitions.journal"    # the distrib/actor layout


class SessionTransitionJournal:
    """One writer's transitions journal under the learner's ingest root.

    Thread-safe: many session callbacks append concurrently (the wire
    driver completes requests on worker threads); rows buffer in memory
    and commit as one framed record per ``flush_rows`` (the group-commit
    shape ingest reads back whole). Stamps are a monotone cumulative row
    counter per writer, recovered from the journal tail at construction
    — the same contract ``distrib/actor.py`` keeps, so the learner's
    per-writer cursor survives client restarts."""

    def __init__(self, root: str, writer_id: str, *, obs_dim: int,
                 flush_rows: int = 64, segment_records: int = 256,
                 fsync_every_records: int = 64,
                 fsync_interval_s: float = 0.5):
        from sharetrade_tpu.data.journal import Journal
        from sharetrade_tpu.data.transitions import read_tail_transitions
        self.workdir = os.path.join(root, writer_id)
        os.makedirs(self.workdir, exist_ok=True)
        self.path = os.path.join(self.workdir, TRANSITIONS_FILE)
        self._journal = Journal(
            self.path,
            fsync_every_records=fsync_every_records,
            fsync_interval_s=fsync_interval_s,
            segment_records=segment_records)
        self.obs_dim = int(obs_dim)
        self.flush_rows = max(1, int(flush_rows))
        tail = read_tail_transitions(self.path, 1, journal=self._journal)
        self._stamp = int(tail[4]) if tail is not None else 0
        self.rows_journaled = 0
        self._buf: list[tuple] = []
        self._lock = threading.Lock()

    def record(self, obs, action: int, reward: float, next_obs) -> None:
        obs = np.asarray(obs, np.float32)
        next_obs = np.asarray(next_obs, np.float32)
        if obs.shape != (self.obs_dim,) or next_obs.shape != obs.shape:
            # Fail HERE, at the writer, not two processes later when the
            # learner's ingest refuses the whole journal.
            raise ValueError(
                f"transition obs shape {obs.shape}/{next_obs.shape} != "
                f"the journal's obs_dim ({self.obs_dim},) — is the "
                "session's window the learner's env window?")
        with self._lock:
            self._buf.append((obs, int(action), float(reward), next_obs))
            if len(self._buf) >= self.flush_rows:
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()
            self._journal.flush()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        from sharetrade_tpu.data.transitions import append_transitions
        rows = self._buf
        self._buf = []
        obs = np.stack([r[0] for r in rows])
        action = np.asarray([r[1] for r in rows], np.int32)
        reward = np.asarray([r[2] for r in rows], np.float32)
        next_obs = np.stack([r[3] for r in rows])
        self._stamp += len(rows)
        append_transitions(self._journal, obs, action, reward, next_obs,
                           env_steps=self._stamp)
        self.rows_journaled += len(rows)

    def close(self) -> None:
        self.flush()
        self._journal.close()


class JournalingSession(SessionSim):
    """A served session that journals what it observes: each
    :meth:`advance` computes the portfolio-value reward of the action it
    was served, captures the before/after observations, and records the
    transition. Obs shape matches the learner env exactly (window prices
    + [budget, shares]) — the ingest path refuses mismatched dims
    loudly, so a misconfigured fleet cannot silently poison replay."""

    def __init__(self, *args, journal: SessionTransitionJournal
                 | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.journal = journal

    def advance(self, action: int) -> None:
        if self.journal is None:
            super().advance(action)
            return
        obs_t = self.observation()
        price = float(self.prices[self.start + self.t + self.window])
        value_before = self.budget + self.shares * price
        gen = self.generation
        super().advance(action)
        if self.generation != gen:
            # Episode wrapped: the fresh episode's first observation is
            # not this transition's successor — skip the boundary row
            # (the integrated trainer's journal has no done flag either;
            # at the serving tier's gamma the bootstrap cost is nil, and
            # a wrong-successor row is worse than a missing one).
            return
        price_next = float(
            self.prices[self.start + self.t + self.window])
        value_after = self.budget + self.shares * price_next
        self.journal.record(obs_t, action, value_after - value_before,
                            self.observation())


def make_journaling_sessions(prices, window: int, n: int, *,
                             journal: SessionTransitionJournal,
                             seed: int = 0,
                             prefix: str = "fs") -> list[JournalingSession]:
    """``n`` journaling sessions with staggered starts (the
    ``make_sessions`` shape, flywheel-wired)."""
    prices = np.asarray(prices, np.float32)
    horizon = len(prices) - window - 1
    if horizon < 1:
        raise ValueError(f"price series too short for window={window}")
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, max(horizon - 1, 1), size=n)
    return [JournalingSession(f"{prefix}{i}", prices, window, starts[i],
                              journal=journal)
            for i in range(n)]
