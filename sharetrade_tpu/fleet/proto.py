"""Sans-IO HTTP/1.1 protocol core for the fleet wire — bytes in,
events out, ZERO I/O.

This module is the ONE definition of the fleet's HTTP/1.1 framing,
shared by every party on the wire: the blocking :class:`~sharetrade_tpu.
fleet.wire.FleetClient`, the threaded front-end, and the evloop
connection engine (fleet/evloop.py) all feed raw socket bytes into the
same parsers and render replies through the same builders. It never
touches a socket, a file, a thread, or a clock — a parser is a pure
state machine, so every framing rule (torn reads at ANY byte boundary,
pipelined requests, the Content-Length contract, header-size limits)
is testable byte-by-byte without a network (tests/test_fleet_wire.py
replays the whole wire corpus split at every offset).

Framing rules (the fleet dialect, deliberately smaller than RFC 9112):

- Requests and responses are framed by ``Content-Length`` only — no
  chunked transfer, no multipart. A request without the header has an
  empty body (GETs); a RESPONSE without it is a protocol error, because
  on a keep-alive connection "read until close" framing is indistinct
  from a torn response (the lesson fleet/wire.py's hand parse encoded,
  now encoded once here).
- A header block larger than :data:`MAX_HEAD_BYTES` or a body larger
  than :data:`MAX_BODY_BYTES` is refused before buffering unboundedly.
- ``feed()`` may be handed ANY split of the byte stream — one byte at a
  time, a half request, three pipelined requests in one chunk — and
  returns the complete messages in arrival order; partial tail bytes
  stay buffered for the next feed.

The "body consumed before early reply" keep-alive lesson is structural
here: a parser only emits a :class:`Request` once its full body has
arrived, so a server replying 404/503 early can never leave body bytes
behind to poison the next request on the connection.
"""

from __future__ import annotations

#: Refuse a request/status line + header block larger than this — a
#: peer streaming an unbounded head is attacking the buffer, not
#: speaking the fleet protocol.
MAX_HEAD_BYTES = 16384

#: Refuse a declared body larger than this (submit bodies are a few KB;
#: the largest legitimate payload on the wire is a /metrics scrape).
MAX_BODY_BYTES = 1 << 26

_CRLF2 = b"\r\n\r\n"

#: Distributed-tracing context headers (ISSUE 17) — rendered and parsed
#: through THIS module only, so both wire backends and the client carry
#: them identically. ``X-Trace-Id`` names the request's whole journey;
#: ``X-Parent-Span`` is the SENDING hop's span id, which the receiving
#: hop parents its own spans under. Replies NEVER echo them (spans are
#: journaled, not returned), which is what keeps the two backends'
#: reply streams byte-identical with tracing on or off.
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Parent-Span"

#: Characters a trace/span id may contain (hex ids, pid-prefixed
#: counter ids like ``1a2f.3c``). Anything else on the wire is ignored
#: rather than propagated — a hop must never relay an id it could not
#: have minted.
_ID_CHARS = frozenset("0123456789abcdefABCDEF.-")
_ID_MAX = 64


def _valid_id(value: str) -> bool:
    return 0 < len(value) <= _ID_MAX and not set(value) - _ID_CHARS


def trace_context(headers: dict) -> tuple[str, str] | None:
    """The inbound trace context of a PARSED message's header dict:
    ``(trace_id, parent_span)`` — or None when absent/malformed (a bad
    id is dropped, never relayed). ``parent_span`` may be ``""`` (a
    trace id minted by a hop with no span of its own)."""
    trace_id = headers.get("x-trace-id")
    if not trace_id or not _valid_id(trace_id):
        return None
    parent = headers.get("x-parent-span", "")
    if parent and not _valid_id(parent):
        parent = ""
    return trace_id, parent

#: Reason phrases for the statuses the fleet actually speaks (see the
#: wire.py status table) — anything else renders its bare code.
REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}


class ProtocolError(Exception):
    """A framing violation. ``status`` is what a SERVER should answer
    (400 for everything a client can cause); a CLIENT treats any
    ProtocolError from a ResponseParser as transport-class — the
    keep-alive byte stream is unrecoverable either way."""

    def __init__(self, detail: str, *, status: int = 400):
        super().__init__(detail)
        self.status = int(status)
        self.detail = detail


class Request:
    """One complete parsed request: ``headers`` is a last-wins dict of
    lower-cased names; ``keep_alive`` already folds the HTTP-version /
    Connection-header rules."""

    __slots__ = ("method", "target", "headers", "body", "keep_alive")

    def __init__(self, method: str, target: str, headers: dict,
                 body: bytes, keep_alive: bool):
        self.method = method
        self.target = target
        self.headers = headers
        self.body = body
        self.keep_alive = keep_alive

    def __repr__(self) -> str:
        return (f"Request({self.method} {self.target}, "
                f"{len(self.body)}B, keep_alive={self.keep_alive})")


class Response:
    """One complete parsed response."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: dict, body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def __repr__(self) -> str:
        return f"Response({self.status}, {len(self.body)}B)"


def content_length(value) -> int:
    """THE Content-Length validation — the parsers and the threaded
    front-end's body read both go through here, so 'what counts as a
    well-formed length' has exactly one definition."""
    if value is None:
        return 0
    try:
        n = int(str(value).strip())
    except ValueError:
        raise ProtocolError(f"malformed Content-Length {value!r}") \
            from None
    if n < 0:
        raise ProtocolError(f"negative Content-Length {value!r}")
    if n > MAX_BODY_BYTES:
        raise ProtocolError(
            f"declared body of {n} bytes exceeds the "
            f"{MAX_BODY_BYTES}-byte limit")
    return n


def _parse_headers(lines: list[bytes]) -> dict:
    headers: dict[str, str] = {}
    for line in lines:
        name, sep, value = line.partition(b":")
        if not sep or not name.strip():
            raise ProtocolError(f"malformed header line {line!r}")
        headers[name.strip().decode("latin-1").lower()] = \
            value.strip().decode("latin-1")
    return headers


class _Parser:
    """Shared incremental framing: buffer → head block → exactly
    Content-Length body bytes → one event; repeat (pipelining)."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._head = None           # parsed head awaiting its body
        self._need = 0              # body bytes still owed

    def pending_bytes(self) -> bool:
        """True if the parser holds buffered bytes of an incomplete (or
        not-yet-consumed) message — a reused connection handing these
        back to a pool must NOT, the stream is mid-message."""
        return bool(self._buf) or self._head is not None

    def feed(self, data: bytes) -> list:
        """Feed any slice of the byte stream; returns every message
        COMPLETED by it, in order. Raises :class:`ProtocolError` on a
        framing violation (the connection is then unrecoverable)."""
        self._buf += data
        out = []
        while True:
            event = self._next()
            if event is None:
                return out
            out.append(event)

    def _next(self):
        if self._head is None:
            idx = self._buf.find(_CRLF2)
            if idx < 0:
                if len(self._buf) > MAX_HEAD_BYTES:
                    raise ProtocolError(
                        f"header block exceeds {MAX_HEAD_BYTES} bytes")
                return None
            if idx > MAX_HEAD_BYTES:
                raise ProtocolError(
                    f"header block exceeds {MAX_HEAD_BYTES} bytes")
            head = bytes(self._buf[:idx])
            del self._buf[:idx + 4]
            self._head, self._need = self._parse_head(head)
        if len(self._buf) < self._need:
            return None
        body = bytes(self._buf[:self._need])
        del self._buf[:self._need]
        head, self._head = self._head, None
        return self._finish(head, body)

    # subclass surface ------------------------------------------------

    def _parse_head(self, head: bytes):
        raise NotImplementedError

    def _finish(self, head, body: bytes):
        raise NotImplementedError


class PyRequestParser(_Parser):
    """Server side: bytes from a client connection → :class:`Request`
    events. (Pure-Python rung; :data:`RequestParser` below points at
    whichever backend is live.)"""

    def _parse_head(self, head: bytes):
        lines = head.split(b"\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            raise ProtocolError(f"malformed request line {lines[0]!r}")
        method, target, version = parts
        if not version.startswith(b"HTTP/1."):
            raise ProtocolError(f"unsupported version {version!r}")
        headers = _parse_headers(lines[1:])
        connection = headers.get("connection", "").lower()
        if version == b"HTTP/1.0":
            keep_alive = connection == "keep-alive"
        else:
            keep_alive = connection != "close"
        meta = (method.decode("latin-1"), target.decode("latin-1"),
                headers, keep_alive)
        return meta, content_length(headers.get("content-length"))

    def _finish(self, head, body: bytes) -> Request:
        method, target, headers, keep_alive = head
        return Request(method, target, headers, body, keep_alive)


class PyResponseParser(_Parser):
    """Client side: bytes from a server connection → :class:`Response`
    events. A response MUST carry Content-Length (module docstring)."""

    def _parse_head(self, head: bytes):
        lines = head.split(b"\r\n")
        parts = lines[0].split(None, 2)
        if len(parts) < 2 or not parts[0].startswith(b"HTTP/1."):
            raise ProtocolError(f"malformed status line {lines[0]!r}")
        try:
            status = int(parts[1])
        except ValueError:
            raise ProtocolError(
                f"malformed status line {lines[0]!r}") from None
        headers = _parse_headers(lines[1:])
        if "content-length" not in headers:
            raise ProtocolError(
                "response without Content-Length on a keep-alive "
                "connection")
        return (status, headers), content_length(headers["content-length"])

    def _finish(self, head, body: bytes) -> Response:
        status, headers = head
        return Response(status, headers, body)


# ---- rendering ------------------------------------------------------


def py_render_request(method: str, target: str, host: str,
                      body: bytes = b"",
                      headers: dict | None = None) -> bytes:
    """Build one request's wire bytes — the exact frame FleetClient has
    always sent (Host + Content-Length + extras, one buffer, ready for
    a single send)."""
    head = [f"{method} {target} HTTP/1.1",
            f"Host: {host}",
            f"Content-Length: {len(body)}"]
    for k, v in (headers or {}).items():
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def py_render_response(status: int, body: bytes,
                       content_type: str = "application/json", *,
                       keep_alive: bool = True,
                       extra_headers: dict | None = None) -> bytes:
    """Build one response's wire bytes. Both wire backends (threaded
    and evloop) render through here, which is what makes their reply
    streams byte-identical — the differential test's precondition."""
    head = [f"HTTP/1.1 {status} {REASONS.get(status, 'Unknown')}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}"]
    if not keep_alive:
        head.append("Connection: close")
    for k, v in (extra_headers or {}).items():
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


# ---- backend dispatch (ISSUE 19) ------------------------------------
#
# The HTTP/1.1 state machines above exist twice: here in Python (the
# differential oracle) and in native/wire.cc (the hot path, a CPython
# extension that releases the GIL around parse/render). Everything on
# the wire — the evloop, the threaded front-end, FleetClient — reaches
# the parsers and renderers through THESE module globals, so swapping
# them swaps the backend for the whole fleet without any caller
# changing. This module is the ONLY place the extension is loaded
# (lint_hot_loop check 18 enforces the confinement), and the events
# and exceptions the native parsers produce are these very classes
# (stwire.configure hands them over), so `isinstance(ev, Request)` and
# `except ProtocolError` are backend-blind.
#
# Contract: set_backend("native") on a host without the built
# extension degrades to "py" with ONE loud log line per process and
# never raises — a missing build is a mode, not an error.

#: The stwire extension module when loaded, else None.
_NATIVE = None
#: Why the native load failed (the loud fallback line names it).
_NATIVE_ERROR = ""
_FALLBACK_LOGGED = False

#: The backend that is LIVE right now: "native" or "py".
proto_backend = "py"


def _load_native_wire():
    """Load ``native/stwire.so`` (built by ``make -C native``) as a
    CPython extension module and hand it this module's event and
    exception classes. Returns None — recording the reason — rather
    than raising: callers decide loudness via :func:`set_backend`."""
    global _NATIVE_ERROR
    import os

    if os.environ.get("SHARETRADE_WIRE_NATIVE", "1") == "0":
        _NATIVE_ERROR = "disabled by SHARETRADE_WIRE_NATIVE=0"
        return None
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native", "stwire.so")
    if not os.path.exists(path):
        _NATIVE_ERROR = "stwire.so not built (run: make -C native)"
        return None
    try:
        from importlib.machinery import ExtensionFileLoader  # native-wire-ok
        from importlib.util import module_from_spec, spec_from_file_location

        loader = ExtensionFileLoader("stwire", path)
        spec = spec_from_file_location("stwire", path, loader=loader)
        mod = module_from_spec(spec)
        loader.exec_module(mod)
        mod.configure(Request, Response, ProtocolError)
    except Exception as exc:  # stale ABI, bad build, ...
        _NATIVE_ERROR = f"{type(exc).__name__}: {exc}"
        return None
    return mod


def native_available() -> bool:
    """True when the native wire extension loaded (and "native" would
    really mean native, not the logged fallback)."""
    return _NATIVE is not None


def native_load_error() -> str:
    """Why :func:`native_available` is False ("" when it is True)."""
    return "" if _NATIVE is not None else _NATIVE_ERROR


def set_backend(name: str) -> str:
    """Point the module-global parse/render surface at ``name``
    ("native" or "py") and return what actually went live — "native"
    degrades to "py" (one loud log line per process) when the
    extension is missing or failed to load."""
    global proto_backend, RequestParser, ResponseParser
    global render_request, render_response, _FALLBACK_LOGGED
    if name not in ("native", "py"):
        raise ValueError(
            f"unknown fleet.proto_backend {name!r} "
            "(expected 'native' or 'py')")
    if name == "native" and _NATIVE is None:
        if not _FALLBACK_LOGGED:
            import logging

            logging.getLogger("sharetrade.fleet.proto").warning(
                "native wire backend unavailable (%s) — falling back "
                "to the Python parser", _NATIVE_ERROR)
            _FALLBACK_LOGGED = True
        name = "py"
    if name == "native":
        RequestParser = _NATIVE.RequestParser
        ResponseParser = _NATIVE.ResponseParser
        render_request = _NATIVE.render_request
        render_response = _NATIVE.render_response
    else:
        RequestParser = PyRequestParser
        ResponseParser = PyResponseParser
        render_request = py_render_request
        render_response = py_render_response
    proto_backend = name
    return name


_NATIVE = _load_native_wire()

#: Live parse/render surface — every wire party uses these names.
RequestParser = PyRequestParser
ResponseParser = PyResponseParser
render_request = py_render_request
render_response = py_render_response

# Native is the default rung whenever the extension imports; the
# silent-at-import case (unbuilt checkout) stays on "py" without the
# loud line — the line belongs to an EXPLICIT "native" request, which
# cli.py issues when fleet.proto_backend says so.
if _NATIVE is not None:
    set_backend("native")
