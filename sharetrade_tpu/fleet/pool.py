""":class:`EnginePool` — whole serve-engine WORKER PROCESSES under the
shared supervision ladder.

Each member is one ``cli serve --listen`` subprocess: a full PR-10
overload-safe :class:`~sharetrade_tpu.serve.engine.ServeEngine` (its own
slot-pool arena, admission control, swap watcher) behind its own
network front-end (fleet/frontend.py) on an EPHEMERAL port the worker
reports in a machine-readable ``engine_listening`` line. The pool is the
ActorPool pattern (distrib/pool.py) at ENGINE granularity, with the
ladder itself — crash classification, seeded exponential backoff,
consecutive-streak terminal failure — factored into distrib/ladder.py
and shared verbatim between the two:

- **spawn/reap**: classify every exit; quiesced/retiring exits retire
  quietly, anything else crashes into the ladder;
- **bring-up watch**: a worker that never prints its listening line
  within ``fleet.startup_timeout_s`` is presumed wedged during startup
  and killed (a crash — bring-up hangs must not escape the contract, the
  PR-12 lesson);
- **HTTP heartbeats**: each supervise tick polls every listening
  member's ``/healthz``; a member silent past
  ``fleet.health_timeout_s`` is killed (crash → ladder). The health
  snapshot (queue depth, params_step, swap counters) rides into
  ``status`` — the router's membership view and the soak's
  reconciliation source;
- **terminal degrade**: a streak past ``fleet.max_engine_restarts``
  marks the engine FAILED and the fleet degrades onto survivors; the
  router answers 503 loudly when none remain;
- **CPU slices** (``fleet.engine_cpus``): each worker is pinned to its
  own core slice via ``sched_setaffinity`` at spawn — the one-host
  stand-in for one-engine-per-machine that makes the scale-out bench
  honest.

A healthy engine that gets SIGKILLed respawns FRESH: empty slot pool,
empty warm store. What SURVIVES the corpse is the shared spill arena
(ISSUE 20): when ``serve.spill_bytes`` is configured the pool hands
every worker the same ``<dir>/spill`` directory, so carries the dead
engine parked/spilled there are ADOPTED warm by whichever engine the
router re-routes each session to — iff the record's step stamp matches
the router's session clock; anything stale, torn, or CRC-bad re-enters
COLD through the batched prefill, bitwise-equal to a fresh session (the
PR-8 eviction contract the fleet tests re-pin over the wire). The pool
also sweeps dead incarnations' unsealed ``.tmp`` debris out of the
arena: at boot (nothing is running — all debris is dead) and on every
crash reap (the corpse's pid-stamped leftovers).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.distrib.ladder import (
    ALIVE,
    BACKOFF,
    FAILED,
    LIVE_STATES,
    RETIRED,
    RETIRING,
    STARTING,
    LadderPolicy,
    crash_step,
)
from sharetrade_tpu.fleet.wire import FleetClient
from sharetrade_tpu.serve.spill import sweep_debris
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.pool")

ENGINE_CONFIG_FILE = "engine_config.json"

#: The worker's machine-readable readiness line (``cli serve --listen``
#: prints it once the front-end is bound): the pool tails each worker's
#: log file for it to learn the ephemeral port.
LISTENING_EVENT = "engine_listening"


@dataclass
class _EngineHandle:
    engine_id: str
    proc: subprocess.Popen | None = None
    state: str = STARTING
    restarts: int = 0
    streak: int = 0
    spawned_at: float = 0.0
    respawn_at: float = 0.0
    last_rc: int | None = None
    port: int | None = None
    #: monotonic stamp of the last successful /healthz (or of the
    #: listening line, which proves the same liveness).
    last_ok: float = 0.0
    health: dict = field(default_factory=dict)
    log_path: str = ""
    _log_offset: int = 0
    cpus: tuple[int, ...] = ()

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None


class EnginePool:
    """Supervisor for ``cli serve --listen`` workers (module docstring).

    ``spawn_fn(engine_id, log_path) -> Popen`` substitutes the spawn for
    tests (the ActorPool stub pattern): the stub child owns writing its
    own ``engine_listening`` line into ``log_path``."""

    def __init__(self, cfg: FrameworkConfig, *, workdir: str | None = None,
                 registry: Any = None, symbol: str = "MSFT",
                 start: str | None = None, end: str | None = None,
                 spawn_fn: Callable[[str, str], subprocess.Popen]
                 | None = None):
        fc = cfg.fleet
        LadderPolicy(
            max_restarts=fc.max_engine_restarts,
            backoff_initial_s=fc.engine_backoff_initial_s,
            backoff_max_s=fc.engine_backoff_max_s,
            backoff_jitter=fc.engine_backoff_jitter,
        ).validate(section="fleet.max_engine_restarts / engine_backoff_*")
        if fc.num_engines < 1:
            raise ConfigError(
                f"fleet.num_engines must be >= 1, got {fc.num_engines}")
        self.cfg = cfg
        self.dir = workdir or fc.dir
        os.makedirs(self.dir, exist_ok=True)
        self.registry = registry
        self._symbol, self._start, self._end = symbol, start, end
        self._spawn_fn = spawn_fn
        import random
        self._rng = random.Random(cfg.seed ^ 0xF1EE7)
        self._policy = LadderPolicy(
            max_restarts=fc.max_engine_restarts,
            backoff_initial_s=fc.engine_backoff_initial_s,
            backoff_max_s=fc.engine_backoff_max_s,
            backoff_jitter=fc.engine_backoff_jitter)
        self._engines: dict[str, _EngineHandle] = {}
        self._next_index = 0
        self.target = 0
        self.scale_events = 0
        self.restarts_total = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._quiesced = threading.Event()
        self._thread: threading.Thread | None = None
        self._config_path: str | None = None
        self.started_at = time.time()
        #: Host core inventory for fleet.engine_cpus slices (stable
        #: round-robin assignment by spawn index).
        self._host_cpus = sorted(os.sched_getaffinity(0))
        #: The fleet-shared spill arena directory (ISSUE 20), or None
        #: with the spill tier off. An explicit serve.spill_dir wins;
        #: otherwise spill_bytes > 0 (and a live warm tier — the engine
        #: refuses spill-without-warm) roots the arena under the pool's
        #: own dir so every worker — and every respawn — shares it.
        sc = cfg.serve
        self.arena_dir: str | None = None
        if sc.spill_dir:
            self.arena_dir = sc.spill_dir
        elif sc.spill_bytes > 0 and sc.warm_bytes > 0:
            self.arena_dir = os.path.join(self.dir, "spill")

    # ---- membership -------------------------------------------------

    def start(self, n: int | None = None) -> "EnginePool":
        n = self.cfg.fleet.num_engines if n is None else n
        if self.arena_dir is not None:
            # Nothing is running yet, so EVERY unsealed temp file in the
            # arena is a dead incarnation's torn write — sealed records
            # are untouched (they are the previous fleet's adoptable
            # carries, exactly what the spill tier exists to preserve).
            swept = sweep_debris(self.arena_dir)
            if swept:
                log.info("swept %d stale spill temp file(s) from %s",
                         swept, self.arena_dir)
        with self._lock:
            self.target = n
            for _ in range(n):
                self._spawn_new_locked()
        self._thread = threading.Thread(target=self._supervise,
                                        name="engine-pool", daemon=True)
        self._thread.start()
        return self

    def _spawn_new_locked(self) -> _EngineHandle:
        engine_id = f"e{self._next_index}"
        idx = self._next_index
        self._next_index += 1
        handle = _EngineHandle(engine_id=engine_id)
        handle.cpus = self._cpu_slice(idx)
        self._engines[engine_id] = handle
        self._spawn_locked(handle)
        return handle

    def _cpu_slice(self, idx: int) -> tuple[int, ...]:
        k = self.cfg.fleet.engine_cpus
        if k <= 0 or not self._host_cpus:
            return ()
        n = len(self._host_cpus)
        lo = (idx * k) % n
        return tuple(self._host_cpus[(lo + j) % n] for j in range(min(k, n)))

    def _spawn_locked(self, handle: _EngineHandle) -> None:
        handle.log_path = os.path.join(self.dir,
                                       f"{handle.engine_id}.log")
        # The log appends across incarnations (crash forensics stay on
        # disk): anchor the listening-line scan at the CURRENT size so a
        # respawn can never re-read its predecessor's port line.
        try:
            handle._log_offset = os.path.getsize(handle.log_path)
        except OSError:
            handle._log_offset = 0
        if self._spawn_fn is not None:
            handle.proc = self._spawn_fn(handle.engine_id,
                                         handle.log_path)
        else:
            if self._config_path is None:
                self._config_path = os.path.join(self.dir,
                                                 ENGINE_CONFIG_FILE)
                worker_cfg = FrameworkConfig.from_dict(self.cfg.to_dict())
                # Telemetry stays with the fleet process: N workers
                # writing one obs run dir would fight over the manifest/
                # exporter files; engine telemetry is scraped over
                # /metrics instead (the router's poller).
                worker_cfg.obs.enabled = False
                worker_cfg.save(self._config_path)
            cmd = [sys.executable, "-m", "sharetrade_tpu.cli", "serve",
                   "--config", self._config_path,
                   "--listen", f"{self.cfg.fleet.host}:0",
                   "--duration", "0",
                   # Each worker's price-data layer scopes to its OWN
                   # dir: sharing journal_dir would contend for the
                   # price-event journal's flock'd writer lock (the
                   # PR-12 actor lesson, verbatim).
                   "--set",
                   "data.journal_dir="
                   + os.path.join(self.dir, f"{handle.engine_id}-data"),
                   "--symbol", self._symbol]
            if self.arena_dir is not None:
                # Every worker shares ONE arena (and a respawn rejoins
                # it): the handoff half of warm-carry migration.
                cmd += ["--set", f"serve.spill_dir={self.arena_dir}"]
            span_dir = getattr(self.cfg.obs, "span_dir", "")
            if span_dir:
                # ISSUE-17 span journaling: each worker appends wire
                # spans to its OWN journal in the fleet's shared spans
                # dir, keyed by engine id (no writer contention — one
                # file per process). The workers run obs.enabled=false;
                # the span journal is the one obs artifact deliberately
                # shared, switched by span_dir alone (obs/__init__.py).
                cmd += ["--set", f"obs.span_dir={span_dir}",
                        "--set", f"obs.span_proc=engine-{handle.engine_id}"]
            if self._start:
                cmd += ["--start", self._start]
            if self._end:
                cmd += ["--end", self._end]
            # Child output to a FILE, never a pipe (the crash-soak
            # lesson: an undrained pipe wedges the child at ~64 KB).
            log_f = open(handle.log_path, "ab")
            preexec = None
            if handle.cpus:
                cpus = handle.cpus
                # Pin the worker (and every XLA thread it spawns) to its
                # slice; runs in the child between fork and exec.
                preexec = lambda: os.sched_setaffinity(0, cpus)  # noqa: E731
            try:
                # actor-spawn-ok: EnginePool IS this child's supervisor
                # (reap/backoff/terminal ladder below — the distrib/pool
                # contract at engine granularity).
                handle.proc = subprocess.Popen(  # actor-spawn-ok: see above
                    cmd, stdout=log_f, stderr=subprocess.STDOUT,
                    preexec_fn=preexec)
            finally:
                log_f.close()
        handle.state = STARTING
        handle.spawned_at = time.monotonic()
        handle.respawn_at = 0.0
        handle.port = None
        handle.last_ok = 0.0
        handle.health = {}
        log.info("engine %s spawned (pid %s, cpus %s)", handle.engine_id,
                 handle.pid, handle.cpus or "unpinned")

    # ---- supervision ------------------------------------------------

    def _supervise(self) -> None:
        interval = max(self.cfg.fleet.supervise_interval_s, 0.05)
        while not self._stop.wait(interval):
            try:
                self.poll_once()
            except Exception:   # noqa: BLE001 — the supervisor outlives
                log.exception("engine-pool supervise tick failed")

    def poll_once(self) -> None:
        """One supervise tick (public: tests and the soak step the pool
        deterministically): reap exits, scan for listening lines, poll
        heartbeats, enforce timeouts, respawn due backoffs, publish
        status + gauges."""
        with self._lock:
            self._reap()
            self._scan_listening()
        # Health polls go over HTTP — off the lock, so a slow peer never
        # blocks membership bookkeeping; results commit under it.
        self._poll_health()
        with self._lock:
            self._enforce_timeouts()
            self._respawn_due()
            self._write_status_locked()
            self._export_gauges()

    def quiesce(self) -> None:
        """Stop respawning: the fleet is draining — engines exiting from
        here on retire instead of crashing."""
        self._quiesced.set()

    def scale(self, n: int) -> None:
        """Retarget LIVE membership to ``n`` engines (the autoscaler's —
        and the operator's — actuator; the ActorPool.scale contract at
        engine granularity). Growing spawns fresh workers; shrinking
        retires the NEWEST live engines first (highest numeric id — the
        longest-lived members keep their warm slot pools and session
        affinity), each through the SIGTERM drain → exit-75 contract so
        in-flight requests finish and its sessions migrate cold. Refused
        while draining: a quiesced pool must not spawn."""
        if self._quiesced.is_set():
            log.warning("scale(%d) refused: pool is quiesced/draining", n)
            return
        with self._lock:
            if n < 0:
                raise ConfigError(f"scale target must be >= 0, got {n}")
            self.target = n
            self.scale_events += 1
            live = [h for h in self._engines.values()
                    if h.state in (STARTING, ALIVE, BACKOFF)]
            if len(live) < n:
                for _ in range(n - len(live)):
                    self._spawn_new_locked()
            elif len(live) > n:
                victims = sorted(
                    live, key=lambda h: int(h.engine_id[1:]),
                    reverse=True)[:len(live) - n]
                for h in victims:
                    self._retire_locked(h)
            self._write_status_locked()
            log.info("fleet scaled to target=%d (%s)", n,
                     {h.engine_id: h.state
                      for h in self._engines.values()})

    def _retire_locked(self, h: _EngineHandle) -> None:
        """Retire one engine: a live process drains via SIGTERM (its own
        drain → exit 75 contract; the next reap classifies the exit as a
        RETIRING retirement, never a crash); a dead/backing-off handle
        just retires in place."""
        if h.proc is not None and h.proc.poll() is None:
            h.state = RETIRING
            try:
                h.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        else:
            h.state = RETIRED

    def _reap(self) -> None:
        for h in self._engines.values():
            if h.proc is None or h.state in (FAILED, RETIRED, BACKOFF):
                continue
            rc = h.proc.poll()
            if rc is None:
                continue
            h.last_rc = rc
            h.port = None
            if self.arena_dir is not None and h.pid is not None:
                # The corpse can never finish a write: its pid-stamped
                # unsealed temp files are debris now (sealed records
                # stay — they are the adoption inventory).
                sweep_debris(self.arena_dir, pid=h.pid)
            if h.state == RETIRING or self._quiesced.is_set():
                h.state = RETIRED
                log.info("engine %s retired (rc=%s)", h.engine_id, rc)
                continue
            h.streak += 1
            h.restarts += 1
            self.restarts_total += 1
            if self.registry is not None:
                self.registry.inc("engine_restarts_total")
            state, delay = crash_step(h.streak, self._policy, self._rng)
            h.state = state
            if state == FAILED:
                log.error(
                    "engine %s FAILED terminally: %d consecutive crashes "
                    "past fleet.max_engine_restarts=%d (last rc=%s); "
                    "fleet degrades onto the survivors",
                    h.engine_id, h.streak,
                    self._policy.max_restarts, rc)
                continue
            h.respawn_at = time.monotonic() + delay
            log.warning("engine %s crashed (rc=%s); restart %d "
                        "(streak %d/%d) in %.2fs", h.engine_id, rc,
                        h.restarts, h.streak, self._policy.max_restarts,
                        delay)

    def _scan_listening(self) -> None:
        """Tail each STARTING worker's log for its ``engine_listening``
        line (incremental byte offsets — no re-reads)."""
        for h in self._engines.values():
            if h.state != STARTING or h.port is not None:
                continue
            try:
                with open(h.log_path, "rb") as f:
                    f.seek(h._log_offset)
                    chunk = f.read()
            except OSError:
                continue
            if not chunk:
                continue
            # Only complete lines advance the offset (a worker caught
            # mid-print re-scans the partial next tick).
            head, sep, _ = chunk.rpartition(b"\n")
            if not sep:
                continue
            h._log_offset += len(head) + 1
            for line in head.splitlines():
                if LISTENING_EVENT.encode() not in line:
                    continue
                try:
                    ev = json.loads(line.decode("utf-8", "replace"))
                except ValueError:
                    continue
                if ev.get("event") == LISTENING_EVENT:
                    h.port = int(ev["port"])
                    h.last_ok = time.monotonic()
                    log.info("engine %s listening on port %d",
                             h.engine_id, h.port)

    def _poll_health(self) -> None:
        with self._lock:
            targets = [(h.engine_id, h.port) for h in
                       self._engines.values()
                       if h.state in (STARTING, ALIVE)
                       and h.port is not None]
        results: dict[str, dict | None] = {}
        for engine_id, port in targets:
            client = FleetClient(self.cfg.fleet.host, port,
                                 timeout_s=self.cfg.fleet.scrape_timeout_s)
            try:
                results[engine_id] = client.health()
            except Exception:   # noqa: BLE001 — an unreachable member is
                results[engine_id] = None   # a health datum, not a fault
            finally:
                client.close()
        now = time.monotonic()
        with self._lock:
            for engine_id, health in results.items():
                h = self._engines.get(engine_id)
                if h is None or h.state not in (STARTING, ALIVE):
                    continue
                if health is not None:
                    h.health = health
                    h.last_ok = now
                    if h.state == STARTING:
                        h.state = ALIVE
                        # A respawn that answers healthz proved itself:
                        # the crash streak resets (the heartbeat-reaches-
                        # rolling rule at engine granularity).
                        h.streak = 0

    def _enforce_timeouts(self) -> None:
        fc = self.cfg.fleet
        now = time.monotonic()
        for h in self._engines.values():
            if h.proc is None or h.proc.poll() is not None:
                continue
            if h.state == STARTING and h.port is None:
                if (fc.startup_timeout_s > 0
                        and now - h.spawned_at > fc.startup_timeout_s):
                    log.error("engine %s never reported listening within "
                              "%.0fs; killing the presumed-wedged "
                              "bring-up", h.engine_id,
                              fc.startup_timeout_s)
                    self._kill_handle(h)
            elif h.state in (STARTING, ALIVE) and h.port is not None:
                if (fc.health_timeout_s > 0 and h.last_ok
                        and now - h.last_ok > fc.health_timeout_s):
                    log.error("engine %s healthz silent %.1fs > %.1fs; "
                              "killing the presumed-wedged process",
                              h.engine_id, now - h.last_ok,
                              fc.health_timeout_s)
                    self._kill_handle(h)

    @staticmethod
    def _kill_handle(h: _EngineHandle) -> None:
        try:
            h.proc.kill()       # the next _reap classifies the crash
        except ProcessLookupError:
            pass

    def _respawn_due(self) -> None:
        if self._quiesced.is_set():
            return
        now = time.monotonic()
        for h in self._engines.values():
            if h.state == BACKOFF and now >= h.respawn_at:
                self._spawn_locked(h)

    # ---- the router's view ------------------------------------------

    def endpoints(self) -> dict[str, tuple[str, int]]:
        """``{engine_id: (host, port)}`` of every member that has
        reported a listening port and is not dead/failed — the router's
        candidate set (the router confirms liveness with its own
        scrapes)."""
        host = self.cfg.fleet.host
        with self._lock:
            return {h.engine_id: (host, h.port)
                    for h in self._engines.values()
                    if h.port is not None
                    and h.state in (STARTING, ALIVE, RETIRING)}

    def counts(self) -> dict[str, int]:
        with self._lock:
            states = [h.state for h in self._engines.values()]
        return {
            "alive": sum(s in (STARTING, ALIVE, RETIRING) for s in states),
            "backoff": sum(s == BACKOFF for s in states),
            "failed": sum(s == FAILED for s in states),
            "retired": sum(s == RETIRED for s in states),
        }

    def live_count(self) -> int:
        with self._lock:
            return sum(h.state in LIVE_STATES
                       for h in self._engines.values())

    def status(self) -> dict:
        """Membership snapshot (fleet_status.json's ``engines`` half —
        the router folds its routing view in before writing)."""
        with self._lock:
            return {
                "pid": os.getpid(),
                "started_at": self.started_at,
                "target": self.target,
                "scale_events": self.scale_events,
                "restarts_total": self.restarts_total,
                **self.counts(),
                "engines": {
                    h.engine_id: {
                        "pid": h.pid, "state": h.state, "port": h.port,
                        "restarts": h.restarts, "streak": h.streak,
                        "last_rc": h.last_rc,
                        "cpus": list(h.cpus),
                        "queue_depth": h.health.get("queue_depth"),
                        "overload": h.health.get("overload"),
                        "params_step": h.health.get("params_step"),
                        "swaps_total": h.health.get("swaps_total"),
                    } for h in self._engines.values()},
            }

    def _export_gauges(self) -> None:
        if self.registry is None:
            return
        c = self.counts()
        self.registry.record_many({
            "engines_alive": float(c["alive"]),
            "engines_failed": float(c["failed"]),
            "engines_backoff": float(c["backoff"])})

    def _write_status_locked(self) -> None:
        # The pool's own status lands inside the router's
        # fleet_status.json; standalone pools (no router) still get a
        # bare file for the soak's pid discovery.
        path = os.path.join(self.dir, "engine_pool.json")
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.status(), f, indent=2)
            os.replace(tmp, path)
        except OSError:
            log.exception("engine-pool status write failed")

    # ---- shutdown ---------------------------------------------------

    def kill_all(self) -> None:
        """Hard-exit teardown (``os._exit`` paths): SIGKILL everything
        now — an unsupervised orphan engine would serve forever."""
        self._quiesced.set()
        with self._lock:
            for h in self._engines.values():
                if h.proc is not None and h.proc.poll() is None:
                    self._kill_handle(h)

    def stop(self, grace_s: float = 15.0) -> None:
        """Drain the fleet: SIGTERM every live engine (their own drain →
        exit 75 contract), SIGKILL stragglers past the grace."""
        self._quiesced.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=grace_s)
        with self._lock:
            live = [h for h in self._engines.values()
                    if h.proc is not None and h.proc.poll() is None]
            for h in live:
                h.state = RETIRING
                try:
                    h.proc.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + grace_s
        for h in live:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                log.warning("engine %s did not drain in %.1fs; SIGKILL",
                            h.engine_id, grace_s)
                h.proc.kill()
                h.proc.wait(timeout=10)
            h.last_rc = h.proc.returncode
            h.state = RETIRED
        with self._lock:
            self._write_status_locked()
