"""Telemetry-driven request router over the engine fleet.

The router is a ``serve_request`` backend (fleet/frontend.py serves it on
the fleet's public port) that proxies each request to ONE engine worker,
chosen from the signals every engine already exports — the load-balancer
surface PR 10 deliberately built and PR 11 made mergeable:

- **routing score** (refreshed by the telemetry poller every
  ``fleet.telemetry_poll_s``): an engine's live queue depth plus a large
  penalty while its ``serve_overload`` gauge is up — new sessions land
  on the least-loaded live engine (round-robin tiebreak);
- **session affinity + clock**: a session sticks to the engine holding
  its slot-pool carry (LRU table bounded at
  ``fleet.affinity_max_sessions``) — the warm path. The affinity entry
  also carries the session's completed-response CLOCK, forwarded on
  every proxy hop as ``X-Session-Clock`` (ISSUE 20): when the engine
  drains, dies, or deploys, the next request re-routes to a survivor,
  which ADOPTS the carry from the shared spill arena iff the record's
  step stamp matches that clock (``fleet_adopt_warm_total``) and
  re-enters cold through the batched prefill otherwise
  (``fleet_adopt_cold_total`` / ``fleet_migrations_total``) — a stale,
  torn, or CRC-bad record can cost latency, never bytes;
- **exact fleet quantiles**: the poller scrapes every engine's
  ``/metrics``, reconstructs the ``serve_request_ms`` histogram from its
  ``_bucket`` exposition (obs/hist.py ``from_prom_buckets`` — exact
  integer counts), and merges the per-window bucket DELTAS bucket-wise:
  ``fleet_p50_ms`` / ``fleet_p99_ms`` are computed on the merged
  histogram, NOT averaged per-engine percentiles (the percentile of a
  union is not a function of shard percentiles — the whole point of the
  PR-11 layout contract), plus a rolling fleet availability burn gauge
  from the engines' terminal-outcome counters;
- **degrade, never wedge**: a transport error mid-request drops the
  engine from the live set, drops the affinity, and retries the request
  ONCE PER SURVIVOR (inference is idempotent; a request in flight on a
  SIGKILLed engine completes on another instead of failing the client).
  With every engine terminal-failed/unreachable the router answers
  ``ServeEngineFailed`` → 503 loudly.

Deadline propagation: forwarded untouched in the ``X-Deadline-Ms``
header — expiry is the ENGINE's batch-collection gate, the router's
transport timeout is only the wedged-peer backstop.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any

from sharetrade_tpu.fleet import proto, wire
from sharetrade_tpu.fleet.wire import FleetClient
from sharetrade_tpu.obs.exporter import parse_prom_text
from sharetrade_tpu.obs.hist import Histogram, from_prom_buckets
from sharetrade_tpu.obs.tsdb import FLEET_HISTORY_FILE, TsdbRing
from sharetrade_tpu.serve.engine import ServeEngineFailed
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.router")

STATUS_FILE = "fleet_status.json"
#: Bounded per-poll telemetry history (obs/tsdb.py) next to the status
#: file — the ``cli obs --history`` window.
HISTORY_FILE = FLEET_HISTORY_FILE

#: The total-outage refusal, word-for-word on both wire backends.
UNROUTED_DETAIL = ("no live engines: the whole fleet is failed, "
                   "draining, or unreachable")

#: Engine-side counters whose window deltas feed the fleet availability
#: burn (bad outcomes) and its denominator (all terminal outcomes).
_BAD_COUNTERS = ("serve_shed_total", "serve_queue_rejected_total",
                 "serve_deadline_expired_total")
_TOTAL_COUNTER = "serve_requests_total"

#: Engine-side spill/adoption counters folded (as window deltas) into
#: the same-named ``fleet_``-prefixed counters — the soak reconciles
#: these exactly against injected kills (ISSUE 20).
_SPILL_COUNTERS = ("serve_adopt_warm_total", "serve_adopt_cold_total",
                   "serve_spill_hits_total", "serve_spill_misses_total",
                   "serve_spill_stale_total", "serve_spill_corrupt_total",
                   "serve_spill_puts_total")

#: Engine-side spill gauges summed fleet-wide each poll.
_SPILL_GAUGES = ("serve_spill_bytes", "serve_spill_sessions")


class _EngineView:
    """The router's live picture of one engine endpoint."""

    __slots__ = ("engine_id", "endpoint", "healthy", "health",
                 "queue_depth", "overload", "params_step",
                 "prev_counts", "prev_counters", "window_p99")

    def __init__(self, engine_id: str, endpoint: tuple[str, int]):
        self.engine_id = engine_id
        self.endpoint = endpoint
        self.healthy = False
        self.health: dict = {}
        self.queue_depth = 0.0
        self.overload = 0.0
        self.params_step = -1
        #: Cumulative serve_request_ms bucket counts at the last scrape
        #: (None until first seen; a restart resets them — detected by a
        #: shrinking count and re-based).
        self.prev_counts: list | None = None
        self.prev_counters: dict = {}
        self.window_p99: float | None = None


class FleetRouter:
    """See the module docstring. ``pool`` is anything with an
    ``endpoints() -> {engine_id: (host, port)}`` view — the supervising
    :class:`~sharetrade_tpu.fleet.pool.EnginePool`, or a static
    ``StaticEndpoints`` for tests/external fleets."""

    #: Front-ends hand this backend the parsed wire trace context.
    wire_traced = True

    def __init__(self, pool: Any, cfg: Any, registry: Any, *,
                 workdir: str | None = None, obs_cfg: Any = None,
                 obs: Any = None):
        self.pool = pool
        self.cfg = cfg                      # FleetConfig
        self.registry = registry
        #: Status-file root; "" disables fleet_status.json entirely
        #: (in-process embedding and unit tests).
        self.dir = cfg.dir if workdir is None else (workdir or None)
        self._obs = obs
        #: The router's span sink (obs/trace.py SpanSink) — None means
        #: no relay spans, and inbound trace context is relayed but not
        #: journaled here.
        self.spans = getattr(obs, "spans", None)
        #: Per-poll gauge history ring; None without a workdir.
        self._history: TsdbRing | None = None
        history_rows = int(getattr(obs_cfg, "history_rows", 2048) or 0)
        if self.dir and history_rows > 0:
            os.makedirs(self.dir, exist_ok=True)
            self._history = TsdbRing(
                os.path.join(self.dir, HISTORY_FILE),
                max_rows=history_rows)
        #: Session → (engine_id | None, completed-response clock),
        #: LRU-bounded. The engine id is None while the session is
        #: between engines (its last engine died/drained) — the CLOCK
        #: must survive that gap, it is what lets the next engine
        #: validate a spill-arena record before adopting the carry.
        self._affinity: OrderedDict[str, tuple[str | None, int]] = \
            OrderedDict()
        self._aff_lock = threading.Lock()
        self._views: dict[str, _EngineView] = {}
        self._views_lock = threading.Lock()
        self._rr = 0                        # round-robin tiebreak
        #: LIVE per-engine outstanding relays (incremented around the
        #: proxy hop, under _views_lock): scraped queue depths go stale
        #: for a whole telemetry interval, and least-loaded routing on a
        #: stale signal sends every arrival in the window to the SAME
        #: "least loaded" engine — a thundering herd that convoys one
        #: engine while the rest idle (measured: worst-case p99 in the
        #: SECONDS under a session burst). The live count is the
        #: router's own ground truth between scrapes.
        self._outstanding: dict[str, int] = {}
        #: Per-handler-thread persistent connections, keyed by endpoint
        #: (an engine respawn changes the port, so stale conns die with
        #: their endpoint key instead of poisoning the new incarnation).
        self._tls = threading.local()
        #: Merged fleet histogram (cumulative across the fleet's whole
        #: life, kills included): bucket-wise sums of per-engine deltas.
        self._fleet_hist = self.registry.attach_histogram(
            "fleet_request_ms", Histogram())
        #: Rolling availability window: (t, cum_bad, cum_total) snapshots
        #: accumulated from engine counter deltas PLUS the router's own
        #: unrouted failures (during a total outage nothing scrapes, and
        #: the burn gauge must climb on router-side refusals alone).
        self._slo_cum_bad = 0.0
        self._slo_cum_total = 0.0
        self._prev_unrouted = 0.0
        # trace-buffer-ok: bounded ring of per-poll snapshots
        self._slo_win: deque[tuple] = deque(maxlen=4096)
        self._slo_win.append((time.monotonic(), 0.0, 0.0))
        slo_avail = float(getattr(obs_cfg, "slo_availability", 0.0)
                          or 0.0)
        slo_window = float(getattr(obs_cfg, "slo_window_s", 60.0) or 60.0)
        self._slo = (slo_avail, slo_window)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)

    # ---- lifecycle --------------------------------------------------

    def start(self) -> "FleetRouter":
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="fleet-telemetry",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._history is not None:
            self._history.close()

    # ---- the serve_request backend (fleet/frontend.py) --------------

    def proxy_request(self, session: str, body: bytes,
                      deadline_raw: str | None,
                      tctx=None) -> tuple[int, bytes]:
        """The THIN data path (fleet/frontend.py's fast path): relay the
        raw request body to one engine and hand its ``(status, body)``
        back — no JSON parse/serialize on the proxy hop, which is what
        keeps the router cheaper per request than an engine (the whole
        premise of scale-out through one router). All routing semantics
        live here: affinity, telemetry scoring, and the migration retry
        — a transport error or a 503 (draining/terminally-failed engine)
        drops the engine from the live view and retries the request on a
        survivor; 429/504/4xx are a LIVE engine's true outcome and pass
        through untouched. The deadline header is forwarded VERBATIM —
        expiry belongs to the engine's collection gate.

        The routing/migration bookkeeping lives in the ``relay_*`` /
        ``note_*`` helpers below so the evloop relay (fleet/evloop.py)
        and this blocking loop share ONE definition of the semantics —
        what keeps the threaded backend an honest differential oracle
        for the event-loop one.

        When ``tctx`` (the front-end's parsed wire trace context)
        arrives and this router has a span sink, the traversal journals
        one ``relay`` envelope plus a ``relay_attempt`` per hop — each
        attempt's span id is forwarded as ``X-Parent-Span`` and its
        ``upstream_io`` child brackets the raw write/read — the same
        span shapes the evloop relay emits (tests hold them to it)."""
        self.registry.inc("fleet_requests_total")
        headers: dict | None = ({wire.DEADLINE_HEADER: deadline_raw}
                                if deadline_raw is not None else None)
        clock = self.session_clock(session)
        if clock > 0:
            # The adoption contract's router half (ISSUE 20): the engine
            # only pages a spilled carry in when its step stamp matches
            # this completed-response count.
            headers = dict(headers or {})
            headers[wire.CLOCK_HEADER] = str(clock)
        timeout_s = self.relay_timeout_s(deadline_raw)
        tried: set[str] = set()
        migrated = False
        spans = self.spans
        if spans is None:
            tctx = None
        relay_span = spans.new_span_id() if tctx is not None else ""
        t0 = time.perf_counter()
        next_note = "first"
        while True:
            choice = self._route(session, exclude=tried)
            if choice is None:
                self.note_unrouted()
                if tctx is not None:
                    spans.span(tctx[0], relay_span, tctx[2] or tctx[1],
                               "relay", t0, time.perf_counter(),
                               "unrouted")
                raise ServeEngineFailed(UNROUTED_DETAIL)
            engine_id, endpoint = choice
            client = self._client_for(endpoint)
            self.note_sent(engine_id)
            hop_headers = headers
            attempt_span = io_span = ""
            attempt_t0 = 0.0
            if tctx is not None:
                attempt_span = spans.new_span_id()
                io_span = spans.new_span_id()
                attempt_t0 = time.perf_counter()
                hop_headers = dict(headers or {})
                hop_headers[proto.TRACE_HEADER] = tctx[0]
                hop_headers[proto.PARENT_HEADER] = attempt_span
            status, exc_repr = None, ""
            try:
                status, reply = client.raw_request(
                    wire.SUBMIT_PATH, body, extra_headers=hop_headers,
                    timeout_s=timeout_s)
            except wire.TRANSPORT_ERRORS as exc:
                status, reply, exc_repr = None, b"", repr(exc)
            finally:
                self.note_done(engine_id)
                if tctx is not None:
                    now = time.perf_counter()
                    why = (exc_repr if status is None
                           else f"status {status}")
                    spans.span(tctx[0], io_span, attempt_span,
                               "upstream_io", attempt_t0, now)
                    spans.span(tctx[0], attempt_span, relay_span,
                               "relay_attempt", attempt_t0, now,
                               f"{next_note} {why}".strip())
            if status is None or status == wire.STATUS_UNAVAILABLE:
                # The engine died/hung mid-request (SIGKILL chaos, a
                # deploy) — or answered 503 over a still-open keep-alive
                # because it is draining or terminally failed: either
                # way THIS ENGINE is gone, not the request. Drop it from
                # the live view NOW (the poller re-adds it when its
                # respawn answers), forget the session's affinity, and
                # retry on a survivor — the migration path.
                tried.add(engine_id)
                migrated = True
                why = exc_repr if status is None else f"status {status}"
                next_note = f"migrate:{why}"
                self.note_engine_gone(session, engine_id, why)
                continue
            if tctx is not None:
                spans.span(tctx[0], relay_span, tctx[2] or tctx[1],
                           "relay", t0, time.perf_counter(),
                           "migrated" if migrated else "")
            return self.finish_relay(session, engine_id, migrated,
                                     status, reply)

    def serve_request(self, session: str, obs,
                      deadline_ms: float | None, tctx=None) -> dict:
        """The in-process convenience surface (tests, embedding): the
        same routing path as :meth:`proxy_request`, with the JSON
        round-trip this caller asked for."""
        body = json.dumps({"session": session,
                           "obs": [float(x) for x in obs]}).encode()
        status, reply = self.proxy_request(
            session, body,
            f"{float(deadline_ms):g}" if deadline_ms else None,
            tctx=tctx)
        try:
            parsed = json.loads(reply.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            parsed = {}
        if status == wire.STATUS_OK:
            return parsed
        raise wire.status_to_error(status, parsed)

    def health(self) -> dict:
        with self._views_lock:
            live = [v.engine_id for v in self._views.values()
                    if v.healthy]
            steps = sorted({v.params_step for v in self._views.values()
                            if v.healthy and v.params_step >= 0})
        with self._aff_lock:
            affinity = len(self._affinity)
        return {
            "ok": bool(live),
            "role": "router",
            "engines_live": len(live),
            "engines": live,
            "affinity_sessions": affinity,
            "params_steps": steps,
        }

    def _count_outcome_error(self) -> None:
        self.registry.inc("fleet_refused_total")

    # ---- relay semantics (shared by both wire backends) --------------
    #
    # One hop of the data path, decomposed so the blocking loop above
    # and the evloop relay drive IDENTICAL bookkeeping: note_sent /
    # note_done bracket the hop (live outstanding), note_engine_gone is
    # the migration step, finish_relay the terminal accounting.

    def relay_timeout_s(self, deadline_raw: str | None) -> float:
        """Per-attempt transport timeout: the deadline plus slack when
        the client set one (expiry still belongs to the ENGINE — this
        is only the wedged-peer backstop), the configured front-end
        budget otherwise."""
        if deadline_raw is not None:
            try:
                return max(float(deadline_raw) / 1e3 * 4, 5.0)
            except ValueError:
                pass
        return self.cfg.request_timeout_s

    def note_sent(self, engine_id: str) -> None:
        with self._views_lock:
            self._outstanding[engine_id] = \
                self._outstanding.get(engine_id, 0) + 1

    def note_done(self, engine_id: str) -> None:
        with self._views_lock:
            n = self._outstanding.get(engine_id, 1) - 1
            if n > 0:
                self._outstanding[engine_id] = n
            else:
                self._outstanding.pop(engine_id, None)

    def note_engine_gone(self, session: str, engine_id: str,
                         why: str) -> None:
        """This ENGINE is gone, not the request: drop it from the live
        view (the poller re-adds it when its respawn answers), forget
        the session's affinity, and let the caller retry a survivor."""
        self._mark_unreachable(engine_id)
        self._drop_affinity(session)
        self.registry.inc("fleet_engine_errors_total")
        log.warning("engine %s gone mid-request (%s); re-routing "
                    "session %s", engine_id, why, session)

    def note_unrouted(self) -> None:
        self.registry.inc("fleet_unrouted_total")

    def finish_relay(self, session: str, engine_id: str, migrated: bool,
                     status: int, reply: bytes) -> tuple[int, bytes]:
        """Terminal accounting for a relayed reply: migration counter,
        affinity (the session clock ticks on a 200 — the router's half
        of the spill-adoption stamp contract), completion/refusal
        counters, and the engine-id splice into a 200's bytes (before
        the object's closing brace — naming the serving engine without
        a JSON round-trip)."""
        if migrated:
            self.registry.inc("fleet_migrations_total")
        self._note_affinity(session, engine_id,
                            bump=status == wire.STATUS_OK)
        if status == wire.STATUS_OK:
            self.registry.inc("fleet_completed_total")
            cut = reply.rfind(b"}")
            if cut >= 0:
                reply = (reply[:cut]
                         + f',"engine":"{engine_id}"'.encode()
                         + reply[cut:])
        else:
            # A live engine's protocol outcome (rejected / deadline /
            # bad request): the request's true terminal state, relayed
            # untouched, never retried by the router.
            self._count_outcome_error()
        return status, reply

    # ---- routing ----------------------------------------------------

    def _route(self, session: str,
               exclude: set) -> tuple[str, tuple[str, int]] | None:
        endpoints = self.pool.endpoints()
        with self._views_lock:
            def usable(eid: str) -> bool:
                if eid in exclude or eid not in endpoints:
                    return False
                view = self._views.get(eid)
                # Before the first telemetry pass a listed endpoint is
                # given the benefit of the doubt (the submit path's
                # transport retry is the corrector).
                return view is None or view.healthy

            with self._aff_lock:
                entry = self._affinity.get(session)
            sticky = entry[0] if entry is not None else None
            if sticky is not None and usable(sticky):
                return sticky, endpoints[sticky]
            candidates = [eid for eid in endpoints if usable(eid)]
            if not candidates:
                return None
            def score(eid: str) -> float:
                view = self._views.get(eid)
                live = float(self._outstanding.get(eid, 0))
                if view is None:
                    return live
                return live + view.queue_depth + 1e6 * view.overload
            scored = [(score(eid), eid) for eid in candidates]
            best = min(s for s, _ in scored)
            pool = [eid for s, eid in scored if s == best]
            self._rr += 1
            chosen = pool[self._rr % len(pool)]
            return chosen, endpoints[chosen]

    def session_clock(self, session: str) -> int:
        """The session's completed-response count as this router has
        observed it (0 for an unknown session) — what the engine
        validates a spill record's step stamp against before adopting."""
        with self._aff_lock:
            entry = self._affinity.get(session)
        return entry[1] if entry is not None else 0

    def _note_affinity(self, session: str, engine_id: str, *,
                       bump: bool) -> None:
        with self._aff_lock:
            existing = self._affinity.pop(session, None)
            clock = existing[1] if existing is not None else 0
            # A 200 means the engine committed one more carry step for
            # this session — tick the clock; protocol refusals
            # (429/504/4xx) never touched the carry.
            self._affinity[session] = (engine_id, clock + 1 if bump
                                       else clock)
            while len(self._affinity) > self.cfg.affinity_max_sessions:
                self._affinity.popitem(last=False)

    def _drop_affinity(self, session: str) -> None:
        """Detach the session from its engine but KEEP its clock: the
        engine is gone, the session's history is not — the clock is the
        key that unlocks warm adoption from the spill arena."""
        with self._aff_lock:
            entry = self._affinity.get(session)
            if entry is not None:
                self._affinity[session] = (None, entry[1])

    def _drop_engine_affinity(self, engine_id: str) -> None:
        """Detach every session stuck to a dead engine (clock kept —
        see :meth:`_drop_affinity`) so the NEXT request of each
        re-routes without paying a transport error."""
        with self._aff_lock:
            for sid, (eid, clk) in list(self._affinity.items()):
                if eid == engine_id:
                    self._affinity[sid] = (None, clk)

    def _mark_unreachable(self, engine_id: str) -> None:
        with self._views_lock:
            view = self._views.get(engine_id)
            if view is not None:
                view.healthy = False
        self._drop_engine_affinity(engine_id)

    def _client_for(self, endpoint: tuple[str, int]) -> FleetClient:
        cache = getattr(self._tls, "clients", None)
        if cache is None:
            cache = self._tls.clients = {}
        client = cache.get(endpoint)
        if client is None:
            client = cache[endpoint] = FleetClient(
                endpoint[0], endpoint[1],
                timeout_s=self.cfg.request_timeout_s)
        return client

    # ---- telemetry poller -------------------------------------------

    def _poll_loop(self) -> None:
        interval = max(self.cfg.telemetry_poll_s, 0.05)
        while not self._stop.wait(interval):
            try:
                self.poll_once()
            except Exception:   # noqa: BLE001 — telemetry must outlive
                log.exception("fleet telemetry poll failed")

    def poll_once(self) -> None:
        """One telemetry pass (public: tests/the soak drive it
        deterministically): scrape every endpoint's healthz + metrics,
        refresh routing scores, merge histogram deltas, publish fleet
        gauges, rewrite fleet_status.json."""
        endpoints = self.pool.endpoints()
        scraped: dict[str, tuple[dict | None, dict | None]] = {}
        for engine_id, endpoint in endpoints.items():
            client = FleetClient(endpoint[0], endpoint[1],
                                 timeout_s=self.cfg.scrape_timeout_s)
            health = metrics = None
            try:
                health = client.health()
                metrics = parse_prom_text(client.metrics())
            except Exception:   # noqa: BLE001 — an unreachable engine is
                pass            # a datum (unhealthy), not a poller fault
            finally:
                client.close()
            scraped[engine_id] = (health, metrics)
        window_counts: list | None = None
        bounds = None
        window_bad = 0.0
        window_total = 0.0
        dead_engines = []
        spill_sums = {name: 0.0 for name in _SPILL_GAUGES}
        spill_seen = False
        with self._views_lock:
            for engine_id, endpoint in endpoints.items():
                view = self._views.get(engine_id)
                if view is None or view.endpoint != endpoint:
                    view = self._views[engine_id] = _EngineView(
                        engine_id, endpoint)
                health, metrics = scraped[engine_id]
                was_healthy = view.healthy
                view.healthy = bool(health) and not health.get(
                    "failed", False) and not health.get("draining", False)
                if health:
                    view.health = health
                    view.queue_depth = float(
                        health.get("queue_depth", 0) or 0)
                    view.overload = float(health.get("overload", 0) or 0)
                    view.params_step = int(
                        health.get("params_step", -1))
                if was_healthy and not view.healthy:
                    dead_engines.append(engine_id)
                if metrics:
                    w_counts, w_p99 = self._fold_engine_metrics(
                        view, metrics)
                    if w_counts is not None:
                        if window_counts is None:
                            window_counts = list(w_counts)
                            bounds = self._fleet_hist.bounds
                        else:
                            for i, c in enumerate(w_counts):
                                window_counts[i] += c
                    view.window_p99 = w_p99
                    bad, total = self._counter_deltas(view, metrics)
                    window_bad += bad
                    window_total += total
                    mg = metrics.get("gauges") or {}
                    for name in _SPILL_GAUGES:
                        v = mg.get(f"sharetrade_{name}")
                        if v is not None:
                            spill_seen = True
                            spill_sums[name] += float(v)
            # Engines the pool no longer lists (retired/failed corpses)
            # drop out of the view entirely.
            for gone in set(self._views) - set(endpoints):
                dead_engines.append(gone)
                del self._views[gone]
            live = sum(v.healthy for v in self._views.values())
            steps = [v.params_step for v in self._views.values()
                     if v.healthy and v.params_step >= 0]
            # Fleet-aggregate load signals, into the same gauge row the
            # history ring records — the autoscaler's (fleet/autoscale.
            # py) queue-pressure and overload inputs.
            agg_depth = sum(v.queue_depth
                            for v in self._views.values() if v.healthy)
            agg_overload = float(any(
                v.overload for v in self._views.values() if v.healthy))
        for engine_id in dead_engines:
            self._drop_engine_affinity(engine_id)
        # Router-level failures count against availability too: an
        # unrouted request never reached an engine counter, and a total
        # outage (no scrapes at all) must still burn the budget.
        unrouted = self.registry.counters().get("fleet_unrouted_total",
                                                0.0)
        d_unrouted = max(0.0, unrouted - self._prev_unrouted)
        self._prev_unrouted = unrouted
        window_bad += d_unrouted
        window_total += d_unrouted
        gauges: dict[str, float] = {
            "fleet_engines_live": float(live),
            "fleet_queue_depth": float(agg_depth),
            "fleet_overload": agg_overload,
        }
        if window_counts is not None and sum(window_counts) > 0:
            from sharetrade_tpu.obs.hist import quantile_from_counts
            gauges["fleet_p50_ms"] = quantile_from_counts(
                bounds, window_counts, 0.50)
            gauges["fleet_p99_ms"] = quantile_from_counts(
                bounds, window_counts, 0.99)
        if steps:
            # Swap-propagation lag: how far the slowest live engine
            # trails the freshest published weights, in checkpoint steps.
            gauges["fleet_swap_lag_steps"] = float(max(steps) - min(steps))
        if spill_seen:
            # Fleet-wide spill-tier footprint: engines sharing one arena
            # each report the whole directory, so these sums over-count
            # by the sharing factor — they are a LOAD signal (how much
            # parked state a kill would put in play), not an exact
            # byte census; the counters above are the exact side.
            gauges["fleet_spill_bytes"] = spill_sums["serve_spill_bytes"]
            gauges["fleet_spill_sessions"] = \
                spill_sums["serve_spill_sessions"]
        with self._aff_lock:
            gauges["fleet_affinity_sessions"] = float(len(self._affinity))
        gauges.update(self._slo_burn(window_bad, window_total))
        self.registry.record_many(gauges)
        if self._history is not None:
            self._history.append({"ts": time.time(), **gauges})
        self._write_status(gauges)

    def _fold_engine_metrics(
            self, view: _EngineView,
            metrics: dict) -> tuple[list | None, float | None]:
        """Fold one engine's scraped ``serve_request_ms`` exposition:
        returns (window bucket-count delta, engine window p99). The
        delta is EXACT (integer cumulative subtraction); an engine
        restart (shrinking counts) re-bases at zero so a respawn's
        fresh histogram is not read as a negative window."""
        hist = (metrics.get("histograms") or {}).get(
            "sharetrade_serve_request_ms")
        if not hist:
            return None, None
        rebuilt = from_prom_buckets(hist["buckets"], hist["sum"],
                                    int(hist["count"]))
        counts = rebuilt.snapshot()["counts"]
        prev = view.prev_counts
        view.prev_counts = counts
        if prev is None or len(prev) != len(counts):
            prev = [0] * len(counts)
        delta = [a - b for a, b in zip(counts, prev)]
        if any(d < 0 for d in delta):
            # ANY negative bucket means the engine restarted between
            # scrapes (cumulative counts only grow within one life) —
            # a total-sum check misses a respawn that already out-served
            # its predecessor, and merging a negative bucket would
            # corrupt the fleet histogram permanently. Re-base: the
            # fresh incarnation's whole histogram IS the window.
            delta = list(counts)
        if sum(delta) <= 0:
            return delta, view.window_p99
        # Merge THIS window's per-engine delta into the cumulative fleet
        # histogram (bucket-wise integer add — exact).
        window = Histogram(bounds=rebuilt.bounds)
        window.counts = list(delta)
        window.count = sum(delta)
        self._fleet_hist.merge(window)
        p99 = rebuilt.quantile(0.99, counts=delta)
        return delta, p99

    def _counter_deltas(self, view: _EngineView,
                        metrics: dict) -> tuple[float, float]:
        counters = metrics.get("counters") or {}
        bad = total = 0.0
        cur: dict[str, float] = {}
        for name in _BAD_COUNTERS + (_TOTAL_COUNTER,) + _SPILL_COUNTERS:
            cur[name] = float(counters.get(f"sharetrade_{name}", 0.0))
        prev = view.prev_counters
        view.prev_counters = cur
        restarted = bool(prev) and cur.get(_TOTAL_COUNTER, 0) < prev.get(
            _TOTAL_COUNTER, 0)
        if prev and not restarted:
            for name in _BAD_COUNTERS:
                bad += max(0.0, cur[name] - prev.get(name, 0.0))
            total = max(0.0, cur[_TOTAL_COUNTER]
                        - prev.get(_TOTAL_COUNTER, 0.0))
        # Spill/adoption deltas fold into same-named fleet_ counters the
        # soak reconciles EXACTLY against injected kills. A restarted
        # engine's fresh counters ARE its window (rebase at zero); the
        # first scrape of a new engine folds everything since its boot.
        base = {} if restarted else (prev or {})
        for name in _SPILL_COUNTERS:
            d = cur[name] - base.get(name, 0.0)
            if d > 0:
                self.registry.inc("fleet_" + name[len("serve_"):], d)
        return bad, total

    def _slo_burn(self, window_bad: float,
                  window_total: float) -> dict[str, float]:
        """Fleet availability burn over the rolling ``obs.slo_window_s``:
        engine-counter deltas (sheds/rejections/expiries) plus the
        router's own unrouted failures, against the same objective the
        per-engine burn gauges use. Inert without an objective."""
        avail, window_s = self._slo
        if avail <= 0:
            return {}
        self._slo_cum_bad += window_bad
        self._slo_cum_total += window_total
        now = time.monotonic()
        win = self._slo_win
        win.append((now, self._slo_cum_bad, self._slo_cum_total))
        while len(win) > 1 and win[1][0] <= now - window_s:
            win.popleft()
        base = win[0]
        d_bad = self._slo_cum_bad - base[1]
        d_total = self._slo_cum_total - base[2]
        if d_total <= 0:
            return {}
        return {"fleet_slo_availability_burn":
                (d_bad / d_total) / (1.0 - avail)}

    # ---- status export ----------------------------------------------

    def _write_status(self, gauges: dict) -> None:
        if not self.dir:
            return
        status = {"ts": time.time(), "router": self.health()}
        pool_status = getattr(self.pool, "status", None)
        if callable(pool_status):
            status["pool"] = pool_status()
        with self._views_lock:
            status["telemetry"] = {
                v.engine_id: {
                    "healthy": v.healthy,
                    "queue_depth": v.queue_depth,
                    "overload": v.overload,
                    "params_step": v.params_step,
                    "window_p99_ms": v.window_p99,
                } for v in self._views.values()}
        status["gauges"] = {k: v for k, v in gauges.items()}
        # Selector internals (ISSUE 19): the front-end records these
        # straight into the shared registry (open keep-alive conns,
        # live parse backend) — fold the latest values in so `cli obs`
        # sees the loop thread without scraping /metrics.
        for name, value in self.registry.snapshot().items():
            if (name.startswith("fleet_evloop_")
                    or name.startswith("fleet_proto_backend")):
                status["gauges"][name] = value
        status["counters"] = {
            k: v for k, v in self.registry.counters().items()
            if k.startswith("fleet_")}
        fleet_hist = self._fleet_hist.snapshot()
        status["fleet_request_ms"] = {
            "count": fleet_hist["count"],
            "p50_ms": self._fleet_hist.quantile(0.50),
            "p99_ms": self._fleet_hist.quantile(0.99),
        }
        path = os.path.join(self.dir, STATUS_FILE)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(status, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            log.exception("fleet status write failed")


class StaticEndpoints:
    """A fixed endpoint set standing in for an :class:`EnginePool` —
    tests and externally-supervised fleets."""

    def __init__(self, endpoints: dict[str, tuple[str, int]]):
        self._endpoints = dict(endpoints)

    def endpoints(self) -> dict[str, tuple[str, int]]:
        return dict(self._endpoints)

    def set(self, endpoints: dict[str, tuple[str, int]]) -> None:
        self._endpoints = dict(endpoints)
