"""Network front-end: ``ServeEngine.submit`` exposed over a wire.

Two interchangeable wire backends serve the same protocol (fleet/
wire.py) over any BACKEND object with the two-method surface

- ``serve_request(session, obs, deadline_ms) -> dict`` — blocking; raises
  the serving exceptions (mapped to distinct wire statuses), and
- ``health() -> dict`` — the ``/healthz`` snapshot;

plus ``/metrics`` rendered live from a :class:`~sharetrade_tpu.utils.
metrics.MetricsRegistry`. Two backends exist: :class:`EngineBackend`
(this module — a local engine, what ``cli serve --listen`` runs) and the
router's proxy (fleet/router.py) — the fleet's public port is literally
this same server over a different backend.

The wire backends (``fleet.wire_backend``):

- ``"evloop"`` (default) — fleet/evloop.py: one selector thread, no
  thread per connection or in-flight request — the scalable data path.
- ``"threaded"`` — :class:`ThreadedServeFrontend` below: a stdlib
  ThreadingHTTPServer, one handler thread per connection. Retained as
  the differential-testing ORACLE: both backends render replies through
  fleet/proto.py, so for the same request stream their response bytes
  are identical (tests/test_fleet_wire.py holds them to it).

:func:`ServeFrontend` is the factory both spellings go through.

Deadline propagation: the client's ``X-Deadline-Ms`` header flows into
``submit(deadline_ms=)`` — the ENGINE's batch-collection gate expires it
(``ServeDeadlineExceeded`` → 504), never this layer's clock; the
front-end's own ``request_timeout_s`` bounds only a request's life
against a wedged engine (and maps to 503, the "engine gone" truth).

Drain contract (the ``cli serve`` SIGTERM contract over a wire): `drain()`
stops the listener — new connections are refused at the TCP layer, the
OS-visible "draining" signal a fleet router reacts to — then waits for
every in-flight request to finish; the process then exits 75.

fleet-net-ok: this module IS the fleet's network layer — the one place
lint check 14 allows listeners inside sharetrade_tpu/.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from sharetrade_tpu.fleet import proto, wire
from sharetrade_tpu.obs.exporter import render_prom_text
from sharetrade_tpu.serve.engine import ServeEngineFailed
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.frontend")


class EngineBackend:
    """The local-engine backend: one blocking wire request ↔ one
    ``engine.submit`` + ``handle.wait`` (threaded backend), or one
    ``submit_async`` parked on the engine's completion callback
    (evloop backend) — identical validation and result payloads.

    When a span sink is wired (``spans=``), a traced request leaves two
    kinds of evidence in THIS process's span journal, both parented
    directly under the router's relay-attempt span from the wire headers
    (never under an engine-local span — obs/collect.py's SIGKILL-orphan
    rule): an ``engine_recv`` instant flushed EAGERLY at arrival (the
    page cache survives a SIGKILL, so a killed engine still proves the
    request reached it) and, at completion, an ``engine_request``
    envelope with stage children cut from the request's lifecycle
    stamps (queue_wait/batch_wait/device/readback)."""

    #: Frontends pass the parsed wire trace context (``tctx``) only to
    #: backends that declare it — test stubs never see the kwarg.
    wire_traced = True
    #: Frontends pass the parsed ``X-Session-Clock`` header (the
    #: router-observed completed-response count, ISSUE 20) only to
    #: backends that declare it — the engine validates a spill record's
    #: step stamp against it before adopting the carry.
    wire_clocked = True

    def __init__(self, engine, *, request_timeout_s: float = 30.0,
                 spans=None):
        self.engine = engine
        self.request_timeout_s = float(request_timeout_s)
        self.spans = spans

    @staticmethod
    def validate_obs(obs) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if obs.ndim != 1 or obs.size < 3:
            raise ValueError(
                f"obs must be a flat (window + portfolio) vector, got "
                f"shape {obs.shape}")
        if not np.all(np.isfinite(obs)):
            raise ValueError("obs contains non-finite values")
        return obs

    @staticmethod
    def result_dict(result) -> dict:
        return {
            "session": result.session_id,
            "action": int(result.action),
            "logits": [float(x) for x in np.asarray(result.logits)],
            "value": float(result.value),
            "params_step": int(result.params_step),
            "latency_ms": float(result.latency_ms),
            "stages": result.stages,
        }

    def trace_recv(self, tctx) -> None:
        """Journal the eager ``engine_recv`` instant (class docstring);
        a no-op without a sink or trace context."""
        if tctx is None or self.spans is None:
            return
        trace_id, parent, own, _t0 = tctx
        self.spans.instant(trace_id, self.spans.new_span_id(),
                           own or parent, "engine_recv", flush=True)

    def trace_complete(self, tctx, handle) -> None:
        """Journal the ``engine_request`` envelope + stage children from
        the handle's lifecycle stamps. The threaded path calls this from
        :meth:`serve_request`; the evloop front-end calls it from its
        completion handler (the async path has no blocking wait to hang
        it on)."""
        if tctx is None or self.spans is None:
            return
        trace_id, parent, own, _t0 = tctx
        tr = handle.trace
        t_end = tr.t_done or tr.t_device or time.perf_counter()
        env = self.spans.new_span_id()
        self.spans.span(trace_id, env, own or parent, "engine_request",
                        tr.t_enq, t_end, note=tr.outcome or "")
        for name, a, b in (("queue_wait", tr.t_enq, tr.t_collected),
                           ("batch_wait", tr.t_collected, tr.t_dispatched),
                           ("device", tr.t_dispatched, tr.t_device),
                           ("readback", tr.t_device, tr.t_done)):
            if a is not None and b is not None:
                self.spans.span(trace_id, self.spans.new_span_id(), env,
                                name, a, b)

    def _submit(self, session: str, obs, deadline_ms, tctx, callback=None,
                clock=None):
        """Shared enqueue: recv span, submit, thread the trace identity
        into the request's :class:`RequestTrace` (the ISSUE-17 stitch
        key the engine's own chrome-trace spans carry)."""
        self.trace_recv(tctx)
        handle = self.engine.submit(session, obs, callback=callback,
                                    deadline_ms=deadline_ms or 0.0,
                                    session_clock=clock)
        if tctx is not None:
            handle.trace.trace_id = tctx[0]
            handle.trace.parent_span = tctx[2] or tctx[1]
        return handle

    def serve_request(self, session: str, obs,
                      deadline_ms: float | None, tctx=None,
                      clock: int | None = None) -> dict:
        obs = self.validate_obs(obs)
        handle = self._submit(session, obs, deadline_ms, tctx, clock=clock)
        # A deadline'd request resolves engine-side well inside
        # deadline + one batch; the no-deadline wait is bounded by the
        # configured front-end budget so a wedged engine surfaces as a
        # loud 503 instead of an immortal handler thread.
        timeout = (max(float(deadline_ms) / 1e3 * 4, 5.0) if deadline_ms
                   else self.request_timeout_s)
        result = handle.wait(timeout)
        try:
            if result is None:
                if handle.error is not None:
                    raise handle.error
                raise ServeEngineFailed(
                    f"request did not complete within the front-end "
                    f"budget ({timeout:.1f}s)")
            return self.result_dict(result)
        finally:
            self.trace_complete(tctx, handle)

    def submit_async(self, session: str, obs, deadline_ms: float | None,
                     signal_done, tctx=None, clock: int | None = None):
        """The evloop front-end's dispatch: validate and enqueue, then
        return the request handle WITHOUT waiting — ``signal_done()``
        fires (from the engine's consumer thread) once the handle
        completes; read ``handle.result`` / ``handle.error`` after."""
        obs = self.validate_obs(obs)
        return self._submit(session, obs, deadline_ms, tctx,
                            callback=lambda _result: signal_done(),
                            clock=clock)

    def health(self) -> dict:
        engine = self.engine
        reg = engine.registry
        refresh = getattr(engine, "refresh_spill_gauges", None)
        if refresh is not None:
            # The scrape IS the stats clock while the engine idles: the
            # router reads health then /metrics each poll, and the spill
            # census must be live in that same poll even with no batch
            # completing (cadence-gated inside — one bounded scandir).
            refresh()
        return {
            "ok": engine.failed is None,
            "failed": engine.failed is not None,
            "queue_depth": int(engine.queue_depth()),
            "overload": float(reg.latest("serve_overload", 0.0) or 0.0),
            "params_step": int(engine.params_step),
            "swaps_total": int(
                reg.counters().get("serve_swaps_total", 0)),
        }


class _FrontendServer(ThreadingHTTPServer):
    # fleet-net-ok: the fleet's threaded listener implementation.
    daemon_threads = True
    allow_reuse_address = True
    # Match the evloop listener's backlog so a connection-storm bench
    # measures the thread-per-connection cost, not accept-queue drops.
    request_queue_size = 1024

    def __init__(self, addr, handler, frontend: "ThreadedServeFrontend"):
        super().__init__(addr, handler)
        self.frontend = frontend


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"       # keep-alive: the perf floor
    server_version = "sharetrade-fleet"

    def log_message(self, fmt, *args):   # request logging is telemetry's
        pass                             # job, not stderr's

    # -- plumbing ---------------------------------------------------------

    def _reply(self, status: int, body: dict | bytes,
               content_type: str = "application/json") -> None:
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        self._last_status = status       # the hop span's outcome note
        try:
            # Rendered by the shared sans-IO builder — byte-identical
            # to the evloop backend's replies (the differential-oracle
            # contract), not send_response's Server/Date decoration.
            self.wfile.write(proto.render_response(status, payload,
                                                   content_type))
        except (BrokenPipeError, ConnectionResetError):
            # The client hung up mid-reply (teardown, a canceled
            # request): its socket is the only casualty — never the
            # handler thread or the error log.
            self.close_connection = True

    # -- verbs ------------------------------------------------------------

    def do_POST(self):
        fe = self.server.frontend
        # Consume the body UNCONDITIONALLY before any reply: an early
        # 404/503 that leaves it unread poisons the next keep-alive
        # request on this connection (the leftover bytes parse as a
        # garbage request line). The length check itself is proto's —
        # one definition of a well-formed Content-Length on the wire.
        try:
            length = proto.content_length(
                self.headers.get("Content-Length"))
        except proto.ProtocolError as exc:
            self._reply(exc.status, {"error": "bad_request",
                                     "detail": exc.detail})
            self.close_connection = True
            return
        raw = self.rfile.read(length)
        if self.path != wire.SUBMIT_PATH:
            self._reply(404, {"error": "not_found"})
            return
        with fe._inflight_cv:
            draining = fe.draining
            if not draining:
                fe._inflight += 1
        if draining:
            # Connections accepted before the listener closed still get
            # a loud, distinct refusal instead of a hang — written OFF
            # the condition lock: a stalled client's full TCP buffer
            # must block only its own handler, never every thread
            # waiting to bump the in-flight count.
            self._reply(wire.STATUS_UNAVAILABLE,
                        {"error": "engine_failed",
                         "detail": "front-end is draining"})
            return
        # email.message.Message.get is case-insensitive, so the parsed
        # proto header dict and this stdlib mapping read identically.
        tracer = fe.tracer
        tctx = tracer.begin(self.headers) if tracer is not None else None
        traced = tctx is not None and getattr(fe.backend, "wire_traced",
                                              False)
        try:
            deadline_raw = self.headers.get(wire.DEADLINE_HEADER)
            proxy = getattr(fe.backend, "proxy_request", None)
            if proxy is not None:
                # Thin-relay fast path (the router): only the session id
                # is extracted — the body is forwarded and the reply
                # relayed as BYTES, so the proxy hop never pays a JSON
                # round-trip (the router-thinner-than-an-engine premise).
                try:
                    session = wire.extract_session(raw)
                except ValueError as exc:
                    self._reply(*wire.error_to_status(exc))
                    return
                try:
                    status, reply = (proxy(session, raw, deadline_raw,
                                           tctx=tctx) if traced
                                     else proxy(session, raw,
                                                deadline_raw))
                except Exception as exc:    # noqa: BLE001
                    status, reply = wire.error_to_status(exc)
                    if status == 500:
                        log.exception("router relay failed internally")
                self._reply(status, reply)
                return
            try:
                payload = json.loads(raw)
                session = payload["session"]
                obs = payload["obs"]
            except (ValueError, KeyError, TypeError) as exc:
                self._reply(*wire.error_to_status(
                    ValueError(f"malformed submit body: {exc!r}")))
                return
            deadline_ms = None
            if deadline_raw is not None:
                try:
                    deadline_ms = float(deadline_raw)
                except ValueError:
                    self._reply(*wire.error_to_status(ValueError(
                        f"malformed {wire.DEADLINE_HEADER}: "
                        f"{deadline_raw!r}")))
                    return
            clock = None
            clock_raw = self.headers.get(wire.CLOCK_HEADER)
            if clock_raw is not None and getattr(
                    fe.backend, "wire_clocked", False):
                try:
                    clock = int(clock_raw) or None
                except ValueError:
                    self._reply(*wire.error_to_status(ValueError(
                        f"malformed {wire.CLOCK_HEADER}: "
                        f"{clock_raw!r}")))
                    return
            fe.registry.inc("frontend_requests_total")
            kwargs = {"clock": clock} if clock is not None else {}
            try:
                result = (fe.backend.serve_request(session, obs,
                                                   deadline_ms, tctx=tctx,
                                                   **kwargs)
                          if traced else
                          fe.backend.serve_request(session, obs,
                                                   deadline_ms, **kwargs))
            except Exception as exc:    # noqa: BLE001 — every serving
                # outcome maps to a wire status; the handler never dies.
                status, body = wire.error_to_status(exc)
                if status == 500:
                    log.exception("front-end request failed internally")
                fe.registry.inc("frontend_errors_total")
                self._reply(status, body)
                return
            self._reply(wire.STATUS_OK, result)
        finally:
            if tctx is not None:
                tracer.finish(tctx, "frontend",
                              note=str(getattr(self, "_last_status", "")))
            with fe._inflight_cv:
                fe._inflight -= 1
                fe._inflight_cv.notify_all()

    def do_GET(self):
        fe = self.server.frontend
        if self.path == wire.HEALTH_PATH:
            try:
                body = fe.backend.health()
            except Exception as exc:    # noqa: BLE001
                self._reply(wire.STATUS_UNAVAILABLE,
                            {"ok": False, "detail": repr(exc)})
                return
            body["draining"] = fe.draining
            self._reply(wire.STATUS_OK, body)
        elif self.path == wire.METRICS_PATH:
            reg = fe.registry
            text = render_prom_text(reg.snapshot(), reg.counters(),
                                    reg.histograms())
            self._reply(wire.STATUS_OK, text.encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply(404, {"error": "not_found"})


class ThreadedServeFrontend:
    """The thread-per-connection wire backend (module docstring).
    ``port=0`` binds an ephemeral port; read :attr:`port` after
    construction for the actual one."""

    def __init__(self, backend, registry, *, host: str = "127.0.0.1",
                 port: int = 0, tracer=None):
        self.backend = backend
        self.registry = registry
        #: Optional :class:`~sharetrade_tpu.fleet.wire.WireTracer` —
        #: None (the default) means zero trace parsing and zero spans.
        self.tracer = tracer
        self.draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._server = _FrontendServer((host, port), _Handler, self)
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "ThreadedServeFrontend":
        # Which parse/render implementation is live behind proto.py
        # (native C or Python) — same gauge the evloop records, so
        # /metrics names the wire path under either backend.
        self.registry.record(
            "fleet_proto_backend_native",
            1.0 if proto.proto_backend == "native" else 0.0)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="fleet-frontend", daemon=True)
        self._thread.start()
        log.info("front-end listening on %s:%d", self.host, self.port)
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop accepting, finish in-flight handlers; False on timeout."""
        self.draining = True
        self._server.shutdown()         # closes the accept loop
        deadline = time.monotonic() + timeout_s
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(remaining)
        return True

    def stop(self, timeout_s: float = 10.0) -> None:
        if not self.draining:
            self.draining = True
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout_s)


def ServeFrontend(backend, registry, *, host: str = "127.0.0.1",
                  port: int = 0, wire_backend: str | None = None,
                  tracer=None):
    """Build a wire front-end — the one construction surface both
    backends share (``FleetConfig.wire_backend`` plumbs through here).
    ``None`` means the default backend (evloop). ``tracer`` (a
    :class:`~sharetrade_tpu.fleet.wire.WireTracer` or None) switches
    ISSUE-17 trace propagation on for either backend identically."""
    wire_backend = wire_backend or "evloop"
    if wire_backend == "evloop":
        from sharetrade_tpu.fleet.evloop import EvloopFrontend
        return EvloopFrontend(backend, registry, host=host, port=port,
                              tracer=tracer)
    if wire_backend == "threaded":
        return ThreadedServeFrontend(backend, registry, host=host,
                                     port=port, tracer=tracer)
    raise ValueError(
        f"unknown fleet.wire_backend {wire_backend!r} "
        f"(expected 'evloop' or 'threaded')")
