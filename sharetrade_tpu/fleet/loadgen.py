"""Wire-side load generation: the serve/driver.py harnesses over HTTP.

:class:`WireEngine` adapts the fleet's blocking wire client to the
``submit(session_id, obs, callback=) -> handle`` surface the existing
closed/open-loop measurement harnesses (serve/driver.py) drive — so the
fleet's saturation/p99 numbers come from the SAME harness code and the
SAME quantile convention as every serving number in BASELINE.md, with
only the transport swapped. ``workers`` threads each own one persistent
keep-alive connection (the connection-per-thread contract of
:class:`~sharetrade_tpu.fleet.wire.FleetClient`); the submit queue is
unbounded host-side but the harnesses bound in-flight work at their
concurrency, exactly like the in-process engine path.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable

import numpy as np

from sharetrade_tpu.fleet.wire import FleetClient
from sharetrade_tpu.serve.engine import ServeResult
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.loadgen")

_SHUTDOWN = object()


class _WireHandle:
    __slots__ = ("_event", "result", "error")

    def __init__(self):
        self._event = threading.Event()
        self.result: ServeResult | None = None
        self.error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> ServeResult | None:
        self._event.wait(timeout)
        return self.result


class WireEngine:
    """See the module docstring. ``deadline_ms`` applies to every
    submitted request (0 = none) — the wire header the engine-side gate
    enforces."""

    def __init__(self, host: str, port: int, *, workers: int = 32,
                 deadline_ms: float = 0.0, timeout_s: float = 30.0,
                 sink=None):
        self.host, self.port = host, int(port)
        self.deadline_ms = float(deadline_ms)
        self.timeout_s = float(timeout_s)
        #: Optional span sink (obs/trace.py SpanSink): every worker's
        #: FleetClient then mints a trace per request and journals the
        #: client_submit root span — the soak's stitch anchor.
        self.sink = sink
        self._q: queue.Queue = queue.Queue()
        #: Outstanding = submitted but not yet completed (queue depth
        #: alone misses items a worker has popped and is mid-request
        #: on — drain() must wait for BOTH).
        self._outstanding = 0
        self._out_cv = threading.Condition()
        self._stopped = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, name=f"wire-{i}",
                             daemon=True)
            for i in range(max(1, int(workers)))]
        for t in self._threads:
            t.start()

    def submit(self, session_id: Any, obs: Any,
               callback: Callable | None = None) -> _WireHandle:
        if self._stopped.is_set():
            raise RuntimeError("wire engine is stopped")
        handle = _WireHandle()
        item = (str(session_id), np.asarray(obs, np.float32), callback,
                handle)
        with self._out_cv:
            self._outstanding += 1
        self._q.put(item)
        return handle

    def _worker(self) -> None:
        client = FleetClient(self.host, self.port,
                             timeout_s=self.timeout_s, sink=self.sink)
        try:
            while True:
                item = self._q.get()
                if item is _SHUTDOWN:
                    return
                session, obs, callback, handle = item
                result = None
                try:
                    t0 = time.perf_counter()
                    reply = client.submit(
                        session, obs,
                        deadline_ms=self.deadline_ms or None)
                    # latency_ms is the CLIENT-OBSERVED wire round trip
                    # (what a fleet p99 means); the engine's internal
                    # decomposition rides along in stages.
                    wire_ms = (time.perf_counter() - t0) * 1e3
                    stages = reply.get("stages") or {}
                    stages["engine_ms"] = float(reply["latency_ms"])
                    result = ServeResult(
                        session_id=reply.get("session", session),
                        action=int(reply["action"]),
                        logits=np.asarray(reply["logits"], np.float32),
                        value=float(reply["value"]),
                        params_step=int(reply["params_step"]),
                        latency_ms=wire_ms,
                        stages=stages)
                except Exception as exc:    # noqa: BLE001 — every wire
                    # outcome (rejection, deadline, transport) completes
                    # the handle; the harness counts it as failed.
                    handle.error = exc
                handle.result = result
                handle._event.set()
                if callback is not None:
                    try:
                        callback(result)
                    except Exception:   # noqa: BLE001
                        log.exception("wire result callback failed")
                with self._out_cv:
                    self._outstanding -= 1
                    if self._outstanding == 0:
                        self._out_cv.notify_all()
        finally:
            client.close()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submitted request has COMPLETED (not merely
        been dequeued); False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._out_cv:
            while self._outstanding > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._out_cv.wait(remaining)
        return True

    def stop(self, **_kw) -> bool:
        self._stopped.set()
        for _ in self._threads:
            self._q.put(_SHUTDOWN)
        for t in self._threads:
            t.join(timeout=10.0)
        return all(not t.is_alive() for t in self._threads)
