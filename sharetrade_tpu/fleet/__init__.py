"""Fleet serving tier (ISSUE 15, ROADMAP item 2's scale-out half).

``fleet/`` turns the single-process :class:`~sharetrade_tpu.serve.
engine.ServeEngine` into a horizontally-scaled service:

- **wire.py** — the HTTP/1.1 protocol every hop speaks (deadline
  header, distinct statuses per serving outcome, persistent-connection
  client);
- **proto.py** — the sans-IO HTTP/1.1 parser/renderer (bytes in,
  events out, zero I/O) every party on the wire frames through;
- **frontend.py / evloop.py** — the network front-end serving any
  ``serve_request`` backend (a local engine, or the router) on either
  wire backend: the selector event loop (``fleet.wire_backend =
  "evloop"``, default — no thread per connection) or the threaded
  differential oracle (``"threaded"``);
- **pool.py** — :class:`EnginePool`: whole ``cli serve --listen`` worker
  processes under the shared supervision ladder (distrib/ladder.py);
- **router.py** — :class:`FleetRouter`: telemetry-driven balancing on
  the engines' own exported signals, session affinity with
  cold-restart-through-prefill migration, EXACT fleet quantiles from
  bucket-wise histogram merges, loud degrade when nothing is left;
- **flywheel.py / loadgen.py** — served sessions journaling their
  observed transitions into the learner's ingest path, and the wire
  adapters that let serve/driver.py's harnesses drive a fleet.

Kill-tested end to end by ``tools/fleet_soak.py``; ``cli fleet`` boots
the whole tier.
"""

from sharetrade_tpu.fleet.evloop import EvloopFrontend
from sharetrade_tpu.fleet.frontend import (
    EngineBackend,
    ServeFrontend,
    ThreadedServeFrontend,
)
from sharetrade_tpu.fleet.loadgen import WireEngine
from sharetrade_tpu.fleet.pool import EnginePool
from sharetrade_tpu.fleet.router import FleetRouter, StaticEndpoints
from sharetrade_tpu.fleet.wire import FleetClient

__all__ = [
    "EngineBackend",
    "EnginePool",
    "EvloopFrontend",
    "FleetClient",
    "FleetRouter",
    "ServeFrontend",
    "StaticEndpoints",
    "ThreadedServeFrontend",
    "WireEngine",
]
