"""Fleet autoscaler: the telemetry loop closed into fleet MEMBERSHIP.

The router already measures everything an operator would scale the
fleet by hand from — every poll it appends its gauge row (fleet SLO
availability burn, aggregate queue depth, overload, live-engine count)
to the bounded on-disk history ring (obs/tsdb.py,
``fleet_history.jsonl``). This module is the actuator (ROADMAP item 3's
last loop): a controller thread that reads that ring and drives
:meth:`EnginePool.scale`, with the PR-14 serve-controller discipline
applied to membership:

- **signals, windowed**: the last ``fleet.autoscale_window`` history
  rows. Scale-up wants SUSTAINED pressure — mean availability burn at
  or past ``autoscale_burn_high`` (1.0 = spending the whole error
  budget), mean per-engine queue depth past ``autoscale_queue_high``,
  or overload on at least half the window's rows. One bad poll is
  noise; a bad window is load.
- **hysteresis**: scale-DOWN needs a 2x-longer window in which EVERY
  row is quiet (burn under ``autoscale_burn_low``, per-engine queue
  under ``autoscale_queue_low``, zero overload). Everything between
  the up and down thresholds is the DEAD BAND: hold. The asymmetry is
  what keeps a diurnal load from oscillating the fleet at the band
  edge (the soak pins no-oscillation).
- **bounded, rate-limited steps**: at most ONE engine per decision,
  at most one APPLIED decision per ``autoscale_cooldown_s`` — capacity
  changes lag their own effect (a spawning engine takes seconds to
  serve), and an unbounded step amplifies that lag into overshoot.
- **config is the ceiling**: the target is clamped to
  [``fleet.min_engines``, ``fleet.max_engines`` (0 = num_engines)].
  The autoscaler can never spawn past what the operator allowed nor
  drain the fleet below its floor.
- **scale-down is state-preserving** (ISSUE 20): a retired engine
  drains through SIGTERM → page-out-all → exit 75, sealing every live
  and parked carry into the fleet-shared spill arena before the
  process dies — survivors ADOPT those sessions warm (step-stamp
  validated) instead of cold-restarting them through prefill, so
  shrinking the fleet no longer massacres its session population.

What the autoscaler may ASSUME about the history ring (README "Session
tiers & fleet autoscaling"): rows are appended oldest-to-newest at the
router's poll cadence, each a flat ``{"ts": epoch_s, **gauges}`` dict;
a torn tail line is dropped by ``read_history``, not raised; gauge
keys are ABSENT (not zero) when there was no signal that poll — the
decision treats a missing burn/queue/overload key as quiet, and a
missing file or short ring as "not enough evidence: hold".

Every decision is visible: ``fleet_autoscale_target`` gauge,
``fleet_autoscale_up_total`` / ``fleet_autoscale_down_total``
counters, an atomically rewritten ``fleet_autoscale.json`` (target,
actual, last decision + reason — the ``cli obs`` "sessions" section),
and a flight-ring event per applied scaling when obs is attached.

Deterministic by construction: :meth:`step` takes a fake ``now``,
:meth:`decide` is a pure function of (rows, current target), and the
unit tests drive both with stubbed telemetry rows — no subprocesses,
no router, no sleeps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, NamedTuple

from sharetrade_tpu.config import ConfigError, FleetConfig
from sharetrade_tpu.obs.tsdb import FLEET_HISTORY_FILE, read_history
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.autoscale")

#: The autoscaler's state file (next to fleet_status.json): what ``cli
#: obs`` reads for the autoscaler half of the "sessions" section.
AUTOSCALE_STATE_FILE = "fleet_autoscale.json"


class ScaleDecision(NamedTuple):
    """One applied membership change (the :meth:`EngineAutoscaler.step`
    return value and the flight-ring payload)."""

    action: str                 # "up" | "down"
    target: int
    reason: str


class EngineAutoscaler:
    """See the module docstring. Duck-typed against the pool surface
    (``scale`` / ``live_count`` / ``target``), so tests drive it with a
    stub pool, stubbed history rows, and a fake clock."""

    def __init__(self, pool: Any, cfg: FleetConfig, *,
                 workdir: str | None = None, registry: Any = None,
                 obs: Any = None, clock=time.monotonic):
        if cfg.min_engines < 1:
            raise ConfigError(
                f"fleet.min_engines must be >= 1, got {cfg.min_engines}")
        ceiling = cfg.max_engines if cfg.max_engines > 0 else cfg.num_engines
        if ceiling < cfg.min_engines:
            raise ConfigError(
                f"fleet.max_engines ({ceiling}) must be >= fleet."
                f"min_engines ({cfg.min_engines})")
        if cfg.autoscale_interval_s <= 0 or cfg.autoscale_cooldown_s < 0:
            raise ConfigError(
                "fleet.autoscale_interval_s must be > 0 and "
                f"autoscale_cooldown_s >= 0, got "
                f"{cfg.autoscale_interval_s}/{cfg.autoscale_cooldown_s}")
        if cfg.autoscale_window < 1:
            raise ConfigError(
                f"fleet.autoscale_window must be >= 1, got "
                f"{cfg.autoscale_window}")
        if not (0.0 <= cfg.autoscale_burn_low < cfg.autoscale_burn_high):
            raise ConfigError(
                "fleet.autoscale_burn_low/high need 0 <= low < high, got "
                f"{cfg.autoscale_burn_low}/{cfg.autoscale_burn_high}")
        if not (0.0 <= cfg.autoscale_queue_low < cfg.autoscale_queue_high):
            raise ConfigError(
                "fleet.autoscale_queue_low/high need 0 <= low < high, "
                f"got {cfg.autoscale_queue_low}/"
                f"{cfg.autoscale_queue_high}")
        self.pool = pool
        self.cfg = cfg
        self.floor = int(cfg.min_engines)
        self.ceiling = int(ceiling)
        self.dir = workdir or cfg.dir
        self.registry = registry
        self._obs = obs
        self._clock = clock
        self._last_step = clock()
        #: Monotonic stamp of the last APPLIED scaling (the cooldown
        #: anchor); 0 = never scaled, first decision is free.
        self._last_applied = 0.0
        self.decisions = 0
        self._last_decision: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- thread plumbing ----------------------------------------------

    def start(self) -> "EngineAutoscaler":
        """Run :meth:`step` every ``autoscale_interval_s`` on a daemon
        thread (the wait rides the stop event — no bare sleeps)."""
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.autoscale_interval_s):
            try:
                self.step()
            except Exception:   # noqa: BLE001 — an autoscaler fault must
                # degrade to "membership stops adapting", never kill the
                # fleet.
                log.exception("fleet autoscale step failed; holding "
                              "current membership")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)

    # -- the control loop ---------------------------------------------

    @staticmethod
    def _row_signals(row: dict) -> tuple[float, float, float]:
        """(burn, per-engine queue depth, overload) of one history row;
        a missing key reads as quiet — absence of a gauge is absence of
        the signal, never an error (the ring contract)."""
        burn = float(row.get("fleet_slo_availability_burn", 0.0) or 0.0)
        engines = max(1.0, float(row.get("fleet_engines_live", 1.0)
                                 or 1.0))
        depth = float(row.get("fleet_queue_depth", 0.0) or 0.0) / engines
        overload = float(row.get("fleet_overload", 0.0) or 0.0)
        return burn, depth, overload

    def decide(self, rows: list[dict], current: int
               ) -> tuple[int, str] | None:
        """The pure state machine: ``(new_target, reason)`` or None
        (hold). ``rows`` oldest-first (the ``read_history`` order),
        ``current`` the pool's present target."""
        cfg = self.cfg
        win = cfg.autoscale_window
        if len(rows) >= win:
            recent = rows[-win:]
            sig = [self._row_signals(r) for r in recent]
            mean_burn = sum(s[0] for s in sig) / win
            mean_depth = sum(s[1] for s in sig) / win
            overloaded = sum(s[2] > 0 for s in sig)
            if current < self.ceiling:
                if mean_burn >= cfg.autoscale_burn_high:
                    return (current + 1,
                            f"availability burn {mean_burn:.2f} >= "
                            f"{cfg.autoscale_burn_high:g} over {win} polls")
                if mean_depth >= cfg.autoscale_queue_high:
                    return (current + 1,
                            f"queue depth {mean_depth:.1f}/engine >= "
                            f"{cfg.autoscale_queue_high:g} over {win} "
                            f"polls")
                if 2 * overloaded >= win:
                    return (current + 1,
                            f"overload on {overloaded}/{win} polls")
        # Scale-down hysteresis: a 2x-longer window, EVERY row quiet.
        quiet_win = 2 * win
        if current > self.floor and len(rows) >= quiet_win:
            quiet = True
            for row in rows[-quiet_win:]:
                burn, depth, overload = self._row_signals(row)
                if (burn >= cfg.autoscale_burn_low
                        or depth >= cfg.autoscale_queue_low
                        or overload > 0):
                    quiet = False
                    break
            if quiet:
                return (current - 1,
                        f"quiet {quiet_win} polls (burn < "
                        f"{cfg.autoscale_burn_low:g}, queue < "
                        f"{cfg.autoscale_queue_low:g}/engine, no "
                        f"overload)")
        return None                 # dead band (or at the bounds): hold

    def read_rows(self) -> list[dict]:
        """The decision window's history rows (oldest-first) out of the
        router's ring; missing file = no evidence = empty."""
        path = os.path.join(self.dir, FLEET_HISTORY_FILE)
        return read_history(path, last_n=2 * self.cfg.autoscale_window)

    def step(self, now: float | None = None,
             rows: list[dict] | None = None) -> ScaleDecision | None:
        """One autoscaler tick: read the ring, decide, actuate.
        Rate-limited by ``autoscale_interval_s`` between reads and
        ``autoscale_cooldown_s`` between APPLIED scalings. Returns the
        applied :class:`ScaleDecision` or None."""
        now = self._clock() if now is None else now
        if now - self._last_step < self.cfg.autoscale_interval_s:
            return None
        self._last_step = now
        if rows is None:
            rows = self.read_rows()
        current = int(self.pool.target)
        actual = int(self.pool.live_count())
        decision = self.decide(rows, current)
        applied: ScaleDecision | None = None
        if decision is not None:
            target, reason = decision
            in_cooldown = (self._last_applied > 0.0
                           and now - self._last_applied
                           < self.cfg.autoscale_cooldown_s)
            if not in_cooldown:
                action = "up" if target > current else "down"
                self.pool.scale(target)
                self._last_applied = now
                self.decisions += 1
                applied = ScaleDecision(action=action, target=target,
                                        reason=reason)
                self._last_decision = {
                    "ts": time.time(), "action": action,
                    "from": current, "to": target, "reason": reason}
                log.info("fleet autoscale %s: %d -> %d engines (%s)",
                         action, current, target, reason)
                if self.registry is not None:
                    self.registry.inc(f"fleet_autoscale_{action}_total")
                if self._obs is not None:
                    self._obs.record("fleet_autoscale", action=action,
                                     engines_from=current,
                                     engines_to=target, reason=reason)
                current = target
        if self.registry is not None:
            self.registry.record_many({
                "fleet_autoscale_target": float(current),
                "fleet_autoscale_actual": float(actual)})
        self._write_state(current, actual)
        return applied

    def _write_state(self, target: int, actual: int) -> None:
        """Atomically rewrite the autoscaler state file (cli obs's
        source for the autoscaler half of the "sessions" section)."""
        if not self.dir:
            return
        path = os.path.join(self.dir, AUTOSCALE_STATE_FILE)
        tmp = f"{path}.tmp-{os.getpid()}"
        state = {
            "ts": time.time(), "target": target, "actual": actual,
            "floor": self.floor, "ceiling": self.ceiling,
            "decisions": self.decisions,
            "last_decision": self._last_decision or None,
        }
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(state, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            log.exception("fleet autoscale state write failed")
