"""Selector-driven wire engine: the fleet's event-loop backend.

One thread, one ``selectors`` loop (epoll/kqueue via DefaultSelector),
thousands of keep-alive connections, ZERO threads per in-flight request
— the data path that breaks the ThreadingHTTPServer ceiling (~1.3-1.8k
req/s/process, GIL convoy past ~2 dozen blocking wire threads; see
BASELINE.md "Fleet serving"). All HTTP framing lives in the sans-IO
fleet/proto.py; this module owns only the I/O mechanics:

- :class:`EventLoop` — a minimal reactor: non-blocking sockets under a
  DefaultSelector, a socketpair waker for cross-thread ``post()``, and
  a heapq deadline wheel (``call_later``) for request budgets.
- :class:`EvloopFrontend` — same surface as the threaded front-end
  (``start``/``drain``/``stop``, ``host``/``port``, the wire status
  table) over the same two-method backend contract, plus two
  non-blocking dispatch modes: a backend with ``submit_async`` (the
  local :class:`~sharetrade_tpu.fleet.frontend.EngineBackend`) parks
  the request on the engine's own completion callback; a backend with
  ``proxy_request`` (the router) runs the byte-level relay below. Any
  other backend's ``serve_request`` is called inline on the loop — fine
  for cheap/test backends, documented as loop-blocking.
- :class:`_RelayEngine` — the router's thin proxy hop as a state
  machine: per-endpoint keep-alive upstream pools, non-blocking
  connects, per-attempt deadline timers, the torn-keep-alive fresh
  retry, and migration-to-a-survivor — driving the exact bookkeeping
  helpers ``FleetRouter.proxy_request`` uses, so both backends share
  one definition of the relay semantics.

Backpressure: writes are optimistic (one ``send`` on the hot path);
leftovers buffer and register EVENT_WRITE, and a connection whose
outbound buffer passes the high-water mark stops reading until it
drains — a stalled client throttles only its own connection.

fleet-net-ok: this module is the fleet's network layer, evloop flavor —
lint check 14 allows its listener; lint check 15 holds it to the
non-blocking discipline (no sendall/settimeout/sleep, no per-connection
threads — the ONE loop-runner thread carries the evloop-block-ok mark).
"""

from __future__ import annotations

import errno
import json
import selectors
import socket
import threading
import time
from collections import deque
from heapq import heappop, heappush

from sharetrade_tpu.fleet import proto, wire
from sharetrade_tpu.fleet.router import UNROUTED_DETAIL
from sharetrade_tpu.obs.exporter import render_prom_text
from sharetrade_tpu.serve.engine import ServeEngineFailed
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("fleet.evloop")

_READ = selectors.EVENT_READ
_WRITE = selectors.EVENT_WRITE
_RECV_SIZE = 1 << 16
#: Pause reads on a connection once this many reply bytes are queued.
_HIGH_WATER = 1 << 18


class _Timer:
    """One deadline-wheel entry; ``cancel()`` is lazy (the heap entry
    stays, the callback is dropped)."""

    __slots__ = ("when", "fn")

    def cancel(self) -> None:
        self.fn = None


class EventLoop:
    """A minimal single-thread reactor. Everything except ``post`` and
    ``stop`` must run ON the loop thread (``call_later`` included — the
    timer heap is unlocked by design)."""

    def __init__(self) -> None:
        self._sel = selectors.DefaultSelector()
        r, w = socket.socketpair()
        r.setblocking(False)
        w.setblocking(False)
        self._waker_r, self._waker_w = r, w
        self._sel.register(r, _READ, self._drain_waker)
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._timers: list = []
        self._seq = 0
        self._running = False
        self.stopped = threading.Event()

    # -- cross-thread surface ------------------------------------------

    def post(self, fn) -> None:
        """Enqueue ``fn`` to run on the loop thread; safe from any
        thread (the engine's consumer callback, drain/stop callers)."""
        with self._lock:
            self._pending.append(fn)
        try:
            self._waker_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass    # waker pipe full (wakeup already pending) or closed

    def stop(self) -> None:
        def _halt() -> None:
            self._running = False
        self.post(_halt)

    # -- loop-thread surface -------------------------------------------

    def call_later(self, delay_s: float, fn) -> _Timer:
        timer = _Timer()
        timer.when = time.monotonic() + delay_s
        timer.fn = fn
        self._seq += 1
        heappush(self._timers, (timer.when, self._seq, timer))
        return timer

    def add(self, sock, mask: int, cb) -> None:
        self._sel.register(sock, mask, cb)

    def set_mask(self, sock, mask: int, cb) -> None:
        self._sel.modify(sock, mask, cb)

    def remove(self, sock) -> None:
        self._sel.unregister(sock)

    def run(self) -> None:
        self._running = True
        try:
            while self._running:
                timeout = None
                if self._timers:
                    timeout = max(0.0,
                                  self._timers[0][0] - time.monotonic())
                with self._lock:
                    if self._pending:
                        timeout = 0.0
                for key, mask in self._sel.select(timeout):
                    try:
                        key.data(mask)
                    except Exception:   # noqa: BLE001 — one connection's
                        log.exception("evloop handler failed")  # fault
                self._run_pending()
                self._run_timers()
        finally:
            self.stopped.set()

    def close(self) -> None:
        try:
            self._sel.close()
        except OSError:
            pass
        for sock in (self._waker_r, self._waker_w):
            try:
                sock.close()
            except OSError:
                pass

    def _drain_waker(self, mask: int) -> None:
        try:
            while self._waker_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass

    def _run_pending(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    return
                fn = self._pending.popleft()
            try:
                fn()
            except Exception:   # noqa: BLE001
                log.exception("evloop posted callback failed")

    def _run_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, timer = heappop(self._timers)
            fn, timer.fn = timer.fn, None
            if fn is None:
                continue
            try:
                fn()
            except Exception:   # noqa: BLE001
                log.exception("evloop timer failed")


class _Conn:
    """One buffered non-blocking socket under the loop: optimistic
    writes, EVENT_WRITE on leftovers, read pause past high water."""

    def __init__(self, loop: EventLoop, sock) -> None:
        self.loop = loop
        self.sock = sock
        self.out = bytearray()
        self.closed = False
        self.close_after_flush = False
        self._mask = 0
        self._reads_paused = False

    def register(self, mask: int) -> None:
        self._mask = mask
        self.loop.add(self.sock, mask, self._on_event)

    def write(self, data: bytes) -> None:
        if self.closed:
            return
        if not self.out:
            try:
                n = self.sock.send(data)
            except (BlockingIOError, InterruptedError):
                n = 0
            except OSError as exc:
                self.on_error(exc)
                return
            if n < len(data):
                self.out += memoryview(data)[n:]
        else:
            self.out += data
        if len(self.out) > _HIGH_WATER and not self._reads_paused:
            self._reads_paused = True
            self.on_paused()
        if self.close_after_flush and not self.out:
            self.close()
            return
        self._sync_mask()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.loop.remove(self.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.on_closed()

    def _sync_mask(self) -> None:
        if self.closed:
            return
        want = 0 if self._reads_paused else _READ
        if self.out:
            want |= _WRITE
        if want == 0:       # selectors refuse an empty mask; a fully
            want = _READ    # stalled conn still watches for EOF/reset
        if want != self._mask:
            self._mask = want
            self.loop.set_mask(self.sock, want, self._on_event)

    def _on_event(self, mask: int) -> None:
        if mask & _WRITE:
            self._on_writable()
        if not self.closed and mask & _READ:
            self._on_readable()

    def _on_writable(self) -> None:
        try:
            while self.out:
                n = self.sock.send(self.out)
                if n <= 0:
                    break
                del self.out[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as exc:
            self.on_error(exc)
            return
        if not self.out:
            if self.close_after_flush:
                self.close()
                return
            self._reads_paused = False
        self._sync_mask()

    def _on_readable(self) -> None:
        try:
            while True:
                chunk = self.sock.recv(_RECV_SIZE)
                if not chunk:
                    self.on_eof()
                    return
                self.on_bytes(chunk)
                if (self.closed or self._reads_paused
                        or len(chunk) < _RECV_SIZE):
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as exc:
            self.on_error(exc)
            return
        self._sync_mask()

    # subclass surface -------------------------------------------------

    def on_bytes(self, data: bytes) -> None:
        raise NotImplementedError

    def on_eof(self) -> None:
        self.close()

    def on_error(self, exc: OSError) -> None:
        self.close()

    def on_closed(self) -> None:
        pass

    def on_paused(self) -> None:
        """Reads just paused past high water (backpressure) — the
        False→True transition only, so subclasses can count pauses
        rather than bytes-over-water polls."""


class _ServerConn(_Conn):
    """One downstream (client-facing) connection: requests parse off
    the byte stream and process ONE AT A TIME per connection (pipelined
    requests queue — HTTP/1.1 responses must return in request order),
    while distinct connections progress concurrently."""

    def __init__(self, fe: "EvloopFrontend", sock) -> None:
        super().__init__(fe.loop, sock)
        self.fe = fe
        self.parser = proto.RequestParser()
        self.pending: deque = deque()
        self.busy = False
        self.tracked = False        # current request counts in-flight
        self.cur_keep_alive = True
        self.cur_tctx = None        # current request's trace context
        self._pumping = False

    def on_bytes(self, data: bytes) -> None:
        try:
            events = self.parser.feed(data)
        except proto.ProtocolError as exc:
            # Unrecoverable framing: one loud reply, then close — the
            # byte stream has no next-message boundary to resync on.
            body = json.dumps({"error": "bad_request",
                               "detail": exc.detail}).encode()
            self._reads_paused = True
            self.close_after_flush = True
            self.write(proto.render_response(exc.status, body,
                                             keep_alive=False))
            return
        if events:
            self.pending.extend(events)
            self.pump()

    def pump(self) -> None:
        if self._pumping:
            return              # re-entered from a synchronous reply
        self._pumping = True
        try:
            while (not self.busy and self.pending and not self.closed
                   and not self.close_after_flush):
                request = self.pending.popleft()
                self.busy = True
                self.cur_keep_alive = request.keep_alive
                self.fe.process(self, request)
        finally:
            self._pumping = False

    def on_paused(self) -> None:
        self.fe.note_backpressure()

    def on_closed(self) -> None:
        self.fe.conns.discard(self)
        self.fe.record_open_conns()
        if self.tracked:
            # The client hung up with its request still in flight: the
            # backend call completes into a dead conn, but the in-flight
            # count must not leak past it (drain would wedge).
            self.tracked = False
            self.fe.request_done()


class _EngineCall:
    """One request parked on the local engine's completion callback —
    the evloop replacement for a handler thread's ``handle.wait``."""

    __slots__ = ("fe", "conn", "handle", "timer", "timeout_s", "tctx",
                 "done")

    def __init__(self, fe: "EvloopFrontend", conn: _ServerConn,
                 timeout_s: float) -> None:
        self.fe = fe
        self.conn = conn
        self.handle = None
        self.timer = None
        self.timeout_s = timeout_s
        self.tctx = None
        self.done = False

    def signal(self) -> None:
        """The engine's completion callback — fires on the engine's
        consumer thread; hop back onto the loop."""
        self.fe.loop.post(self.finish)

    def finish(self) -> None:
        if self.done:
            return
        self.done = True
        if self.timer is not None:
            self.timer.cancel()
        if self.tctx is not None:
            # The async twin of serve_request's completion spans — on
            # the loop thread, but a bounded tuple append (lint 16).
            self.fe.backend.trace_complete(self.tctx, self.handle)
        result = self.handle.result
        if result is None:
            error = self.handle.error
            if error is None:   # raced the budget timer's semantics
                error = ServeEngineFailed(
                    f"request did not complete within the front-end "
                    f"budget ({self.timeout_s:.1f}s)")
            self.fe.reply_error(self.conn, error)
            return
        self.fe.reply(self.conn, wire.STATUS_OK,
                      self.fe.backend.result_dict(result))

    def on_timeout(self) -> None:
        """The front-end budget: a wedged engine surfaces as a loud 503
        instead of an immortal parked request."""
        if self.done:
            return
        self.done = True
        self.fe.note_deadline_expiry()
        self.fe.reply_error(self.conn, ServeEngineFailed(
            f"request did not complete within the front-end budget "
            f"({self.timeout_s:.1f}s)"))


class EvloopFrontend:
    """Event-loop wire front-end — the threaded front-end's surface
    (module docstring) with no thread per connection or request."""

    def __init__(self, backend, registry, *, host: str = "127.0.0.1",
                 port: int = 0, tracer=None) -> None:
        self.backend = backend
        self.registry = registry
        #: Optional :class:`~sharetrade_tpu.fleet.wire.WireTracer` —
        #: None (the default) means zero trace parsing and zero spans.
        self.tracer = tracer
        self.draining = False
        self.loop = EventLoop()
        # fleet-net-ok: the fleet's one listener, evloop flavor.
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(1024)
        lsock.setblocking(False)
        self._lsock = lsock
        self.host, self.port = lsock.getsockname()[:2]
        self.conns: set = set()
        self._inflight = 0
        self._drain_waiters: list = []
        self._thread: threading.Thread | None = None
        if getattr(backend, "proxy_request", None) is not None:
            # The router: its relay runs natively on the loop, driving
            # the same FleetRouter bookkeeping the blocking path uses.
            self._relay = _RelayEngine(self, backend)
        else:
            self._relay = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "EvloopFrontend":
        # Selector internals land in /metrics (ISSUE 19): which parse
        # path is live, how many keep-alive conns are open, and the
        # counters note_backpressure/note_deadline_expiry bump — the
        # loop thread stops being a black box.
        self.registry.record(
            "fleet_proto_backend_native",
            1.0 if proto.proto_backend == "native" else 0.0)
        self.record_open_conns()
        self.loop.add(self._lsock, _READ, self._on_accept)
        # Every connection and request multiplexes onto this single
        # selector thread, never a thread per connection:
        # evloop-block-ok — the ONE loop-runner thread.
        self._thread = threading.Thread(target=self.loop.run,
                                        name="fleet-evloop", daemon=True)
        self._thread.start()
        log.info("evloop front-end listening on %s:%d",
                 self.host, self.port)
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop accepting, finish in-flight requests; False on timeout.
        New requests on surviving keep-alive connections get the loud
        503 draining refusal, same as the threaded backend."""
        done = threading.Event()

        def _begin_drain() -> None:
            self.draining = True
            self._close_listener()
            self._drain_waiters.append(done)
            self._check_drained()

        if self._thread is None:
            _begin_drain()
            return True
        self.loop.post(_begin_drain)
        return done.wait(timeout_s)

    def stop(self, timeout_s: float = 10.0) -> None:
        if self._thread is None:
            self._close_listener()
            self.loop.close()
            return

        def _shutdown() -> None:
            self.draining = True
            self._close_listener()
            for conn in list(self.conns):
                conn.close()
            if self._relay is not None:
                self._relay.close_all()
            self.loop.stop()

        self.loop.post(_shutdown)
        if self.loop.stopped.wait(timeout_s):
            self.loop.close()
        self._thread.join(timeout_s)
        self._thread = None

    def _close_listener(self) -> None:
        if self._lsock is None:
            return
        try:
            self.loop.remove(self._lsock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        self._lsock = None

    def _on_accept(self, mask: int) -> None:
        while True:
            try:
                sock, _ = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return          # listener closed under us (drain)
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _ServerConn(self, sock)
            self.conns.add(conn)
            self.record_open_conns()
            conn.register(_READ)

    # -- selector observability ----------------------------------------

    def record_open_conns(self) -> None:
        self.registry.record("fleet_evloop_open_conns",
                             float(len(self.conns)))

    def note_backpressure(self) -> None:
        self.registry.inc("fleet_evloop_backpressure_pauses_total")

    def note_deadline_expiry(self) -> None:
        self.registry.inc("fleet_evloop_deadline_expiries_total")

    # -- in-flight accounting (loop thread only) -----------------------

    def request_begin(self, conn: _ServerConn) -> None:
        self._inflight += 1
        conn.tracked = True

    def request_done(self) -> None:
        self._inflight -= 1
        self._check_drained()

    def _check_drained(self) -> None:
        if self.draining and self._inflight <= 0 and self._drain_waiters:
            for waiter in self._drain_waiters:
                waiter.set()
            del self._drain_waiters[:]

    # -- request processing --------------------------------------------

    def process(self, conn: _ServerConn, request: proto.Request) -> None:
        # The body is already consumed — the parser only emits complete
        # messages, so an early 404/503 can never poison the keep-alive.
        if request.method == "GET":
            self._do_get(conn, request)
            return
        if request.target != wire.SUBMIT_PATH:
            self.reply(conn, 404, {"error": "not_found"})
            return
        if self.draining:
            self.reply(conn, wire.STATUS_UNAVAILABLE,
                       {"error": "engine_failed",
                        "detail": "front-end is draining"})
            return
        self.request_begin(conn)
        tctx = (self.tracer.begin(request.headers)
                if self.tracer is not None else None)
        conn.cur_tctx = tctx
        deadline_raw = request.headers.get("x-deadline-ms")
        clock_raw = request.headers.get("x-session-clock")
        if self._relay is not None:
            # The relay re-derives the clock from the router's own
            # affinity table per hop — an inbound header is not trusted.
            self._relay.start(conn, request.body, deadline_raw, tctx)
        elif getattr(self.backend, "submit_async", None) is not None:
            self._dispatch_engine(conn, request.body, deadline_raw,
                                  clock_raw, tctx)
        else:
            self._dispatch_inline(conn, request.body, deadline_raw,
                                  clock_raw, tctx)

    def _do_get(self, conn: _ServerConn, request: proto.Request) -> None:
        if request.target == wire.HEALTH_PATH:
            try:
                body = self.backend.health()
            except Exception as exc:    # noqa: BLE001
                self.reply(conn, wire.STATUS_UNAVAILABLE,
                           {"ok": False, "detail": repr(exc)})
                return
            body["draining"] = self.draining
            self.reply(conn, wire.STATUS_OK, body)
        elif request.target == wire.METRICS_PATH:
            reg = self.registry
            text = render_prom_text(reg.snapshot(), reg.counters(),
                                    reg.histograms())
            self.reply(conn, wire.STATUS_OK, text.encode(),
                       content_type="text/plain; version=0.0.4")
        else:
            self.reply(conn, 404, {"error": "not_found"})

    def _parse_submit(self, conn: _ServerConn, raw: bytes,
                      deadline_raw: str | None,
                      clock_raw: str | None = None):
        """Shared JSON/deadline/clock validation for the non-proxy
        paths; None means the 400 already went out."""
        try:
            payload = json.loads(raw)
            session = payload["session"]
            obs = payload["obs"]
        except (ValueError, KeyError, TypeError) as exc:
            self.reply_error(conn, ValueError(
                f"malformed submit body: {exc!r}"), counted=False)
            return None
        deadline_ms = None
        if deadline_raw is not None:
            try:
                deadline_ms = float(deadline_raw)
            except ValueError:
                self.reply_error(conn, ValueError(
                    f"malformed {wire.DEADLINE_HEADER}: "
                    f"{deadline_raw!r}"), counted=False)
                return None
        clock = None
        if clock_raw is not None and getattr(self.backend, "wire_clocked",
                                             False):
            try:
                clock = int(clock_raw) or None
            except ValueError:
                self.reply_error(conn, ValueError(
                    f"malformed {wire.CLOCK_HEADER}: "
                    f"{clock_raw!r}"), counted=False)
                return None
        return session, obs, deadline_ms, clock

    def _dispatch_engine(self, conn: _ServerConn, raw: bytes,
                         deadline_raw: str | None,
                         clock_raw: str | None = None, tctx=None) -> None:
        parsed = self._parse_submit(conn, raw, deadline_raw, clock_raw)
        if parsed is None:
            return
        session, obs, deadline_ms, clock = parsed
        self.registry.inc("frontend_requests_total")
        timeout_s = (max(float(deadline_ms) / 1e3 * 4, 5.0)
                     if deadline_ms else self.backend.request_timeout_s)
        traced = (tctx is not None
                  and getattr(self.backend, "wire_traced", False))
        call = _EngineCall(self, conn, timeout_s)
        call.tctx = tctx if traced else None
        kwargs = {"clock": clock} if clock is not None else {}
        try:
            call.handle = (self.backend.submit_async(
                session, obs, deadline_ms, call.signal, tctx=tctx,
                **kwargs)
                if traced else self.backend.submit_async(
                    session, obs, deadline_ms, call.signal, **kwargs))
        except Exception as exc:    # noqa: BLE001 — every serving
            # outcome maps to a wire status; the loop never dies.
            self.reply_error(conn, exc)
            return
        call.timer = self.loop.call_later(timeout_s, call.on_timeout)

    def _dispatch_inline(self, conn: _ServerConn, raw: bytes,
                         deadline_raw: str | None,
                         clock_raw: str | None = None, tctx=None) -> None:
        parsed = self._parse_submit(conn, raw, deadline_raw, clock_raw)
        if parsed is None:
            return
        session, obs, deadline_ms, clock = parsed
        self.registry.inc("frontend_requests_total")
        traced = (tctx is not None
                  and getattr(self.backend, "wire_traced", False))
        kwargs = {"clock": clock} if clock is not None else {}
        try:
            result = (self.backend.serve_request(session, obs,
                                                 deadline_ms, tctx=tctx,
                                                 **kwargs)
                      if traced else
                      self.backend.serve_request(session, obs,
                                                 deadline_ms, **kwargs))
        except Exception as exc:    # noqa: BLE001
            self.reply_error(conn, exc)
            return
        self.reply(conn, wire.STATUS_OK, result)

    # -- replies -------------------------------------------------------

    def reply(self, conn: _ServerConn, status: int, body,
              content_type: str = "application/json") -> None:
        tctx, conn.cur_tctx = conn.cur_tctx, None
        if tctx is not None:
            # The hop span closes when the reply is handed to the conn
            # buffer — a bounded tuple append (lint 16), never a dump.
            self.tracer.finish(tctx, "frontend", note=str(status))
        if conn.tracked:
            conn.tracked = False
            self.request_done()
        conn.busy = False
        if conn.closed:
            return              # client hung up mid-request
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        conn.write(proto.render_response(status, payload, content_type))
        if not conn.cur_keep_alive:
            conn.close_after_flush = True
            if not conn.out:
                conn.close()
            return
        conn.pump()

    def reply_error(self, conn: _ServerConn, exc: BaseException, *,
                    counted: bool = True) -> None:
        status, body = wire.error_to_status(exc)
        if status == 500:
            log.error("front-end request failed internally: %r", exc)
        if counted:
            self.registry.inc("frontend_errors_total")
        self.reply(conn, status, body)


class _UpstreamConn(_Conn):
    """One keep-alive connection from the relay to an engine: at most
    one request in flight (matching the blocking FleetClient), pooled
    per endpoint between requests."""

    def __init__(self, relay: "_RelayEngine", sock,
                 endpoint: tuple) -> None:
        super().__init__(relay.fe.loop, sock)
        self.relay = relay
        self.endpoint = endpoint
        self.parser = proto.ResponseParser()
        self.call = None
        self.connecting = False

    def on_paused(self) -> None:
        self.relay.fe.note_backpressure()

    def bind(self, call: "_RelayCall") -> None:
        self.call = call

    def _on_event(self, mask: int) -> None:
        if self.connecting:
            err = self.sock.getsockopt(socket.SOL_SOCKET,
                                       socket.SO_ERROR)
            if err:
                self.fail(f"connect failed: "
                          f"{errno.errorcode.get(err, err)}")
                return
            self.connecting = False
            call, self._mask = self.call, _READ
            self.loop.set_mask(self.sock, _READ, self._on_event)
            if call is not None:
                call.on_connected(self)
            return
        super()._on_event(mask)

    def on_bytes(self, data: bytes) -> None:
        try:
            events = self.parser.feed(data)
        except proto.ProtocolError as exc:
            self.fail(f"malformed upstream response: {exc.detail}")
            return
        if not events:
            return
        call, self.call = self.call, None
        if call is None:
            # Unsolicited bytes on an idle pooled connection: the
            # engine violated request/response pairing — discard it.
            self.close()
            return
        if len(events) > 1 or self.parser.pending_bytes():
            self.close()        # over-delivery: never pool this stream
        else:
            self.relay.checkin(self)
        call.on_response(events[0])

    def on_eof(self) -> None:
        self.fail("connection closed mid-response")

    def on_error(self, exc: OSError) -> None:
        self.fail(repr(exc))

    def fail(self, why: str) -> None:
        call, self.call = self.call, None
        self.close()
        if call is not None:
            call.on_conn_failed(self, why)


class _RelayCall:
    """One client request traversing the relay: hop to a routed engine,
    ONE fresh-connection retry on a torn keep-alive (the FleetClient
    contract — a failure on a fresh connection is the peer's true
    state), then migration to a survivor on engine loss or 503.

    Trace spans (when the request carries context and the router has a
    span sink): one ``relay`` envelope for the whole traversal, plus one
    ``relay_attempt`` child PER upstream attempt — its note names why
    the attempt was made (``first`` / ``retry:<why>`` /
    ``migrate:<why>``) and its span id rides to the engine as
    ``X-Parent-Span``, so a SIGKILLed engine's eagerly-flushed
    ``engine_recv`` still parents under a span the surviving router
    journals. All emission is bounded tuple appends (lint 16)."""

    __slots__ = ("relay", "router", "conn", "session", "body",
                 "deadline_raw", "timeout_s", "tried", "migrated",
                 "engine_id", "endpoint", "up", "timer", "reused",
                 "fresh_retry_used", "done", "tctx", "relay_span", "t0",
                 "attempt_span", "attempt_t0", "next_note")

    def __init__(self, relay: "_RelayEngine", conn: _ServerConn,
                 session: str, body: bytes,
                 deadline_raw: str | None, tctx=None) -> None:
        self.relay = relay
        self.router = relay.router
        self.conn = conn
        self.session = session
        self.body = body
        self.deadline_raw = deadline_raw
        self.timeout_s = relay.router.relay_timeout_s(deadline_raw)
        self.tried: set = set()
        self.migrated = False
        self.engine_id = None
        self.up = None
        self.timer = None
        self.reused = False
        self.fresh_retry_used = False
        self.done = False
        spans = getattr(relay.router, "spans", None)
        self.tctx = tctx if spans is not None else None
        if self.tctx is not None:
            self.relay_span = spans.new_span_id()
            self.t0 = time.perf_counter()
        else:
            self.relay_span = ""
            self.t0 = 0.0
        self.attempt_span = ""
        self.attempt_t0 = 0.0
        self.next_note = "first"

    # -- trace spans ---------------------------------------------------

    def _begin_attempt(self) -> None:
        if self.tctx is None:
            return
        self.attempt_span = self.router.spans.new_span_id()
        self.attempt_t0 = time.perf_counter()

    def _end_attempt(self, outcome: str = "") -> None:
        if self.tctx is None or not self.attempt_span:
            return
        note = (f"{self.next_note} {outcome}".strip()
                if outcome else self.next_note)
        self.router.spans.span(
            self.tctx[0], self.attempt_span, self.relay_span,
            "relay_attempt", self.attempt_t0, time.perf_counter(), note)
        self.attempt_span = ""

    # -- hop lifecycle -------------------------------------------------

    def next_hop(self) -> None:
        choice = self.router._route(self.session, exclude=self.tried)
        if choice is None:
            self.router.note_unrouted()
            status, body = wire.error_to_status(
                ServeEngineFailed(UNROUTED_DETAIL))
            self.finish(status, json.dumps(body).encode())
            return
        self.engine_id, self.endpoint = choice
        self.router.note_sent(self.engine_id)
        self.reused = False
        self.fresh_retry_used = False
        self._begin_attempt()
        self._attempt()

    def _attempt(self) -> None:
        self._arm_timer()
        up = self.relay.checkout(self.endpoint)
        if up is not None:
            self.reused = True
            up.bind(self)
            self.up = up
            self._send(up)
        else:
            self.up = self.relay.connect(self.endpoint, self)

    def _send(self, up: _UpstreamConn) -> None:
        headers = {}
        if self.deadline_raw is not None:
            headers[wire.DEADLINE_HEADER] = self.deadline_raw
        clock = self.router.session_clock(self.session)
        if clock > 0:
            # The router-observed session clock rides every hop so an
            # adopting engine can validate a spill record's step stamp
            # (the same header the blocking proxy path sends).
            headers[wire.CLOCK_HEADER] = str(clock)
        if self.attempt_span:
            # This attempt's span id is the downstream parent — each
            # retry/migration hands the engine a fresh parent.
            headers[proto.TRACE_HEADER] = self.tctx[0]
            headers[proto.PARENT_HEADER] = self.attempt_span
        up.write(proto.render_request(
            "POST", wire.SUBMIT_PATH,
            f"{self.endpoint[0]}:{self.endpoint[1]}", self.body,
            headers=headers or None))

    def _arm_timer(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
        self.timer = self.relay.fe.loop.call_later(self.timeout_s,
                                                   self.on_timeout)

    def _disarm_timer(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None

    # -- upstream events -----------------------------------------------

    def on_connected(self, up: _UpstreamConn) -> None:
        if self.done or up is not self.up:
            up.call = None      # stale attempt (we timed out and moved
            up.close()          # on): never send on it
            return
        self._send(up)

    def on_conn_failed(self, up, why: str) -> None:
        if self.done or up is not self.up:
            return              # a stale attempt's verdict, not ours
        self.up = None
        self._end_attempt(why)
        if self.reused and not self.fresh_retry_used:
            # Torn keep-alive (the engine restarted, an idle timeout):
            # ONE retry on a fresh connection to the SAME engine.
            self.fresh_retry_used = True
            self.reused = False
            self.next_note = f"retry:{why}"
            self._begin_attempt()
            self._arm_timer()
            self.up = self.relay.connect(self.endpoint, self)
            return
        self._engine_gone(why)

    def on_timeout(self) -> None:
        if self.done:
            return
        self.relay.fe.note_deadline_expiry()
        up, self.up = self.up, None
        if up is not None:
            up.call = None
            up.close()
        why = f"timeout after {self.timeout_s:.1f}s"
        self._end_attempt(why)
        # Mirror the blocking path: a per-attempt timeout is a
        # transport error — fresh retry if the conn was reused, else
        # this engine is gone.
        if self.reused and not self.fresh_retry_used:
            self.fresh_retry_used = True
            self.reused = False
            self.next_note = f"retry:{why}"
            self._begin_attempt()
            self._arm_timer()
            self.up = self.relay.connect(self.endpoint, self)
            return
        self._engine_gone(why)

    def on_response(self, response: proto.Response) -> None:
        if self.done:
            return
        self.up = None
        self.router.note_done(self.engine_id)
        if response.status == wire.STATUS_UNAVAILABLE:
            self._disarm_timer()
            self._end_attempt(f"status {response.status}")
            self.tried.add(self.engine_id)
            self.migrated = True
            self.next_note = f"migrate:status {response.status}"
            self.router.note_engine_gone(
                self.session, self.engine_id,
                f"status {response.status}")
            self.next_hop()
            return
        self._disarm_timer()
        self._end_attempt(f"status {response.status}")
        status, reply = self.router.finish_relay(
            self.session, self.engine_id, self.migrated,
            response.status, response.body)
        self.finish(status, reply)

    def _engine_gone(self, why: str) -> None:
        self._disarm_timer()
        self.router.note_done(self.engine_id)
        self.tried.add(self.engine_id)
        self.migrated = True
        self.next_note = f"migrate:{why}"
        self.router.note_engine_gone(self.session, self.engine_id, why)
        self.next_hop()

    def finish(self, status: int, reply: bytes) -> None:
        self.done = True
        self._disarm_timer()
        self._end_attempt(f"status {status}")
        if self.tctx is not None:
            tctx = self.tctx
            self.router.spans.span(
                tctx[0], self.relay_span, tctx[2] or tctx[1], "relay",
                self.t0, time.perf_counter(),
                "migrated" if self.migrated else "")
        self.relay.fe.reply(self.conn, status, reply)


class _RelayEngine:
    """The router's data path on the loop (class docstring above)."""

    def __init__(self, fe: EvloopFrontend, router) -> None:
        self.fe = fe
        self.router = router
        self._pools: dict = {}      # endpoint -> deque of idle conns

    def start(self, conn: _ServerConn, body: bytes,
              deadline_raw: str | None, tctx=None) -> None:
        self.router.registry.inc("fleet_requests_total")
        try:
            session = wire.extract_session(body)
        except ValueError as exc:
            self.fe.reply_error(conn, exc, counted=False)
            return
        _RelayCall(self, conn, session, body, deadline_raw,
                   tctx).next_hop()

    # -- connection pool -----------------------------------------------

    def checkout(self, endpoint: tuple) -> _UpstreamConn | None:
        pool = self._pools.get(endpoint)
        while pool:
            up = pool.pop()
            if not up.closed:
                return up
        return None

    def checkin(self, up: _UpstreamConn) -> None:
        if up.closed or up.parser.pending_bytes():
            up.close()
            return
        self._pools.setdefault(up.endpoint, deque()).append(up)

    def connect(self, endpoint: tuple,
                call: _RelayCall) -> _UpstreamConn:
        """Begin a non-blocking connect; the verdict arrives as
        ``call.on_connected`` / ``call.on_conn_failed`` — ALWAYS via the
        loop (a synchronous refusal is posted, never re-entered), so the
        caller can record the returned conn as its current attempt
        first."""
        # fleet-net-ok: outbound non-blocking connect, no listener.
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        up = _UpstreamConn(self, sock, endpoint)
        up.bind(call)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            rc = sock.connect_ex(endpoint)
        except OSError as exc:
            rc, why = -1, repr(exc)
        else:
            why = f"connect failed: {errno.errorcode.get(rc, rc)}"
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK,
                      errno.EALREADY):
            up.closed = True
            sock.close()
            self.fe.loop.post(lambda: call.on_conn_failed(up, why))
            return up
        up.connecting = True
        up.register(_WRITE)
        return up

    def close_all(self) -> None:
        for pool in self._pools.values():
            while pool:
                pool.pop().close()
        self._pools.clear()
