"""The fleet wire protocol — one HTTP/1.1 surface, spoken three times.

Every network hop in the fleet speaks the same tiny protocol: the
end-client talks to the ROUTER, the router talks to each ENGINE worker,
and the supervising pool/telemetry pollers scrape both. Keeping it in
one module (paths, headers, the status↔exception mapping, and the
persistent-connection client) is what makes "the router is just another
client of an engine" literally true in the code.

Endpoints (fleet/frontend.py serves them over any ``serve_request``
backend — a local :class:`~sharetrade_tpu.serve.engine.ServeEngine` or
the router's proxy):

- ``POST /v1/submit`` — body ``{"session": str, "obs": [float, ...]}``;
  the per-request deadline travels as the ``X-Deadline-Ms`` header and
  flows INTO ``ServeEngine.submit(deadline_ms=)`` engine-side (the
  router forwards it untouched — deadline enforcement belongs to the
  engine's batch-collection gate, never to a proxy's clock). Response
  200 carries the full :class:`~sharetrade_tpu.serve.engine.ServeResult`
  payload (action/logits/value/params_step/latency_ms/stages) as JSON;
  float64 JSON round-trips the float32 logits exactly, so the serving
  tier's bitwise parity contract survives the wire.
- ``GET /healthz`` — small JSON liveness/telemetry snapshot (queue
  depth, overload, params_step, failed) — the router's routing signal
  and the pool's heartbeat.
- ``GET /metrics`` — the standard Prometheus exposition
  (:func:`~sharetrade_tpu.obs.exporter.render_prom_text` over the live
  registry), histograms included — what the router merges bucket-wise
  for exact fleet-level quantiles.

Status mapping (each distinct serving outcome is a distinct wire
status, so a client — including the router — reconstructs the exact
engine-side exception):

====  ==========================  =======================================
code  exception                   meaning
====  ==========================  =======================================
200   —                           served; body is the result
400   ``ValueError``              malformed request (refused pre-engine)
429   ``ServeRejected``           admission refused / shed (reason in
                                  body: queue_full/shed_oldest/...)
503   ``ServeEngineFailed``       engine terminally failed, stopped,
                                  draining, or (router) no live engines
504   ``ServeDeadlineExceeded``   deadline expired engine-side before a
                                  device batch
====  ==========================  =======================================
"""

from __future__ import annotations

import json
import re
import socket
import time
from http.client import HTTPException

from sharetrade_tpu.fleet import proto
from sharetrade_tpu.obs.trace import new_trace_id
from sharetrade_tpu.serve.engine import (
    ServeDeadlineExceeded,
    ServeEngineFailed,
    ServeRejected,
)

SUBMIT_PATH = "/v1/submit"
HEALTH_PATH = "/healthz"
METRICS_PATH = "/metrics"
DEADLINE_HEADER = "X-Deadline-Ms"
#: The session's completed-response count as the ROUTER has observed it
#: (ISSUE 20): forwarded on every proxy hop so an adopting engine can
#: validate a spill record's step stamp against the session's expected
#: clock — a stale record demotes to cold prefill instead of serving a
#: rolled-back carry.
CLOCK_HEADER = "X-Session-Clock"

STATUS_OK = 200
STATUS_BAD_REQUEST = 400
STATUS_REJECTED = 429
STATUS_UNAVAILABLE = 503
STATUS_DEADLINE = 504

#: Network-layer failures a caller may treat as "this endpoint is gone —
#: reconnect or re-route" (vs a clean protocol-status reply). OSError
#: covers refused/reset/broken-pipe; HTTPException covers the torn
#: keep-alive reads (RemoteDisconnected, BadStatusLine).
TRANSPORT_ERRORS = (OSError, HTTPException)


def error_to_status(exc: BaseException) -> tuple[int, dict]:
    """Map a serving exception to ``(status, body)`` — the single
    server-side encoding of the table above."""
    if isinstance(exc, ServeRejected):
        return STATUS_REJECTED, {"error": "rejected",
                                 "reason": exc.reason,
                                 "detail": str(exc)}
    if isinstance(exc, ServeDeadlineExceeded):
        return STATUS_DEADLINE, {"error": "deadline", "detail": str(exc)}
    if isinstance(exc, ServeEngineFailed):
        return STATUS_UNAVAILABLE, {"error": "engine_failed",
                                    "detail": str(exc)}
    if isinstance(exc, ValueError):
        return STATUS_BAD_REQUEST, {"error": "bad_request",
                                    "detail": str(exc)}
    return 500, {"error": "internal", "detail": repr(exc)}


def status_to_error(status: int, body: dict) -> BaseException:
    """Client-side inverse: reconstruct the engine-side exception from a
    non-200 reply, so code above a :class:`FleetClient` handles wire and
    in-process serving identically."""
    detail = body.get("detail", f"wire status {status}")
    if status == STATUS_REJECTED:
        return ServeRejected(detail,
                             reason=body.get("reason", "queue_full"))
    if status == STATUS_DEADLINE:
        return ServeDeadlineExceeded(detail)
    if status == STATUS_UNAVAILABLE:
        return ServeEngineFailed(detail)
    if status == STATUS_BAD_REQUEST:
        return ValueError(detail)
    return RuntimeError(f"unexpected wire status {status}: {detail}")


#: Fast-path session extraction for the router's byte-level relay: the
#: submit body leads with a plain-string session id in every client this
#: repo ships; anything fancier (escapes, non-string ids) falls back to
#: a real JSON parse.
_SESSION_RE = re.compile(rb'"session"\s*:\s*"([^"\\]*)"')


def extract_session(raw: bytes) -> str:
    """Pull the session id out of a submit body without a full JSON
    round-trip (both wire backends' relay paths use this); raises the
    400-mapped ``ValueError`` on a body with no recoverable session."""
    m = _SESSION_RE.search(raw)
    if m is not None:
        return m.group(1).decode("utf-8", "replace")
    try:
        return str(json.loads(raw)["session"])
    except (ValueError, KeyError, TypeError) as exc:
        raise ValueError(f"malformed submit body: {exc!r}") from exc


class _WireConnError(ConnectionError):
    """A malformed/torn HTTP response on a persistent connection —
    transport-class (the keep-alive is unusable), never protocol-class."""


class WireTracer:
    """Frontend-side trace context for one process: parse the inbound
    ``X-Trace-Id``/``X-Parent-Span`` headers (fleet/proto.py — the one
    framing definition) or, when ``mint`` and none arrived, mint a fresh
    trace id — so every request through a tracing front-end belongs to
    exactly one trace. Shared by BOTH wire backends (threaded handler
    and evloop), which is what keeps their span shapes identical.

    ``begin`` returns an opaque tuple context (or None = untraced
    request); ``finish`` journals this hop's span through the bounded
    :class:`~sharetrade_tpu.obs.trace.SpanSink` (tuple append now,
    serialization at flush — the lint-16 emission discipline).

    ``sink=None`` is the ENGINE-worker spelling: parse and propagate the
    inbound context without emitting a hop span of our own — an engine's
    spans must parent DIRECTLY under the router's journaled attempt span,
    never under an engine-local span a SIGKILL could leave unflushed
    (the stitch contract in obs/collect.py)."""

    __slots__ = ("sink", "mint")

    def __init__(self, sink=None, *, mint: bool = False):
        self.sink = sink
        self.mint = mint

    def begin(self, headers: dict) -> tuple | None:
        """(trace_id, inbound_parent, own_span_id, t0) for one inbound
        request, or None when it carries no context and we don't mint.
        ``own_span_id`` is ``""`` for a parse-only (sink-less) tracer —
        downstream hops then parent under ``inbound_parent``."""
        ctx = proto.trace_context(headers)
        if ctx is None:
            if not self.mint or self.sink is None:
                return None
            trace_id, parent = new_trace_id(), ""
        else:
            trace_id, parent = ctx
        own = self.sink.new_span_id() if self.sink is not None else ""
        return (trace_id, parent, own, time.perf_counter())

    def finish(self, tctx: tuple, name: str, note: str = "") -> None:
        trace_id, parent, span_id, t0 = tctx
        if not span_id:
            return
        self.sink.span(trace_id, span_id, parent, name, t0,
                       time.perf_counter(), note)


class FleetClient:
    """Blocking wire client over ONE persistent keep-alive connection.

    NOT thread-safe by design — each worker/handler thread owns its own
    client (the connection-per-thread pattern both the router's proxy
    path and the load harness's :class:`WireEngine` use), so there is no
    lock on the request path. A torn keep-alive (server restarted, idle
    timeout) is retried ONCE on a fresh connection; a second transport
    failure propagates to the caller, which owns the re-route/give-up
    decision.

    Implementation note: this speaks HTTP/1.1 over a RAW socket — one
    ``sendall`` of a prebuilt request, responses framed by the shared
    sans-IO parser (fleet/proto.py) — instead of ``http.client``. Same
    protocol on the wire; ~4-5x less per-request Python, which is the
    difference between the router being thinner than an engine and the
    router being the fleet's bottleneck (bench_fleet's framing)."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, sink=None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._sock: socket.socket | None = None
        self._parser = proto.ResponseParser()
        #: Optional wire-span sink (obs/trace.py SpanSink). When set,
        #: every submit MINTS a trace id, carries it (plus this client
        #: span's id as the parent) on the request headers, journals a
        #: ``client_submit`` root span, and returns the trace id in the
        #: reply dict under ``"trace_id"`` (added CLIENT-side — reply
        #: wire bytes never carry trace state). None (default) = zero
        #: headers, zero spans: the obs-disabled wire is byte-identical
        #: to the pre-tracing wire.
        self.sink = sink

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._parser = proto.ResponseParser()

    def _connect(self, timeout_s: float) -> socket.socket:
        # fleet-net-ok: CLIENT socket (outbound connect, no listener).
        sock = socket.create_connection((self.host, self.port),
                                        timeout=timeout_s)
        # One-sendall requests make Nagle pointless and delayed-ACK
        # interplay expensive; serving RPCs always disable it.
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _read_response(self, sock: socket.socket) -> tuple[int, bytes]:
        """One HTTP/1.1 response off the socket, framed by the shared
        sans-IO parser (torn reads, missing/malformed Content-Length
        and oversized heads all handled in ONE place — fleet/proto.py);
        any framing violation is transport-class, the keep-alive is
        unrecoverable."""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                raise _WireConnError("connection closed mid-response")
            try:
                events = self._parser.feed(chunk)
            except proto.ProtocolError as exc:
                raise _WireConnError(exc.detail) from exc
            if events:
                response = events[0]
                return response.status, response.body

    def _request(self, method: str, path: str,
                 body: bytes | None = None,
                 headers: dict | None = None,
                 timeout_s: float | None = None) -> tuple[int, bytes]:
        body = body or b""
        request = proto.render_request(method, path,
                                       f"{self.host}:{self.port}",
                                       body, headers=headers)
        timeout = timeout_s or self.timeout_s
        attempts = 2            # fresh-connection retry for torn keep-alive
        for attempt in range(attempts):
            fresh = self._sock is None
            if fresh:
                self._sock = self._connect(timeout)
            else:
                self._sock.settimeout(timeout)
            try:
                self._sock.sendall(request)
                return self._read_response(self._sock)
            except TRANSPORT_ERRORS:
                self.close()
                # Retry ONLY a torn keep-alive: a failure on a fresh
                # connection is the peer's true state, and re-sending
                # after response bytes may already have been consumed
                # risks a duplicate.
                if fresh or attempt + 1 >= attempts:
                    raise
        raise AssertionError("unreachable")

    def raw_request(self, path: str, body: bytes,
                    extra_headers: dict | None = None,
                    timeout_s: float | None = None) -> tuple[int, bytes]:
        """Byte-level POST relay (the router's thin-proxy hop): the body
        is forwarded VERBATIM and the reply's ``(status, body)`` handed
        back unparsed — no JSON round-trip on the proxy path."""
        return self._request("POST", path, body=body,
                             headers=extra_headers, timeout_s=timeout_s)

    def submit(self, session: str, obs, *,
               deadline_ms: float | None = None,
               timeout_s: float | None = None) -> dict:
        """One inference over the wire; returns the result dict or raises
        the reconstructed serving exception (see module table). The HTTP
        read timeout defaults to the deadline plus slack — a deadline'd
        request should die ENGINE-side (504), the transport timeout is
        only the backstop for a wedged peer."""
        payload = json.dumps(
            {"session": session,
             "obs": [float(x) for x in obs]}).encode()
        headers = {"Content-Type": "application/json"}
        if deadline_ms:
            headers[DEADLINE_HEADER] = f"{float(deadline_ms):g}"
            if timeout_s is None:
                timeout_s = max(float(deadline_ms) / 1e3 * 4, 5.0)
        trace_id = span_id = None
        if self.sink is not None:
            trace_id = new_trace_id()
            span_id = self.sink.new_span_id()
            headers[proto.TRACE_HEADER] = trace_id
            headers[proto.PARENT_HEADER] = span_id
        t0 = time.perf_counter()
        try:
            status, body = self._request("POST", SUBMIT_PATH,
                                         body=payload, headers=headers,
                                         timeout_s=timeout_s)
        finally:
            if span_id is not None:
                self.sink.span(trace_id, span_id, "", "client_submit",
                               t0, time.perf_counter(), note=session)
        parsed = self._json(body)
        if trace_id is not None:
            parsed.setdefault("trace_id", trace_id)
        if status == STATUS_OK:
            return parsed
        raise status_to_error(status, parsed)

    def health(self, *, timeout_s: float | None = None) -> dict:
        status, body = self._request("GET", HEALTH_PATH,
                                     timeout_s=timeout_s)
        if status != STATUS_OK:
            raise ServeEngineFailed(f"healthz returned {status}")
        return self._json(body)

    def metrics(self, *, timeout_s: float | None = None) -> str:
        status, body = self._request("GET", METRICS_PATH,
                                     timeout_s=timeout_s)
        if status != STATUS_OK:
            raise ServeEngineFailed(f"metrics returned {status}")
        return body.decode("utf-8", errors="replace")

    @staticmethod
    def _json(body: bytes) -> dict:
        try:
            parsed = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return {}
        return parsed if isinstance(parsed, dict) else {}
