"""Parallelism layer: meshes, shardings, collectives, sequence parallelism.

Replaces the reference's Akka Router + mailbox parameter server (SURVEY.md
§2.2-2.3) with jax.sharding meshes and XLA collectives over ICI/DCN.
"""

from sharetrade_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    build_mesh,
    init_distributed,
    is_cpu_mesh,
    mesh_platform,
)
from sharetrade_tpu.parallel.moe import (  # noqa: F401
    init_moe_params,
    moe_apply,
    moe_apply_sharded,
    moe_apply_topk,
    moe_apply_topk_a2a,
    moe_apply_topk_sharded,
)
from sharetrade_tpu.parallel.episode_sp import (  # noqa: F401
    halo_banded_attention_sharded,
)
from sharetrade_tpu.parallel.pipeline import pipeline_apply, stack_stage_params  # noqa: F401
from sharetrade_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
    sequence_sharding,
)
from sharetrade_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_padded,
    ulysses_attention_sharded,
)
from sharetrade_tpu.parallel.sharding import (  # noqa: F401
    batch_axis_sharding,
    canonical_sharding,
    constrain_train_state,
    jit_parallel_step,
    make_parallel_step,
    mlp_tp_rules,
    param_shardings,
    train_state_shardings,
)
