"""Named-axis collective helpers.

The reference's entire communication layer is actor mailboxes: broadcast
routing (TrainerRouterActor.scala:66), ask-based gather (:137-139), and the
mailbox-serialized parameter server (QDecisionPolicyActor.scala:54-77). The
TPU-native equivalents are XLA collectives over ICI/DCN — these helpers name
the correspondence once so call sites read as intent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def all_reduce_mean(x, axis: str):
    """Gradient/metric averaging — replaces the serialized UpdateQ stream."""
    return jax.lax.pmean(x, axis)


def all_reduce_sum(x, axis: str):
    return jax.lax.psum(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False):
    """Result aggregation — replaces the router's ask(GetPortfolio) fan-in."""
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str):
    """Sharded reduction (ZeRO-style optimizer sharding building block)."""
    return jax.lax.psum_scatter(x, axis, tiled=True)


def ring_shift(x, axis: str, *, reverse: bool = False):
    """One ring hop (the ring-attention/pipeline transfer primitive)."""
    n = jax.lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def broadcast_from(x, axis: str, src: int = 0):
    """Replicate one shard's value to the whole axis (router broadcast)."""
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)
