"""Expert parallelism: a mixture-of-experts layer sharded over the ``ep`` axis.

Absent from the reference (SURVEY.md §2.2 lists EP as none) — supplied here
as the mechanism: E expert MLPs live E/ep-per-device on the ``ep`` axis; a
replicated top-1 gate routes each token; every device evaluates its resident
experts on the full token batch under the routing mask and a ``psum``
combines the (disjoint) contributions. Communication is one all-reduce of the
token activations — the dense-mask scheme, chosen over capacity-bucketed
all_to_all dispatch because it is shape-static, load-balance-oblivious, and
exact (no token dropping); an all_to_all dispatch path is the natural later
optimization once expert counts grow.

An auxiliary load-balancing loss (mean-importance · mean-load, the standard
switch-style regularizer) is returned alongside the output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def init_moe_params(key: jax.Array, num_experts: int, in_dim: int,
                    hidden_dim: int, *, dtype=jnp.float32) -> dict:
    k_gate, k_in, k_out = jax.random.split(key, 3)
    s_in = jnp.sqrt(2.0 / in_dim).astype(dtype)
    s_hid = jnp.sqrt(2.0 / hidden_dim).astype(dtype)
    return {
        "gate": jax.random.normal(k_gate, (in_dim, num_experts), dtype) * 0.01,
        "w_in": jax.random.normal(
            k_in, (num_experts, in_dim, hidden_dim), dtype) * s_in,
        "w_out": jax.random.normal(
            k_out, (num_experts, hidden_dim, in_dim), dtype) * s_hid,
    }


def moe_apply(params: dict, tokens: jax.Array):
    """Single-device reference: top-1 MoE over (N, in_dim) tokens.

    Returns (output (N, in_dim), aux_loss scalar)."""
    logits = tokens @ params["gate"]                        # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(logits, axis=-1)                    # (N,)
    num_experts = params["gate"].shape[-1]
    onehot = jax.nn.one_hot(choice, num_experts, dtype=tokens.dtype)
    weight = jnp.sum(probs * onehot, axis=-1)               # gate value of pick

    # Dense-mask evaluation: h[e] = relu(x @ w_in[e]) @ w_out[e], masked.
    h = jnp.einsum("ni,eih->enh", tokens, params["w_in"],
                   preferred_element_type=jnp.float32).astype(tokens.dtype)
    h = jax.nn.relu(h)
    y = jnp.einsum("enh,ehi->eni", h, params["w_out"],
                   preferred_element_type=jnp.float32).astype(tokens.dtype)
    out = jnp.einsum("eni,ne->ni", y, onehot) * weight[:, None]

    # Switch-style load-balance loss: E * sum_e importance_e * load_e.
    importance = jnp.mean(probs, axis=0)
    load = jnp.mean(onehot, axis=0)
    aux = num_experts * jnp.sum(importance * load)
    return out, aux


def moe_apply_sharded(params: dict, tokens: jax.Array, mesh: Mesh,
                      *, axis: str = "ep", batch_axis: str | None = None):
    """Expert-parallel evaluation: experts sharded over ``axis``, tokens and
    gate replicated, contributions psum-combined. Numerically identical to
    :func:`moe_apply`. ``batch_axis`` names a mesh axis the token batch is
    already sharded over (e.g. "dp") so the shard_map keeps that layout
    instead of all-gathering the tokens."""
    num_experts = params["gate"].shape[-1]
    ep = mesh.shape[axis]
    if num_experts % ep != 0:
        raise ValueError(f"num_experts={num_experts} not divisible by "
                         f"{axis}={ep}")
    if batch_axis is not None and tokens.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None   # odd token count: fall back to replication

    def local_fn(gate, w_in, w_out, toks):
        logits = toks @ gate                                # replicated (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(logits, axis=-1)
        onehot = jax.nn.one_hot(choice, num_experts, dtype=toks.dtype)
        weight = jnp.sum(probs * onehot, axis=-1)

        # This device's expert slice: global ids [lo, lo + E/ep).
        local_e = num_experts // ep
        lo = jax.lax.axis_index(axis) * local_e
        local_mask = jax.lax.dynamic_slice_in_dim(onehot, lo, local_e, axis=1)

        h = jnp.einsum("ni,eih->enh", toks, w_in,
                       preferred_element_type=jnp.float32).astype(toks.dtype)
        h = jax.nn.relu(h)
        y = jnp.einsum("enh,ehi->eni", h, w_out,
                       preferred_element_type=jnp.float32).astype(toks.dtype)
        partial = jnp.einsum("eni,ne->ni", y, local_mask) * weight[:, None]
        out = jax.lax.psum(partial, axis)                   # disjoint -> exact

        importance = jnp.mean(probs, axis=0)
        load = jnp.mean(onehot, axis=0)
        aux = num_experts * jnp.sum(importance * load)
        if batch_axis is not None:
            aux = jax.lax.pmean(aux, batch_axis)
        return out, aux

    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(batch_axis)),
        out_specs=(P(batch_axis), P()),
    )(params["gate"], params["w_in"], params["w_out"], tokens)
