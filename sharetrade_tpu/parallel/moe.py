"""Expert parallelism: mixture-of-experts layers sharded over the ``ep`` axis.

Absent from the reference (SURVEY.md §2.2 lists EP as none). Two routing
schemes, both shape-static:

1. **Dense-mask top-1** (``moe_apply`` / ``moe_apply_sharded``): every device
   evaluates its resident experts on the full token batch under the routing
   mask; a ``psum`` combines the disjoint contributions. Exact (no token
   dropping) and load-balance-oblivious, but O(E·N) compute — the right
   choice for small expert counts and the numeric reference for the rest.
2. **Capacity-bucketed top-k dispatch** (``moe_apply_topk`` and its
   ``_sharded`` psum / ``_a2a`` all_to_all variants): GShard-style grouped
   routing into per-expert buffers of C = O(k·g/E) tokens, so each expert
   only computes its routed tokens; picks overflowing the static buffers are
   dropped. The ``_a2a`` variant additionally shards the tokens over ``ep``
   and moves only dispatched buffers across the ICI — the pattern that
   scales both E and N.

Every path returns an auxiliary load-balancing loss (mean-importance ·
mean-load, the standard switch-style regularizer) alongside the output;
models surface it via ``ModelOut.aux`` and learners weight it by
``LearnerConfig.aux_loss_coef`` — essential for the dropping schemes, where
a collapsed gate silently zeroes overflow tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sharetrade_tpu.config import ConfigError
from sharetrade_tpu.parallel.compat import shard_map


def init_moe_params(key: jax.Array, num_experts: int, in_dim: int,
                    hidden_dim: int, *, dtype=jnp.float32) -> dict:
    k_gate, k_in, k_out = jax.random.split(key, 3)
    s_in = jnp.sqrt(2.0 / in_dim).astype(dtype)
    s_hid = jnp.sqrt(2.0 / hidden_dim).astype(dtype)
    return {
        "gate": jax.random.normal(k_gate, (in_dim, num_experts), dtype) * 0.01,
        "w_in": jax.random.normal(
            k_in, (num_experts, in_dim, hidden_dim), dtype) * s_in,
        "w_out": jax.random.normal(
            k_out, (num_experts, hidden_dim, in_dim), dtype) * s_hid,
    }


def moe_apply(params: dict, tokens: jax.Array):
    """Single-device reference: top-1 MoE over (N, in_dim) tokens.

    Returns (output (N, in_dim), aux_loss scalar)."""
    logits = tokens @ params["gate"]                        # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(logits, axis=-1)                    # (N,)
    num_experts = params["gate"].shape[-1]
    onehot = jax.nn.one_hot(choice, num_experts, dtype=tokens.dtype)
    weight = jnp.sum(probs * onehot, axis=-1)               # gate value of pick

    # Dense-mask evaluation: h[e] = relu(x @ w_in[e]) @ w_out[e], masked.
    h = jnp.einsum("ni,eih->enh", tokens, params["w_in"],
                   preferred_element_type=jnp.float32).astype(tokens.dtype)
    h = jax.nn.relu(h)
    y = jnp.einsum("enh,ehi->eni", h, params["w_out"],
                   preferred_element_type=jnp.float32).astype(tokens.dtype)
    out = jnp.einsum("eni,ne->ni", y, onehot) * weight[:, None]

    # Switch-style load-balance loss: E * sum_e importance_e * load_e.
    importance = jnp.mean(probs, axis=0)
    load = jnp.mean(onehot, axis=0)
    aux = num_experts * jnp.sum(importance * load)
    return out, aux


def _pad_groups(tokens: jax.Array, group_size: int | None):
    """Reshape (N, d) tokens into fixed-size routing groups, zero-padding the
    tail (GShard's group dimension): the one-hot dispatch/combine tensors
    stay O(g·E·C) per group instead of O(N·E·C) globally — without grouping
    they grow quadratically in N.

    Returns ``(grouped (G, g, d), valid (G, g) 0/1 mask)``; callers slice
    their output back to N rows.
    """
    n = tokens.shape[0]
    if group_size is None or n <= group_size:
        groups, g = 1, n
    else:
        g = group_size
        groups = -(-n // g)
    n_pad = groups * g
    toks = jnp.pad(tokens, ((0, n_pad - n), (0, 0)))
    valid = (jnp.arange(n_pad) < n).astype(jnp.float32).reshape(groups, g)
    return toks.reshape(groups, g, -1), valid


def _capacity(group_tokens: int, num_experts: int, top_k: int,
              capacity_factor: float) -> int:
    """Static per-expert buffer length per routing group, rounded up to a
    sublane multiple so the (E, C, d) dispatch buffers tile cleanly on TPU."""
    cap = -(-top_k * group_tokens * capacity_factor // num_experts)
    cap = max(int(cap), 1)
    return -(-cap // 8) * 8


def _topk_route(gate_logits: jax.Array, top_k: int, capacity: int, dtype,
                valid: jax.Array | None = None):
    """Shape-static top-k routing with per-expert capacity, per group.

    ``gate_logits`` is (G, g, E); ``valid`` is an optional (G, g) 0/1 mask —
    padding rows claim no buffer slots and are excluded from the balance
    statistics. Returns ``(dispatch (G, g, E, C), combine (G, g, E, C),
    (importance, load))``: ``dispatch`` is a 0/1 scatter of each surviving
    (token, pick) into its expert's buffer slot; ``combine`` additionally
    carries the gate weight; the final element is the per-expert balance
    statistics pair for :func:`_balance_loss`. Within a group, slots are
    claimed in pick-rank-major order (every token's top-1 pick beats any
    token's top-2 pick), the standard overflow priority; picks past capacity
    are dropped — the documented trade for static shapes.
    """
    groups, g, num_experts = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, top_k)              # (G, g, k)
    sel = jax.nn.one_hot(top_i, num_experts, dtype=jnp.float32)  # (G, g, k, E)
    if valid is not None:
        sel = sel * valid[:, :, None, None]

    # Buffer position of each pick: cumulative count of earlier claims on the
    # same expert, counting rank-major (k outer, token inner) per group.
    sel_rank_major = sel.transpose(0, 2, 1, 3).reshape(
        groups, top_k * g, num_experts)
    pos = jnp.cumsum(sel_rank_major, axis=1) - sel_rank_major
    pos = pos.reshape(groups, top_k, g, num_experts).transpose(0, 2, 1, 3)
    pos_of_pick = jnp.sum(pos * sel, axis=-1).astype(jnp.int32)  # (G, g, k)

    keep = (pos_of_pick < capacity).astype(jnp.float32)     # (G, g, k)
    slot = jax.nn.one_hot(pos_of_pick, capacity, dtype=jnp.float32)
    dispatch = jnp.einsum("Gnk,Gnke,Gnkc->Gnec", keep, sel, slot)
    combine = jnp.einsum("Gnk,Gnke,Gnkc->Gnec", keep * top_p, sel, slot)

    if valid is None:
        importance = jnp.mean(probs, axis=(0, 1))
        load = jnp.mean(sel[:, :, 0, :], axis=(0, 1))       # top-1 routing share
    else:
        denom = jnp.maximum(jnp.sum(valid), 1.0)
        importance = jnp.sum(probs * valid[:, :, None], axis=(0, 1)) / denom
        load = jnp.sum(sel[:, :, 0, :], axis=(0, 1)) / denom
    return dispatch.astype(dtype), combine.astype(dtype), (importance, load)


def _balance_loss(importance: jax.Array, load: jax.Array) -> jax.Array:
    """Switch-style load-balance regularizer: E · Σ_e importance_e · load_e."""
    return importance.shape[-1] * jnp.sum(importance * load)


def _expert_ffn(w_in: jax.Array, w_out: jax.Array, xs: jax.Array) -> jax.Array:
    """relu FFN over per-expert buffers: (E, C, in) -> (E, C, in)."""
    h = jnp.einsum("eci,eih->ech", xs, w_in,
                   preferred_element_type=jnp.float32).astype(xs.dtype)
    h = jax.nn.relu(h)
    return jnp.einsum("ech,ehi->eci", h, w_out,
                      preferred_element_type=jnp.float32).astype(xs.dtype)


def _dispatch_gather(dispatch: jax.Array, toks: jax.Array) -> jax.Array:
    """(G, g, E, C) dispatch × (G, g, d) tokens -> (E, G·C, d) buffers."""
    groups, _, num_experts, cap = dispatch.shape
    xs = jnp.einsum("Gnec,Gni->Geci", dispatch, toks)
    return xs.transpose(1, 0, 2, 3).reshape(num_experts, groups * cap, -1)


def _combine_scatter(combine: jax.Array, ys: jax.Array) -> jax.Array:
    """(E, G·C, d) expert outputs × (G, g, E, C) combine -> (G·g, d)."""
    groups, g, num_experts, cap = combine.shape
    ys = ys.reshape(num_experts, groups, cap, -1).transpose(1, 0, 2, 3)
    out = jnp.einsum("Geci,Gnec->Gni", ys, combine)
    return out.reshape(groups * g, -1)


def moe_apply_topk(params: dict, tokens: jax.Array, *, top_k: int = 2,
                   capacity_factor: float = 1.25,
                   group_size: int | None = 1024):
    """Top-k MoE with capacity-bucketed dispatch (single-device reference).

    Unlike :func:`moe_apply`'s dense-mask scheme — exact but O(E·N), every
    expert runs every token — each expert here evaluates only its
    C = O(k·g/E) dispatched tokens per routing group, the compute profile
    that makes large expert counts affordable. Picks overflowing an expert's
    static per-group buffer are dropped (contribute zero), bounded by
    ``capacity_factor``.

    Returns (output (N, in_dim), aux_loss scalar).
    """
    n = tokens.shape[0]
    num_experts = params["gate"].shape[-1]
    toks, valid = _pad_groups(tokens, group_size)
    cap = _capacity(toks.shape[1], num_experts, top_k, capacity_factor)
    dispatch, combine, (importance, load) = _topk_route(
        jnp.einsum("Gni,ie->Gne", toks, params["gate"]), top_k, cap,
        tokens.dtype, valid)
    ys = _expert_ffn(params["w_in"], params["w_out"],
                     _dispatch_gather(dispatch, toks))
    return _combine_scatter(combine, ys)[:n], _balance_loss(importance, load)


def moe_apply_topk_sharded(params: dict, tokens: jax.Array, mesh: Mesh,
                           *, axis: str = "ep", top_k: int = 2,
                           capacity_factor: float = 1.25,
                           group_size: int | None = 1024,
                           batch_axis: str | None = None):
    """Expert-parallel top-k MoE: experts sharded over ``axis``, routing
    replicated, each device running only its resident experts' buffers.

    Per-device expert compute is E/ep buffers of G·C tokens — versus the
    dense-mask scheme's E/ep experts × ALL N tokens — with the same single
    psum combine. Numerically identical to :func:`moe_apply_topk` (same
    global buffer positions, so the same picks drop).
    """
    num_experts = params["gate"].shape[-1]
    ep = mesh.shape[axis]
    if num_experts % ep != 0:
        raise ConfigError(f"num_experts={num_experts} not divisible by "
                         f"{axis}={ep}")
    if batch_axis is not None and tokens.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None   # odd token count: fall back to replication
    n = tokens.shape[0]
    local_e = num_experts // ep

    def local_fn(gate, w_in, w_out, toks):
        if batch_axis is not None:
            toks = jax.lax.all_gather(toks, batch_axis, axis=0, tiled=True)
        toks, valid = _pad_groups(toks, group_size)
        cap = _capacity(toks.shape[1], num_experts, top_k, capacity_factor)
        dispatch, combine, (importance, load) = _topk_route(
            jnp.einsum("Gni,ie->Gne", toks, gate), top_k, cap, toks.dtype,
            valid)
        aux = _balance_loss(importance, load)
        if batch_axis is not None:
            # Computed from the all_gathered batch, so already equal across
            # batch shards; the pmean marks the replication for shard_map's
            # out_specs check.
            aux = jax.lax.pmean(aux, batch_axis)
        lo = jax.lax.axis_index(axis) * local_e
        disp_l = jax.lax.dynamic_slice_in_dim(dispatch, lo, local_e, axis=2)
        comb_l = jax.lax.dynamic_slice_in_dim(combine, lo, local_e, axis=2)
        ys = _expert_ffn(w_in, w_out, _dispatch_gather(disp_l, toks))
        partial = _combine_scatter(comb_l, ys)[:n]
        out = jax.lax.psum(partial, axis)                   # disjoint -> exact
        if batch_axis is not None:
            shard = jax.lax.axis_index(batch_axis)
            nloc = n // mesh.shape[batch_axis]
            out = jax.lax.dynamic_slice_in_dim(out, shard * nloc, nloc, axis=0)
        return out, aux

    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(batch_axis)),
        out_specs=(P(batch_axis), P()),
    )(params["gate"], params["w_in"], params["w_out"], tokens)


def moe_apply_topk_a2a(params: dict, tokens: jax.Array, mesh: Mesh,
                       *, axis: str = "ep", top_k: int = 2,
                       capacity_factor: float = 1.25,
                       group_size: int | None = 1024,
                       n_valid: int | None = None):
    """GShard-style all_to_all dispatch: tokens AND experts sharded over
    ``axis``; each shard routes its local tokens, an all_to_all carries the
    dispatched buffers to their expert-owner devices, and a second
    all_to_all returns expert outputs for the local combine.

    Communication is two all_to_alls of the (ep, E/ep, G·C, d) buffers —
    O(k·N·d·capacity_factor) bytes total, independent of E — versus the
    replicated schemes' psum of the full (N, d) activations on every device.
    This is the dispatch pattern that scales token counts: no device ever
    materializes the global batch. Routing groups are per source shard, so
    drop decisions are shard-local; in the no-drop regime the result equals
    :func:`moe_apply_topk` exactly.

    ``n_valid`` marks rows past it as padding (callers pad the token count
    up to a multiple of ep): they claim no buffer slots and are excluded
    from the balance statistics, exactly like group padding.
    """
    num_experts = params["gate"].shape[-1]
    ep = mesh.shape[axis]
    if num_experts % ep != 0:
        raise ConfigError(f"num_experts={num_experts} not divisible by "
                         f"{axis}={ep}")
    if tokens.shape[0] % ep != 0:
        raise ConfigError(f"token count {tokens.shape[0]} not divisible by "
                         f"{axis}={ep} (a2a dispatch shards tokens)")
    n_local = tokens.shape[0] // ep
    local_e = num_experts // ep

    def local_fn(gate, w_in, w_out, toks):
        # toks: (N/ep, d) — this shard's tokens only.
        gtoks, valid = _pad_groups(toks, group_size)
        if n_valid is not None:
            # Global row ids of this shard's rows, laid into the group grid.
            start = jax.lax.axis_index(axis) * n_local
            row_ok = (start + jnp.arange(n_local) < n_valid)
            row_ok = jnp.pad(row_ok, (0, valid.size - n_local))
            valid = valid * row_ok.reshape(valid.shape).astype(valid.dtype)
        groups = gtoks.shape[0]
        cap = _capacity(gtoks.shape[1], num_experts, top_k, capacity_factor)
        dispatch, combine, (importance, load) = _topk_route(
            jnp.einsum("Gni,ie->Gne", gtoks, gate), top_k, cap, toks.dtype,
            valid)
        # Global balance statistics BEFORE the product: averaging per-shard
        # importance·load products is not the global loss (nonlinear in the
        # means). Count-weighted: shards can hold unequal VALID counts (the
        # n_valid pad tail lives on the last shard), so per-shard means are
        # recombined as global-sum / global-count, not pmean'd.
        cnt = jnp.sum(valid)
        total = jnp.maximum(jax.lax.psum(cnt, axis), 1.0)
        imp_g = jax.lax.psum(importance * jnp.maximum(cnt, 1.0), axis) / total
        load_g = jax.lax.psum(load * jnp.maximum(cnt, 1.0), axis) / total
        aux = _balance_loss(imp_g, load_g)
        xs = _dispatch_gather(dispatch, gtoks)              # (E, G·C, d)
        d = xs.shape[-1]
        xs = xs.reshape(ep, local_e, groups * cap, d)
        # Non-tiled all_to_all: slice j of the leading (size-ep) axis goes to
        # device j; the received leading axis indexes the SOURCE shard, so
        # each owner holds (ep_src, E_local, G·C, d).
        xs = jax.lax.all_to_all(xs, axis, split_axis=0, concat_axis=0)
        ys = _expert_ffn(
            w_in, w_out,
            xs.transpose(1, 0, 2, 3).reshape(local_e, ep * groups * cap, d))
        ys = ys.reshape(local_e, ep, groups * cap, d).transpose(1, 0, 2, 3)
        ys = jax.lax.all_to_all(ys, axis, split_axis=0, concat_axis=0)
        out = _combine_scatter(
            combine, ys.reshape(num_experts, groups * cap, d))
        return out[:toks.shape[0]], aux

    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P()),
    )(params["gate"], params["w_in"], params["w_out"], tokens)


def moe_apply_sharded(params: dict, tokens: jax.Array, mesh: Mesh,
                      *, axis: str = "ep", batch_axis: str | None = None):
    """Expert-parallel evaluation: experts sharded over ``axis``, tokens and
    gate replicated, contributions psum-combined. Numerically identical to
    :func:`moe_apply`. ``batch_axis`` names a mesh axis the token batch is
    already sharded over (e.g. "dp") so the shard_map keeps that layout
    instead of all-gathering the tokens."""
    num_experts = params["gate"].shape[-1]
    ep = mesh.shape[axis]
    if num_experts % ep != 0:
        raise ConfigError(f"num_experts={num_experts} not divisible by "
                         f"{axis}={ep}")
    if batch_axis is not None and tokens.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None   # odd token count: fall back to replication

    def local_fn(gate, w_in, w_out, toks):
        logits = toks @ gate                                # replicated (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(logits, axis=-1)
        onehot = jax.nn.one_hot(choice, num_experts, dtype=toks.dtype)
        weight = jnp.sum(probs * onehot, axis=-1)

        # This device's expert slice: global ids [lo, lo + E/ep).
        local_e = num_experts // ep
        lo = jax.lax.axis_index(axis) * local_e
        local_mask = jax.lax.dynamic_slice_in_dim(onehot, lo, local_e, axis=1)

        h = jnp.einsum("ni,eih->enh", toks, w_in,
                       preferred_element_type=jnp.float32).astype(toks.dtype)
        h = jax.nn.relu(h)
        y = jnp.einsum("enh,ehi->eni", h, w_out,
                       preferred_element_type=jnp.float32).astype(toks.dtype)
        partial = jnp.einsum("eni,ne->ni", y, local_mask) * weight[:, None]
        out = jax.lax.psum(partial, axis)                   # disjoint -> exact

        importance = jnp.mean(probs, axis=0)
        load = jnp.mean(onehot, axis=0)
        aux = num_experts * jnp.sum(importance * load)
        if batch_axis is not None:
            aux = jax.lax.pmean(aux, batch_axis)
        return out, aux

    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(batch_axis)),
        out_specs=(P(batch_axis), P()),
    )(params["gate"], params["w_in"], params["w_out"], tokens)
