"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp`` axis.

No analogue exists in the reference (its model is a 2-layer MLP in one
process; SURVEY.md §2.2 lists PP as absent) — this supplies the mechanism so
deep stacks scale across chips: consecutive layer groups ("stages") live on
consecutive devices of the ``pp`` mesh axis, activations flow stage→stage via
``ppermute`` (one ICI hop per schedule tick), and M microbatches keep every
stage busy after an S-tick fill. Per-device parameter memory drops by the
pipeline factor; the bubble fraction is (S-1)/(M+S-1).

The schedule is data-oblivious (a static Python loop of M+S-1 ticks inside
one jit), so XLA sees straight-line code with S-fold smaller matmuls — no
dynamic control flow (XLA-semantics rule: no data-dependent Python control
flow under jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sharetrade_tpu.config import ConfigError
from sharetrade_tpu.parallel.compat import shard_map


def pipeline_apply(stage_fn, stage_params, microbatches, mesh: Mesh,
                   *, axis: str = "pp", mb_spec: P = P(),
                   side_template=None, side_specs=None,
                   carry_template=None):
    """Run ``microbatches`` through ``num_stages`` pipelined stages.

    - ``stage_fn(params, x) -> x``: one stage's forward (same signature for
      every stage; heterogeneous stacks encode choice inside params). With
      ``side_template``, ``stage_fn(params, x) -> (x, side)`` — ``side`` is
      a per-(stage, microbatch) pytree matching the template's
      shapes/dtypes (e.g. a block's K/V cache tail, its MoE balance loss).
      With ``carry_template`` (requires ``side_template``),
      ``stage_fn(params, x, carry) -> (x, side, carry)`` — ``carry`` is a
      STAGE-LOCAL streaming state threaded tick-to-tick within each stage
      and never communicated: microbatch m's processing at stage i sees the
      carry microbatch m-1 left there (GPipe microbatches are normally
      independent; the carry supports SEQUENTIAL microbatches — sequence
      chunks whose banded-attention halo flows chunk to chunk,
      models/transformer_episode.py). Initialized to the template's zeros
      per call; updates are masked off on fill/drain ticks so garbage
      states never pollute it.
    - ``stage_params``: pytree whose leaves have leading dim ``num_stages``
      (stage i's slice lives on pp-device i).
    - ``microbatches``: array of shape (M, ...) — M microbatches.
    - ``mb_spec``: the microbatches' PartitionSpec over OTHER mesh axes
      (e.g. ``P(None, "dp")`` when the per-microbatch batch dim is
      dp-sharded in a dp x pp mesh); must not mention ``axis`` itself —
      every pipeline stage needs the ticks it owns.

    Returns the (M, ...) outputs with the same ``mb_spec`` sharding; with
    ``side_template`` returns ``(out, sides)`` where each side leaf gains
    leading dims (num_stages, M) (each stage computes its row; a one-hot
    psum assembles the full stack) — how per-layer byproducts (K/V caches,
    aux losses) escape a schedule whose stage activations never leave
    their device. When ``mb_spec`` shards a batch axis, any side leaf
    carrying per-row data must declare that axis in ``side_specs`` (a
    side-shaped pytree of PartitionSpecs over the ASSEMBLED (S, M, ...)
    layout; default all-replicated) — a replicated spec on a sharded-batch
    side would silently return one shard's rows for everybody. Per-leaf
    template shapes are the LOCAL shard shapes in that case, and any
    scalar side (an aux loss) must be made batch-axis-uniform inside
    ``stage_fn`` (e.g. ``lax.pmean``) to honor its replicated spec.
    """
    num_stages = mesh.shape[axis]
    num_micro = microbatches.shape[0]
    if axis in jax.tree.leaves(tuple(mb_spec)):
        raise ConfigError(f"mb_spec {mb_spec} must not shard over {axis!r}")
    if carry_template is not None and side_template is None:
        raise ConfigError("carry_template requires side_template "
                         "(stage_fn returns (x, side, carry))")

    def local_fn(params_local, mb_local):
        # params_local: this stage's params (leading dim stripped by the
        # sharding: (1, ...) -> squeeze); mb_local: the (M, ...) batch in
        # this device's LOCAL view (other axes may shard trailing dims).
        params_here = jax.tree.map(lambda x: x[0], params_local)
        stage = jax.lax.axis_index(axis)
        fwd = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        state = jnp.zeros(mb_local.shape[1:], mb_local.dtype)
        out = jnp.zeros(mb_local.shape, mb_local.dtype)
        sides = jax.tree.map(
            lambda t: jnp.zeros((num_micro,) + t.shape, t.dtype),
            side_template)
        carry = jax.tree.map(lambda t: jnp.zeros(t.shape, t.dtype),
                             carry_template)

        for t in range(num_micro + num_stages - 1):
            # Stage 0 ingests microbatch t on ticks 0..M-1.
            feed_idx = min(t, num_micro - 1)
            state = jnp.where(stage == 0,
                              jnp.where(t < num_micro,
                                        mb_local[feed_idx], state),
                              state)
            if side_template is None:
                state = stage_fn(params_here, state)
            else:
                # This stage processes microbatch (t - stage) at tick t;
                # record its side there (ticks outside [stage, stage+M)
                # carry fill/garbage state and are masked off).
                mb_idx = jnp.clip(t - stage, 0, num_micro - 1)
                live = (t >= stage) & (t - stage < num_micro)
                if carry_template is None:
                    state, side = stage_fn(params_here, state)
                else:
                    state, side, new_carry = stage_fn(
                        params_here, state, carry)
                    # Fill/drain ticks run on garbage states; their carry
                    # must not leak into the first real microbatch.
                    carry = jax.tree.map(
                        lambda c, nc: jnp.where(live, nc, c),
                        carry, new_carry)
                sides = jax.tree.map(
                    lambda acc, s: acc.at[mb_idx].set(
                        jnp.where(live, s, acc[mb_idx])), sides, side)
            # Last stage emits microbatch t-(S-1) on ticks S-1..M+S-2.
            emit = t - (num_stages - 1)
            if emit >= 0:
                out = jnp.where(
                    (stage == num_stages - 1),
                    out.at[emit].set(state), out)
            if t + 1 < num_micro + num_stages - 1:
                state = jax.lax.ppermute(state, axis, fwd)

        # Only the last stage holds real outputs; replicate them ring-wide.
        out = jnp.where(stage == num_stages - 1, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis)
        if side_template is None:
            return out
        # Assemble the (S, M, ...) side stack: each stage contributes its
        # own row, zero elsewhere, and a psum over the ring fills the rest.
        onehot = (jnp.arange(num_stages) == stage)
        sides = jax.tree.map(
            lambda s: jax.lax.psum(
                jnp.where(onehot.reshape((num_stages,) + (1,) * s.ndim),
                          s[None], 0), axis), sides)
        return out, sides

    stage_spec = jax.tree.map(lambda _: P(axis), stage_params)
    if side_template is not None and side_specs is None:
        side_specs = jax.tree.map(lambda _: P(), side_template)
    out_specs = mb_spec if side_template is None else (mb_spec, side_specs)
    # check_vma=False: stage_fn may invoke a pallas_call (the flash kernel),
    # whose out_shapes don't carry varying-mesh-axes metadata; the schedule
    # is stage-local by construction so the check adds nothing here.
    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(stage_spec, mb_spec), out_specs=out_specs,
        check_vma=False,
    )(stage_params, microbatches)


def stack_stage_params(per_stage_params: list) -> object:
    """Stack a list of per-stage param pytrees into the leading-dim layout
    ``pipeline_apply`` expects (leaf shapes (S, ...))."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)
