"""Ulysses-style sequence parallelism: all_to_all head↔sequence re-partition.

The second long-context scheme next to ring attention (absent from the
reference, which only slides a 201-price window — SURVEY.md §5). Inputs
arrive sequence-sharded over ``sp`` like the ring's; two all_to_alls
re-partition them so each device holds H/S *heads* with the FULL sequence,
runs ordinary local attention — on TPU, the Pallas flash kernel unchanged
(sharetrade_tpu/ops/attention.py) — and re-partitions back.

Trade-offs vs the ring (parallel/ring_attention.py):

- Communication: activations cross the ICI once per direction (2 all_to_alls
  of O(B·H·T·D/S) bytes per tensor) instead of S-1 ppermute hops of the full
  K/V; no per-hop latency on the critical path.
- Compute: full-sequence attention per head group — the local flash kernel's
  blocked online softmax applies as-is; the ring re-derives it across hops.
- Constraint: S must divide the head count (the ring scales to arbitrary S),
  and per-device K/V memory is O(T·H/S) instead of O(T/S·H).

Both are reachable from the public config surface (``model.attention=
"ring" | "ulysses"``) so the scheme is a measured choice, not a rewrite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sharetrade_tpu.config import ConfigError
from sharetrade_tpu.parallel.compat import shard_map

from sharetrade_tpu.ops.attention import flash_attention


def ulysses_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                      causal: bool = True, sm_scale: float | None = None,
                      batch_axis: str | None = None,
                      use_pallas: bool | None = None):
    """Causal MHA with (batch, heads, seq, head_dim) inputs sharded over
    ``seq_axis``; returns output with the same sharding. ``batch_axis``
    names a mesh axis the batch dim is already sharded over (e.g. "dp")."""
    num_shards = mesh.shape[seq_axis]
    heads, seq = q.shape[1], q.shape[2]
    if heads % num_shards != 0:
        raise ConfigError(
            f"ulysses needs heads divisible by {seq_axis}: "
            f"{heads} % {num_shards} != 0 (use ring attention for rings "
            f"wider than the head count)")
    if seq % num_shards != 0:
        raise ConfigError(
            f"seq len {seq} not divisible by {seq_axis}={num_shards}")

    def local_fn(q_loc, k_loc, v_loc):
        # (B, H, T/S, D) seq-sharded -> (B, H/S, T, D) head-sharded: the
        # tiled all_to_all splits the head axis S ways and concatenates the
        # received sequence shards.
        def to_heads(x):
            return jax.lax.all_to_all(x, seq_axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        out = flash_attention(
            to_heads(q_loc), to_heads(k_loc), to_heads(v_loc),
            causal=causal, sm_scale=sm_scale, use_pallas=use_pallas)
        # (B, H/S, T, D) -> (B, H, T/S, D): the inverse re-partition.
        return jax.lax.all_to_all(out, seq_axis, split_axis=2,
                                  concat_axis=1, tiled=True)

    spec = P(batch_axis, None, seq_axis, None)
    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(
        q, k, v)


def ulysses_attention_padded(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                             causal: bool = True,
                             sm_scale: float | None = None,
                             batch_axis: str | None = None,
                             use_pallas: bool | None = None):
    """Ulysses attention for sequence lengths not divisible by the sp size.

    Pads q/k/v with trailing zero tokens to the next multiple of the sp size
    and slices the output back — causal-safe for the same reason as
    ring_attention_padded: padded KEY positions sit strictly after every real
    query's row, padded QUERY rows are sliced off."""
    if not causal:
        raise ConfigError("ulysses_attention_padded requires causal=True "
                         "(non-causal padding would attend to zero tokens)")
    if batch_axis is not None and q.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None   # odd batch (e.g. eval's batch-1): replicate it
    num_shards = mesh.shape[seq_axis]
    seq = q.shape[2]
    pad = (-seq) % num_shards
    if pad:
        widths = ((0, 0), (0, 0), (0, pad), (0, 0))
        q, k, v = (jnp.pad(x, widths) for x in (q, k, v))
    out = ulysses_attention(q, k, v, mesh, seq_axis=seq_axis, causal=causal,
                            sm_scale=sm_scale, batch_axis=batch_axis,
                            use_pallas=use_pallas)
    return out[:, :, :seq] if pad else out


def ulysses_attention_sharded(mesh: Mesh, seq_axis: str = "sp",
                              batch_axis: str | None = None,
                              use_pallas: bool | None = None):
    """Convenience partial with the mesh bound (for model wiring); handles
    non-divisible sequence lengths via padding."""
    return functools.partial(ulysses_attention_padded, mesh=mesh,
                             seq_axis=seq_axis, batch_axis=batch_axis,
                             use_pallas=use_pallas)
