"""Ring attention: sequence-parallel causal attention over the ``sp`` axis.

Long-context capability (absent from the reference, which only ever slides a
201-price window — SURVEY.md §5): the sequence axis is sharded across
devices, each holding T/S queries and one rotating K/V block. At every ring
step a device contracts its queries against the resident K/V block with
online-softmax accumulation, then passes the block to its neighbor via
``ppermute`` — S-1 hops that ride the ICI ring while the next block's matmul
overlaps with the transfer. Peak memory per device is O(T/S), so context
scales linearly with the ring size.

Built on ``shard_map`` + XLA collectives (the scaling-book recipe), with the
same online-softmax algebra as the local Pallas flash kernel
(sharetrade_tpu/ops/attention.py) — the kernel handles intra-block locality,
the ring handles inter-device locality.

Why the per-hop contraction is plain XLA rather than the Pallas kernel
(measured, TPU v5e, 2026-07-30): the flash kernel returns only the
normalized output, so ring composition through it would need per-hop
(out, logsumexp) pairs with a custom VJP across hops; that machinery buys
nothing at the shapes this path serves. Window mode bounds the sequence at
window+1 tokens, so a hop block is T/S ≲ 1k rows — chained-timing both
implementations at (8, 4, T, 64): T=256 fwd XLA 1 µs vs Pallas 2 µs,
fwd+bwd 2 µs vs 5 µs; T=1024 fwd 1 µs vs 2 µs, fwd+bwd 2 µs vs 2 µs —
dispatch-bound and equal within tunnel noise. The XLA hop's real limit is
the BACKWARD's O((T/S)²) score residuals (a T=4096 50-step grad chain
asked for a 100 GB allocation), but sequences that long ride episode mode,
whose sp path routes through the kernel's banded streaming form
(parallel/episode_sp.py) — so no supported window-mode configuration
reaches the regime where the kernel would win.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sharetrade_tpu.config import ConfigError
from sharetrade_tpu.parallel.compat import shard_map

_NEG_INF = -1e30


def _block_contract(q, k, v, q_offset, k_offset, causal, sm_scale, acc, m, l):
    """Online-softmax accumulate one (q-block, k-block) pair.

    q: (B, H, Tq, D); k/v: (B, H, Tk, D); acc/m/l carry the running
    numerator, row max, and row normalizer.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                   causal: bool = True, sm_scale: float | None = None,
                   batch_axis: str | None = None):
    """Causal MHA with (batch, heads, seq, head_dim) inputs sharded over
    ``seq_axis``. Returns output with the same sharding. ``batch_axis``
    names a mesh axis the batch dim is already sharded over (e.g. "dp" in a
    dp x sp mesh) so the shard_map doesn't force an all-gather of the batch."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    num_shards = mesh.shape[seq_axis]
    if q.shape[2] % num_shards != 0:
        raise ConfigError(
            f"seq len {q.shape[2]} not divisible by {seq_axis}={num_shards}")
    local_len = q.shape[2] // num_shards

    def local_fn(q_loc, k_loc, v_loc):
        # q_loc/k_loc/v_loc: (B, H, T/S, D) — this device's shard.
        my_idx = jax.lax.axis_index(seq_axis)
        q_offset = my_idx * local_len

        batch, heads, t_loc, d = q_loc.shape
        acc = jnp.zeros((batch, heads, t_loc, d), jnp.float32)
        m = jnp.full((batch, heads, t_loc), _NEG_INF, jnp.float32)
        l = jnp.zeros((batch, heads, t_loc), jnp.float32)

        k_cur, v_cur = k_loc, v_loc
        perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
        for step in range(num_shards):  # static unroll: S ring stages
            src = (my_idx - step) % num_shards  # whose block we now hold
            acc, m, l = _block_contract(
                q_loc, k_cur, v_cur, q_offset, src * local_len,
                causal, sm_scale, acc, m, l)
            if step + 1 < num_shards:
                # Rotate K/V around the ring; XLA overlaps the ppermute
                # with the next stage's contraction where possible.
                k_cur = jax.lax.ppermute(k_cur, seq_axis, perm)
                v_cur = jax.lax.ppermute(v_cur, seq_axis, perm)

        l_safe = jnp.where(l > 0, l, 1.0)
        return (acc / l_safe[..., None]).astype(q_loc.dtype)

    spec = P(batch_axis, None, seq_axis, None)
    shmap = shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return shmap(q, k, v)


def ring_attention_padded(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                          causal: bool = True, sm_scale: float | None = None,
                          batch_axis: str | None = None):
    """Ring attention for sequence lengths not divisible by the ring size.

    Pads queries/keys/values with trailing zero tokens up to the next
    multiple of the sp size and slices the output back. Safe under the
    causal mask: padded KEY positions sit strictly after every real query's
    row, so no real output attends to padding; padded QUERY rows produce
    garbage that is sliced off."""
    if not causal:
        raise ConfigError("ring_attention_padded requires causal=True "
                         "(non-causal padding would attend to zero tokens)")
    if batch_axis is not None and q.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None   # odd batch (e.g. eval's batch-1): replicate it
    num_shards = mesh.shape[seq_axis]
    seq = q.shape[2]
    pad = (-seq) % num_shards
    if pad:
        widths = ((0, 0), (0, 0), (0, pad), (0, 0))
        q, k, v = (jnp.pad(x, widths) for x in (q, k, v))
    out = ring_attention(q, k, v, mesh, seq_axis=seq_axis, causal=causal,
                         sm_scale=sm_scale, batch_axis=batch_axis)
    return out[:, :, :seq] if pad else out


def ring_attention_sharded(mesh: Mesh, seq_axis: str = "sp",
                           batch_axis: str | None = None):
    """Convenience partial with the mesh bound (for model wiring); handles
    non-divisible sequence lengths via padding."""
    return functools.partial(ring_attention_padded, mesh=mesh,
                             seq_axis=seq_axis, batch_axis=batch_axis)


def sequence_sharding(mesh: Mesh, seq_axis: str = "sp") -> NamedSharding:
    return NamedSharding(mesh, P(None, None, seq_axis, None))
