"""Sequence-parallel banded attention for the episode-mode transformer.

Ring attention (parallel/ring_attention.py) rotates FULL K/V shards all the
way around the sp axis because causal attention can reach arbitrarily far
back. Banded attention can't: a query's band covers exactly ``window`` keys,
so with the tick sequence sharded over sp (shard length >= window-1) the
band crosses AT MOST ONE shard boundary. The whole exchange collapses to a
single ``ppermute`` of the previous shard's last ``window-1`` K/V rows — a
halo exchange, the cheapest possible sequence-parallel communication
pattern (one neighbor hop on ICI instead of sp-1 rotations).

Alignment trick: after attaching the halo the local keys are
``[halo(window-1) | local(S)]`` while queries are the local S rows. Left-
padding the queries with ``window-1`` zero rows restores ``q_len == kv_len``
with query row j aligned to key row j, and the ordinary causal+banded flash
kernel (ops/attention.py ``local_window``) computes exactly the halo-band
semantics; the pad rows' outputs are sliced off.

Shard 0 has no predecessor: its ``ppermute`` destination is unwritten and
arrives as ZEROS. Zero keys would still receive softmax weight (score 0,
not -inf), so shard 0's first ``window-1`` outputs are CORRECTED exactly:
those queries' bands lie entirely inside the local prefix (query j < w-1
attends keys 0..j), so one small causal pass over the first ``window-1``
local rows computes their true outputs, selected by ``axis_index == 0``.
The function is therefore exact for any caller — not just ones (like
models/transformer_episode.py) whose leading positions are never read.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sharetrade_tpu.config import ConfigError

from sharetrade_tpu.ops.attention import flash_attention
from sharetrade_tpu.parallel.compat import shard_map


def halo_banded_attention_sharded(mesh: Mesh, *, seq_axis: str = "sp",
                                  batch_axis: str | None = None,
                                  use_pallas: bool | None = None):
    """Build ``fn(q, k, v, window) -> out`` attending a banded causal mask
    with the sequence dim sharded over ``mesh``'s ``seq_axis``.

    Shapes are (batch, heads, seq, head_dim); ``batch_axis`` optionally
    shards the batch dim (usually "dp"). The sequence is padded up to a
    multiple of the sp size with zero rows — trailing pad positions are
    later than every real query, so causality keeps them invisible.
    """
    n = mesh.shape[seq_axis]

    def attend(q, k, v, window: int):
        if n == 1 or window == 1:
            # One shard, or a 1-wide band (each query attends only itself:
            # the halo is empty and kl[:, :, -0:] would grab the WHOLE
            # shard) — the local kernel is exact either way.
            return flash_attention(q, k, v, causal=True, local_window=window,
                                   use_pallas=use_pallas)
        seq = q.shape[2]
        pad = (-seq) % n
        if pad:
            widths = [(0, 0), (0, 0), (0, pad), (0, 0)]
            q, k, v = (jnp.pad(x, widths) for x in (q, k, v))
        if (seq + pad) // n < window - 1:
            raise ConfigError(
                f"sp shard length {(seq + pad) // n} < window-1 "
                f"({window - 1}); the halo band would span multiple shards "
                f"— use fewer sp shards or longer unrolls")

        b_axis = batch_axis
        if b_axis is not None and q.shape[0] % mesh.shape[b_axis]:
            b_axis = None   # odd batch (e.g. 1-agent minibatch): replicate
        spec = P(b_axis, None, seq_axis, None)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)
        def sharded(ql, kl, vl):
            halo = window - 1
            perm = [(i, i + 1) for i in range(n - 1)]  # no wrap: shard 0 -> zeros
            halo_k = jax.lax.ppermute(kl[:, :, -halo:], seq_axis, perm)
            halo_v = jax.lax.ppermute(vl[:, :, -halo:], seq_axis, perm)
            kv_k = jnp.concatenate([halo_k, kl], axis=2)
            kv_v = jnp.concatenate([halo_v, vl], axis=2)
            qp = jnp.pad(ql, [(0, 0), (0, 0), (halo, 0), (0, 0)])
            out = flash_attention(qp, kv_k, kv_v, causal=True,
                                  local_window=window, use_pallas=use_pallas)
            out = out[:, :, halo:]
            # Shard 0's zero-filled halo rows would otherwise take softmax
            # weight (score 0, not -inf) in its first `halo` outputs. Those
            # queries' true bands sit entirely inside the local prefix
            # (query j < window-1 attends keys 0..j), so a small plain-causal
            # pass over the first `halo` local rows is their exact answer.
            # O(window^2) per shard vs the O(S*window) main pass; computed
            # everywhere, used only where axis_index == 0.
            head_exact = flash_attention(
                ql[:, :, :halo], kl[:, :, :halo], vl[:, :, :halo],
                causal=True, use_pallas=use_pallas)
            first = (jax.lax.axis_index(seq_axis) == 0)
            head = jnp.where(first, head_exact, out[:, :, :halo])
            return jnp.concatenate([head, out[:, :, halo:]], axis=2)

        out = sharded(q, k, v)
        return out[:, :, :seq] if pad else out

    return attend
