"""Device mesh construction (replacing the Akka Router fan-out, SURVEY.md §2.2).

The reference's "cluster" is 10 actors in one JVM with remoting stubbed
(build.sbt:13, README.md:13). Here scale-out is a named ``jax.sharding.Mesh``:
axes dp/tp/sp/pp/ep are declared up front and shardings annotate how each
tensor spreads over them; XLA inserts the ICI/DCN collectives (scaling-book
recipe: pick a mesh, annotate, let the compiler place communication).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from sharetrade_tpu.config import ParallelConfig
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("parallel.mesh")

AXIS_ORDER = ("dp", "tp", "sp", "pp", "ep")


def mesh_platform(mesh: Mesh) -> str:
    """THE platform probe for a mesh ("cpu" | "tpu" | "gpu" | ...).

    One definition so every platform-keyed carve-out — the CPU no-donation
    seam in ``parallel/sharding.py``, the Pallas-kernel gate in
    ``models/__init__.py`` — keys off the same predicate and can never
    drift (a probe that checked ``jax.default_backend()`` instead of the
    MESH's devices would misfire exactly on the forced-8-device host
    platform the shard audit and the multichip dryrun run on)."""
    return next(iter(mesh.devices.flat)).platform


def is_cpu_mesh(mesh: Mesh) -> bool:
    """True when the mesh is backed by (possibly virtual) CPU devices —
    the forced-8-device host platform of tests/the shard audit, or the
    orchestrator's CPU fallback."""
    return mesh_platform(mesh) == "cpu"


#: Mesh axes whose code paths run shard_map-partitioned programs (sp
#: sequence parallelism, ep expert dispatch) — the axes that can propagate
#: a transposed-mesh spec back onto dp-sharded state.
SHARD_MAP_AXES = ("sp", "ep")


def has_shard_map_axis(mesh: Mesh | None) -> bool:
    """THE scope predicate for the round-8 replicate seams (PPO's
    rollout→update seam, the episode transformer's carry→series pin):
    True when the mesh carries a >1-sized shard_map axis. One definition
    so the two seams can never silently diverge; meshes without such an
    axis compile the permuted gathers clean already and must keep their
    exact (byte-identical) programs."""
    return (mesh is not None
            and any(dict(mesh.shape).get(a, 1) > 1 for a in SHARD_MAP_AXES))


def build_mesh(cfg: ParallelConfig | None = None, devices=None) -> Mesh:
    """Build a mesh from ``cfg.mesh_shape`` (e.g. ``{"dp": 4, "tp": 2}``).

    Empty/missing shape puts every device on the data axis — the moral
    equivalent of the reference's "all workers under one broadcast router".
    Axis sizes must multiply to the device count (a partial mesh would
    silently idle chips).
    """
    cfg = cfg or ParallelConfig()
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)

    shape = dict(cfg.mesh_shape) if cfg.mesh_shape else {}
    if not shape:
        shape = {cfg.data_axis: devices.size}
    names = [a for a in AXIS_ORDER if shape.get(a, 1) > 1]
    if not names:
        names = [cfg.data_axis]
    sizes = [shape.get(a, 1) for a in names]
    total = int(np.prod(sizes))
    if total != devices.size:
        raise ValueError(
            f"mesh shape {dict(zip(names, sizes))} needs {total} devices, "
            f"got {devices.size}")
    mesh = Mesh(devices.reshape(sizes), tuple(names))
    log.info("mesh %s over %d devices", dict(zip(names, sizes)), devices.size)
    return mesh


def _distributed_initialized() -> bool:
    """Version-portable "is the distributed runtime already up?" probe —
    the idempotence guard of :func:`init_distributed`. Newer jax exposes
    ``jax.distributed.is_initialized``; the 0.4.x line on this container
    does not (calling it raised AttributeError, which is what broke
    tests/test_distributed.py's gating tier since seed), but its client
    handle lives at ``jax._src.distributed.global_state.client`` — None
    until initialize() succeeds. An unreadable probe reads as "not
    initialized": the worst case is jax's own loud double-initialize
    error, strictly better than silently skipping bring-up."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     cpu_collectives: str | None = None) -> bool:
    """Multi-host bring-up (the reference's never-built Akka Cluster tier,
    README.md:13, build.sbt:13 akka-remote on the classpath but dormant).

    Three tiers, in precedence order:

    1. Explicit args — manual bring-up on any cluster:
       ``init_distributed("host0:8476", num_processes=2, process_id=i)``
       on every host, then ``build_mesh`` sees the GLOBAL device set and
       shardings spanning hosts ride DCN (jax inserts the cross-host
       collectives; lay dp over hosts, tp/sp within a host so the heavy
       collectives stay on ICI).
    2. Env-gated — ``JAX_COORDINATOR_ADDRESS`` (set by TPU pod runtimes and
       GKE) or ``MEGASCALE_COORDINATOR_ADDRESS``: ``jax.distributed
       .initialize()`` discovers everything from the environment.
    3. No-op — single-process: returns whether jax already reports multiple
       processes.

    ``cpu_collectives`` selects the CPU cross-process collective backend
    ("gloo" or "mpi") — on TPU the collectives ride ICI/DCN and this is
    unused, but it makes the multi-process path runnable (and tested,
    tests/test_distributed.py::TestTwoProcessSmoke) on CPU-only hosts.

    Returns True when running multi-process. Idempotent: a second call after
    successful bring-up is a no-op (jax raises on double-initialize).
    """
    import os
    if _distributed_initialized():
        return jax.process_count() > 1
    if cpu_collectives is not None:
        jax.config.update("jax_cpu_collectives_implementation", cpu_collectives)
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        log.info("distributed: process %d of %d (explicit coordinator %s)",
                 jax.process_index(), jax.process_count(), coordinator_address)
        return jax.process_count() > 1
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
            "MEGASCALE_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()
        log.info("distributed: process %d of %d",
                 jax.process_index(), jax.process_count())
        return True
    return jax.process_count() > 1
