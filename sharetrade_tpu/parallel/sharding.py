"""Sharding rules: how TrainState tensors spread over the mesh.

Replaces the reference's implicit placement (everything in one JVM heap, one
TF session owning the only parameter copy) with explicit PartitionSpecs:

- batch-leading state (env cursors, carries, replay rows) shards over ``dp``;
- parameters/optimizer state replicate by default, or shard over ``tp`` via
  path rules (the mechanism SURVEY.md §2.2 asks for even though the reference
  model is tiny);
- scalars (rng, counters) replicate.

With these in/out shardings on a jitted step, XLA turns the loss mean over
the dp-sharded batch into an ICI all-reduce — the parameter-server mailbox
(QDecisionPolicyActor.scala:54-77) become a collective (SURVEY.md §7.2).

Consistency contract (the anti-resharding tentpole): every path that places,
restores, heals, or steps a TrainState on a mesh resolves its shardings
through :func:`canonical_sharding`, and the compiled step re-pins its output
carry/env_state with ``jax.lax.with_sharding_constraint`` at the chunk seam.
Without the pin, program regions introduced by the sp/pp/ep shard_maps leave
GSPMD free to pick a transposed-mesh layout for the carry mid-program, and
the partitioner then falls back to replicate-then-repartition ("Involuntary
full rematerialization" in the SPMD log) on every chunk — the failure mode
``tools/shard_audit.py`` compiles the whole config matrix to keep out.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sharetrade_tpu.agents.base import TrainState, megachunk_step
from sharetrade_tpu.parallel.mesh import is_cpu_mesh

#: One NamedSharding OBJECT per (mesh, spec): every layer that places or
#: constrains state asks here, so "the same sharding" is identity, not an
#: equality the reader must verify across call sites.
_CANONICAL: dict[tuple[Mesh, P], NamedSharding] = {}


def canonical_sharding(mesh: Mesh, spec: P = P()) -> NamedSharding:
    """THE NamedSharding for (mesh, spec).

    Memoized so the sharding trees built by :func:`train_state_shardings`,
    the orchestrator's place/restore/heal paths, and the in-step
    ``with_sharding_constraint`` pins all hold the identical object — a
    path that constructed its own would still compare equal today, but the
    cache makes the canonical-spec contract structural instead of
    conventional."""
    got = _CANONICAL.get((mesh, spec))
    if got is None:
        if len(_CANONICAL) >= 4096:
            # Ephemeral-mesh processes (the test suite, shard-audit
            # children) would otherwise pin every mesh they ever built for
            # the process lifetime; a flush preserves identity within any
            # live working set (production owns ONE mesh) while bounding
            # retention. (A weak cache doesn't work here: the value holds
            # its mesh, so weak-keying by mesh never collects.)
            _CANONICAL.clear()
        got = _CANONICAL[(mesh, spec)] = NamedSharding(mesh, spec)
    return got


def batch_axis_sharding(mesh: Mesh, data_axis: str = "dp"):
    """P(dp, None, ...) for arrays whose leading dim is the agent batch."""
    return canonical_sharding(mesh, P(data_axis))


def param_shardings(params: Any, mesh: Mesh, rules: dict[str, P] | None = None):
    """Map each param leaf to a NamedSharding.

    ``rules`` maps a '/'-joined path *suffix* to a PartitionSpec, e.g.
    ``{"layer1/w": P(None, "tp"), "layer2/w": P("tp", None)}`` for Megatron-
    style column→row sharding of the MLP. Unmatched leaves replicate.
    """
    rules = rules or {}

    def leaf_sharding(path, leaf):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for suffix, spec in rules.items():
            if key.endswith(suffix):
                return canonical_sharding(mesh, spec)
        return canonical_sharding(mesh, P())

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def mlp_tp_rules(model_axis: str = "tp") -> dict[str, P]:
    """Column-parallel first layer, row-parallel second — one all-reduce at
    the output, the classic Megatron split mapped onto ICI.

    The suffix set covers both MLP families (layer/torso heads) and the
    transformer block projections (qkv column, proj row, mlp_in column,
    mlp_out row), so one rule table serves every model kind; unmatched
    leaves (embeddings, layernorms, heads) replicate."""
    return {
        "layer1/w": P(None, model_axis),
        "layer2/w": P(model_axis, None),
        "torso1/w": P(None, model_axis),
        "torso2/w": P(model_axis, None),
        "qkv/w": P(None, model_axis),
        "proj/w": P(model_axis, None),
        "mlp_in/w": P(None, model_axis),
        "mlp_out/w": P(model_axis, None),
    }


def train_state_shardings(ts: TrainState, mesh: Mesh, *,
                          data_axis: str = "dp",
                          param_rules: dict[str, P] | None = None) -> TrainState:
    """Build the TrainState-shaped pytree of NamedShardings for jit in/out."""
    replicate = canonical_sharding(mesh, P())
    batch = canonical_sharding(mesh, P(data_axis))

    p_shard = param_shardings(ts.params, mesh, param_rules)

    # Optimizer accumulators (AdaGrad sums, Adam moments) embed a params-
    # shaped subtree, so an opt leaf's path *ends with* some param's full
    # path (e.g. `.0.sum_of_squares.layer1.w` ends with `layer1/w`). Match
    # on that path suffix plus shape — never shape alone, which picks the
    # wrong spec when two differently-sharded params share a shape.
    def _path_keys(path):
        return tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    param_items = [
        (_path_keys(path), leaf.shape, sharding)
        for (path, leaf), sharding in zip(
            jax.tree_util.tree_flatten_with_path(ts.params)[0],
            jax.tree.leaves(p_shard))
    ]

    def opt_leaf(path, leaf):
        keys = _path_keys(path)
        for pkeys, pshape, sharding in param_items:
            if (len(keys) >= len(pkeys) and keys[-len(pkeys):] == pkeys
                    and getattr(leaf, "shape", None) == pshape):
                return sharding
        return replicate

    # The agent-batch size identifies which leaves shard over dp: exactly
    # those whose leading dim is the batch (env cursors, carries).
    batch_size = int(ts.env_state.t.shape[0])

    def batched_leaf(leaf):
        shape = getattr(leaf, "shape", ())
        return batch if (len(shape) >= 1 and shape[0] == batch_size) else replicate

    def extras_leaf(path, leaf):
        # Algorithm extras mix params-shaped trees (DQN target net — shard
        # like the matching param), batch-leading arrays (shard over dp),
        # and everything else (replay rows, counters — replicate). Replay
        # buffers replicate unconditionally: their leading dim is capacity,
        # which can coincide with the batch size while the sampling indices
        # assume the whole buffer.
        keys = _path_keys(path)
        if "replay" in keys or "per" in keys:
            # "per": the PER sum-tree + max-priority scalar replicate with
            # the replay arrays they index — the tree's (2L,) leading dim
            # is a capacity, never the batch.
            return replicate
        match = opt_leaf(path, leaf)
        if match is not replicate:
            return match
        return batched_leaf(leaf)

    return TrainState(
        params=p_shard,
        opt_state=jax.tree_util.tree_map_with_path(opt_leaf, ts.opt_state),
        carry=jax.tree.map(batched_leaf, ts.carry),
        env_state=jax.tree.map(batched_leaf, ts.env_state),
        rng=replicate,
        env_steps=replicate,
        updates=replicate,
        extras=(jax.tree_util.tree_map_with_path(extras_leaf, ts.extras)
                if ts.extras is not None else None),
    )


def constrain_train_state(ts: TrainState, shardings: TrainState) -> TrainState:
    """Pin the BATCH-CARRIED TrainState leaves — ``carry`` (notably the
    episode transformer's ``hist`` buffer) and ``env_state`` — to their
    canonical shardings INSIDE a traced program
    (``jax.lax.with_sharding_constraint``). The seam this serves: between
    the shard_map regions of the sp/ring/pipeline/MoE paths and the
    surrounding dataflow, GSPMD may otherwise re-derive a transposed-mesh
    layout for the carry (e.g. ``carry['hist']`` [dp,1,sp] → [1,sp,dp]) and
    bridge it with a full replicate-then-repartition per chunk.

    Deliberately NOT the whole state: params/opt_state are loop-invariant
    inside a megachunk scan and already pinned by the outer jit's in/out
    shardings — re-constraining them mid-scan makes GSPMD materialize the
    constraint (measured +8 all-gathers on the dp4×tp2 bench_reshard
    workload) instead of leaving the tp-sharded layout untouched."""
    return ts.replace(
        carry=jax.lax.with_sharding_constraint(ts.carry, shardings.carry),
        env_state=jax.lax.with_sharding_constraint(ts.env_state,
                                                   shardings.env_state))


def _constrained(step_fn, shardings: TrainState):
    """Wrap a chunk step so its OUTPUT TrainState is re-pinned to the
    canonical specs. Composed UNDER ``megachunk_step``, this pins the
    lax.scan carry at every inner-chunk seam — the K-1 seams that have no
    jit in/out shardings of their own and where an involuntary reshard
    would otherwise be paid K times per dispatch."""

    def step(ts: TrainState):
        new_ts, metrics = step_fn(ts)
        return constrain_train_state(new_ts, shardings), metrics

    return step


def jit_parallel_step(agent, mesh: Mesh, ts: TrainState, *,
                      data_axis: str = "dp",
                      param_rules: dict[str, P] | None = None,
                      megachunk_factor: int = 1,
                      constrain: bool = True,
                      donate: bool = True,
                      cost_hook=None):
    """Build the jitted (uncalled) partitioned chunk program and its
    sharding tree: ``(shardings, jitted_fn)``.

    The ONE construction shared by :func:`make_parallel_step` (which
    executes it) and ``tools/shard_audit.py`` / ``bench.py bench_reshard``
    (which ``.lower(...).compile()`` it to inspect SPMD warnings, HLO
    collectives and memory) — so what the audit certifies is byte-for-byte
    the program the orchestrator dispatches.

    Sharding decisions:

    - in_shardings: the canonical TrainState tree (params by rule, batch-
      leading leaves over ``data_axis``, scalars replicated).
    - out_shardings: the same tree for the TrainState; ``None`` (GSPMD-
      chosen) for the metrics. Forcing the metrics to replicate — the old
      behavior — inserted an all-gather INSIDE the fused program for any
      batch-shaped metric leaf (DQN's journaled ``(K, T, B, ...)``
      transitions); leaving them unspecified keeps them shard-resident
      until the orchestrator's single batched ``device_get`` readback,
      which assembles on the host for free.
    - ``constrain`` (``parallel.shard_constraints``): re-pin the output
      state inside the program (see :func:`_constrained`); off only for
      the bench's with/without comparison.

    ``cost_hook`` (the ``obs.roofline`` seam): called once, after the jit
    wrapper is built, as ``cost_hook(fn, (ts,),
    megachunk_factor=megachunk_factor, devices=<mesh size>)`` —
    obs/roofline.py AOT-lowers the
    program there and records its XLA cost/memory analysis, so the costs
    the roofline gauges report belong to byte-for-byte the program the
    orchestrator dispatches (the same identity guarantee the shard audit
    relies on). Compile-time only: the hook must never ride a dispatch.
    """
    sh = train_state_shardings(ts, mesh, data_axis=data_axis,
                               param_rules=param_rules)
    step_fn = _constrained(agent.step, sh) if constrain else agent.step
    if megachunk_factor > 1:
        step_fn = megachunk_step(step_fn, megachunk_factor)
    # NO donation for a fused megachunk on CPU devices: donating the
    # TrainState into the lax.scan corrupts the heap on the CPU runtime
    # (use-after-free once checkpoint restores interleave with megachunk
    # dispatches — same hazard the orchestrator's CPU-fallback seam avoids).
    # ``donate=False`` extends the same carve-out to the async-pipeline
    # orchestrator on CPU meshes: a consumer-thread device_get concurrent
    # with a donating dispatch segfaults the CPU runtime the same way.
    # Accelerator meshes keep donation, where HBM double-buffering matters.
    argnums = ((0,) if donate
               and not (megachunk_factor > 1 and is_cpu_mesh(mesh))
               else ())
    fn = jax.jit(step_fn, in_shardings=(sh,), out_shardings=(sh, None),
                 donate_argnums=argnums)
    if cost_hook is not None:
        # devices: cost_analysis() describes the PER-DEVICE partition of
        # the SPMD program; the hook needs the mesh size to relate it to
        # the analytic (global-work) model.
        cost_hook(fn, (ts,), megachunk_factor=megachunk_factor,
                  devices=mesh.devices.size)
    return sh, fn


def make_parallel_step(agent, mesh: Mesh, *, data_axis: str = "dp",
                       param_rules: dict[str, P] | None = None,
                       megachunk_factor: int = 1,
                       constrain: bool = True,
                       donate: bool = True,
                       cost_hook=None):
    """jit the agent's chunk step with mesh shardings.

    Returns ``(place, step)``: ``place(ts)`` device_puts a freshly-initialized
    TrainState onto the mesh; ``step`` is the compiled chunk function with
    donated input (the TrainState is consumed each call — no HBM double-
    buffering of parameters).

    ``megachunk_factor`` K > 1 composes the device-resident megachunk
    (agents/base.py ``megachunk_step``) INSIDE the pjit boundary: the
    K-chunk ``lax.scan`` is one partitioned program, so the ICI collectives
    of consecutive inner chunks stay fused (no host round-trip re-dispatches
    them) and the host pays one dispatch per K chunks. Metrics return
    stacked ``(K, ...)`` with GSPMD-chosen (shard-resident) layouts; see
    :func:`jit_parallel_step` for the sharding contract, including the
    per-inner-chunk carry pin that keeps the scan free of involuntary
    resharding."""
    cache: dict[str, Any] = {}  # sharding pytree + jitted fn, built once

    def _ensure(ts):
        if "fn" not in cache:
            cache["sh"], cache["fn"] = jit_parallel_step(
                agent, mesh, ts, data_axis=data_axis,
                param_rules=param_rules, megachunk_factor=megachunk_factor,
                constrain=constrain, donate=donate, cost_hook=cost_hook)
        return cache

    def place(ts: TrainState) -> TrainState:
        return jax.device_put(ts, _ensure(ts)["sh"])

    def compiled(ts):
        return _ensure(ts)["fn"](ts)

    return place, compiled
