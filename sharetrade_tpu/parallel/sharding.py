"""Sharding rules: how TrainState tensors spread over the mesh.

Replaces the reference's implicit placement (everything in one JVM heap, one
TF session owning the only parameter copy) with explicit PartitionSpecs:

- batch-leading state (env cursors, carries, replay rows) shards over ``dp``;
- parameters/optimizer state replicate by default, or shard over ``tp`` via
  path rules (the mechanism SURVEY.md §2.2 asks for even though the reference
  model is tiny);
- scalars (rng, counters) replicate.

With these in/out shardings on a jitted step, XLA turns the loss mean over
the dp-sharded batch into an ICI all-reduce — the parameter-server mailbox
(QDecisionPolicyActor.scala:54-77) become a collective (SURVEY.md §7.2).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sharetrade_tpu.agents.base import TrainState, megachunk_step


def batch_axis_sharding(mesh: Mesh, data_axis: str = "dp"):
    """P(dp, None, ...) for arrays whose leading dim is the agent batch."""
    return NamedSharding(mesh, P(data_axis))


def param_shardings(params: Any, mesh: Mesh, rules: dict[str, P] | None = None):
    """Map each param leaf to a NamedSharding.

    ``rules`` maps a '/'-joined path *suffix* to a PartitionSpec, e.g.
    ``{"layer1/w": P(None, "tp"), "layer2/w": P("tp", None)}`` for Megatron-
    style column→row sharding of the MLP. Unmatched leaves replicate.
    """
    rules = rules or {}

    def leaf_sharding(path, leaf):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for suffix, spec in rules.items():
            if key.endswith(suffix):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def mlp_tp_rules(model_axis: str = "tp") -> dict[str, P]:
    """Column-parallel first layer, row-parallel second — one all-reduce at
    the output, the classic Megatron split mapped onto ICI.

    The suffix set covers both MLP families (layer/torso heads) and the
    transformer block projections (qkv column, proj row, mlp_in column,
    mlp_out row), so one rule table serves every model kind; unmatched
    leaves (embeddings, layernorms, heads) replicate."""
    return {
        "layer1/w": P(None, model_axis),
        "layer2/w": P(model_axis, None),
        "torso1/w": P(None, model_axis),
        "torso2/w": P(model_axis, None),
        "qkv/w": P(None, model_axis),
        "proj/w": P(model_axis, None),
        "mlp_in/w": P(None, model_axis),
        "mlp_out/w": P(model_axis, None),
    }


def train_state_shardings(ts: TrainState, mesh: Mesh, *,
                          data_axis: str = "dp",
                          param_rules: dict[str, P] | None = None) -> TrainState:
    """Build the TrainState-shaped pytree of NamedShardings for jit in/out."""
    replicate = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P(data_axis))

    p_shard = param_shardings(ts.params, mesh, param_rules)

    # Optimizer accumulators (AdaGrad sums, Adam moments) embed a params-
    # shaped subtree, so an opt leaf's path *ends with* some param's full
    # path (e.g. `.0.sum_of_squares.layer1.w` ends with `layer1/w`). Match
    # on that path suffix plus shape — never shape alone, which picks the
    # wrong spec when two differently-sharded params share a shape.
    def _path_keys(path):
        return tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    param_items = [
        (_path_keys(path), leaf.shape, sharding)
        for (path, leaf), sharding in zip(
            jax.tree_util.tree_flatten_with_path(ts.params)[0],
            jax.tree.leaves(p_shard))
    ]

    def opt_leaf(path, leaf):
        keys = _path_keys(path)
        for pkeys, pshape, sharding in param_items:
            if (len(keys) >= len(pkeys) and keys[-len(pkeys):] == pkeys
                    and getattr(leaf, "shape", None) == pshape):
                return sharding
        return replicate

    # The agent-batch size identifies which leaves shard over dp: exactly
    # those whose leading dim is the batch (env cursors, carries).
    batch_size = int(ts.env_state.t.shape[0])

    def batched_leaf(leaf):
        shape = getattr(leaf, "shape", ())
        return batch if (len(shape) >= 1 and shape[0] == batch_size) else replicate

    def extras_leaf(path, leaf):
        # Algorithm extras mix params-shaped trees (DQN target net — shard
        # like the matching param), batch-leading arrays (shard over dp),
        # and everything else (replay rows, counters — replicate). Replay
        # buffers replicate unconditionally: their leading dim is capacity,
        # which can coincide with the batch size while the sampling indices
        # assume the whole buffer.
        keys = _path_keys(path)
        if "replay" in keys:
            return replicate
        match = opt_leaf(path, leaf)
        if match is not replicate:
            return match
        return batched_leaf(leaf)

    return TrainState(
        params=p_shard,
        opt_state=jax.tree_util.tree_map_with_path(opt_leaf, ts.opt_state),
        carry=jax.tree.map(batched_leaf, ts.carry),
        env_state=jax.tree.map(batched_leaf, ts.env_state),
        rng=replicate,
        env_steps=replicate,
        updates=replicate,
        extras=(jax.tree_util.tree_map_with_path(extras_leaf, ts.extras)
                if ts.extras is not None else None),
    )


def make_parallel_step(agent, mesh: Mesh, *, data_axis: str = "dp",
                       param_rules: dict[str, P] | None = None,
                       megachunk_factor: int = 1):
    """jit the agent's chunk step with mesh shardings.

    Returns ``(place, step)``: ``place(ts)`` device_puts a freshly-initialized
    TrainState onto the mesh; ``step`` is the compiled chunk function with
    donated input (the TrainState is consumed each call — no HBM double-
    buffering of parameters).

    ``megachunk_factor`` K > 1 composes the device-resident megachunk
    (agents/base.py ``megachunk_step``) INSIDE the pjit boundary: the
    K-chunk ``lax.scan`` is one partitioned program, so the ICI collectives
    of consecutive inner chunks stay fused (no host round-trip re-dispatches
    them) and the host pays one dispatch per K chunks. Metrics return
    stacked ``(K, ...)``, replicated — the out-sharding spec is rank-
    agnostic, so the same replicate spec covers both shapes.
    """
    replicate = NamedSharding(mesh, P())
    step_fn = (agent.step if megachunk_factor <= 1
               else megachunk_step(agent.step, megachunk_factor))
    # NO donation for a fused megachunk on CPU devices: donating the
    # TrainState into the lax.scan corrupts the heap on the CPU runtime
    # (use-after-free once checkpoint restores interleave with megachunk
    # dispatches — same hazard the orchestrator's CPU-fallback path avoids).
    # Accelerator meshes keep donation, where HBM double-buffering matters.
    donate = (() if megachunk_factor > 1
              and next(iter(mesh.devices.flat)).platform == "cpu"
              else (0,))
    cache: dict[str, Any] = {}  # sharding pytree + jitted fn, built once

    def _ensure(ts):
        if "fn" not in cache:
            sh = train_state_shardings(ts, mesh, data_axis=data_axis,
                                       param_rules=param_rules)
            cache["sh"] = sh
            cache["fn"] = jax.jit(step_fn, in_shardings=(sh,),
                                  out_shardings=(sh, replicate),
                                  donate_argnums=donate)
        return cache

    def place(ts: TrainState) -> TrainState:
        return jax.device_put(ts, _ensure(ts)["sh"])

    def compiled(ts):
        return _ensure(ts)["fn"](ts)

    return place, compiled
