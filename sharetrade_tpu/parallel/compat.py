"""jax API compatibility for the parallel layer.

The partitioned paths target the current jax surface — ``jax.shard_map``
with the ``check_vma`` keyword. Older toolchains (jax 0.4.x, still common
on CPU-only CI hosts) ship the SAME primitive as
``jax.experimental.shard_map.shard_map`` with the keyword spelled
``check_rep``. One resolver here so every sp/pp/ep path — and the shard
audit that compiles them on a forced-8-device host platform — runs on
both, instead of each call site growing its own try/except.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when the toolchain has it, else the 0.4.x
    ``jax.experimental.shard_map`` spelling (``check_vma`` → ``check_rep``:
    same per-output replication check, renamed upstream)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
