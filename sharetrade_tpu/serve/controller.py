"""Online serve controller: the telemetry loop closed into the knobs.

The engine already measures everything an operator would tune
``batch_timeout_ms``/``max_queue`` by hand from — the windowed end-to-end
latency histogram behind ``serve_p99_ms``, the shed/reject/expiry
counters, the queue-depth gauge. This module is the actuator
(ROADMAP item 5's online tier): a feedback loop that holds a target
request p99 under whatever the measured arrival rate is doing, by
tightening the same two knobs a human would, with the same discipline the
PR-11 burn-rate alerts use (hysteresis, never spam):

- **objective**: the p99 of the engine's end-to-end latency histogram
  over the controller's own window (snapshot deltas — cumulative bucket
  counts subtract exactly, the ``serve_p99_ms`` math);
- **dead band + hysteresis**: above ``target_p99_ms`` the controller
  TIGHTENS; below ``rearm_frac * target`` it RELAXES back toward the
  configured values; in between it holds. The gap between the two
  thresholds is what keeps a noisy p99 hovering near the target from
  flapping the knobs (tests/test_autotune.py pins no-oscillation on a
  noisy synthetic series);
- **bounded, rate-limited steps**: multiplicative factors per tick
  (``shrink``/``grow``), at most ONE adjustment per
  ``interval_s`` — a controller that can slam a knob to its floor in one
  tick amplifies its own measurement noise;
- **config is the ceiling**: :meth:`ServeEngine.set_knobs` clamps both
  knobs to their configured values, so the controller can only ever
  TIGHTEN below what the operator allowed — it may shrink the coalescing
  wait and the admission bound (trading shed rate for queueing delay),
  and it may restore them, but it can never grow host memory or batching
  latency past config. It never touches shed policy, deadlines,
  supervision, or the swap breaker (the safety rails it must not fight —
  the chaos soak runs green with the controller ON).

Every adjustment is visible: knob gauges (``serve_knob_*``), the
``serve_controller_adjustments_total`` counter, a
``serve_controller_p99_ms`` objective gauge, and a flight-ring event per
adjustment when obs is attached.

Deterministic by construction: :meth:`step` takes an optional fake ``now``
and :meth:`_decide` is a pure function of (p99, knobs), so the state
machine unit-tests run on a fake clock with synthetic objective series —
no engine, no threads, no sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Any, NamedTuple

from sharetrade_tpu.config import ConfigError
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("serve.controller")

#: Counters whose deltas mean "the engine refused/expired work this
#: window" — the overload signal published next to the objective gauge.
_BAD_COUNTERS = ("serve_shed_total", "serve_queue_rejected_total",
                 "serve_deadline_expired_total")

#: Snap-to-floor threshold for the multiplicative timeout shrink (ms): a
#: geometric decay never REACHES the floor, and sub-50 µs coalescing
#: waits are indistinguishable from 0 on a host scheduler.
_TIMEOUT_SNAP_MS = 0.05

#: Additive escape for growing a timeout back off the 0 floor (ms):
#: multiplicative growth of 0 is 0 forever.
_TIMEOUT_GROW_FLOOR_MS = 0.25


class Adjustment(NamedTuple):
    """One applied knob change (the :meth:`ServeController.step` return
    value and the flight-ring payload)."""

    action: str                 # "tighten" | "relax"
    p99_ms: float
    batch_timeout_ms: float
    max_queue: int


class ServeController:
    """See the module docstring. Duck-typed against the engine surface
    (``cfg`` / ``knobs`` / ``set_knobs`` / ``registry`` /
    ``queue_depth`` / ``latency_histogram``), so tests drive it with a
    stub engine and a fake clock."""

    def __init__(self, engine: Any, *, target_p99_ms: float,
                 interval_s: float = 1.0, shrink: float = 0.5,
                 grow: float = 1.25, rearm_frac: float = 0.5,
                 min_batch_timeout_ms: float = 0.0,
                 min_queue: int | None = None, obs: Any = None,
                 clock=time.perf_counter):
        if target_p99_ms <= 0:
            raise ConfigError(
                f"tuning.target_p99_ms must be > 0, got {target_p99_ms}")
        if interval_s <= 0:
            raise ConfigError(
                f"tuning.controller_interval_s must be > 0, got "
                f"{interval_s}")
        if not 0.0 < shrink < 1.0 or grow <= 1.0:
            raise ConfigError(
                f"controller steps need 0 < shrink < 1 < grow, got "
                f"shrink={shrink} grow={grow}")
        if not 0.0 < rearm_frac < 1.0:
            raise ConfigError(
                f"controller rearm_frac must be in (0, 1), got "
                f"{rearm_frac}")
        self.engine = engine
        self.target_p99_ms = float(target_p99_ms)
        self.interval_s = float(interval_s)
        self._shrink = float(shrink)
        self._grow = float(grow)
        self._rearm_frac = float(rearm_frac)
        cfg = engine.cfg
        # Config values are the CEILINGS (set_knobs re-clamps anyway;
        # kept here so _decide is pure and the tests see the same bounds).
        self._ceil_timeout = float(cfg.batch_timeout_ms)
        self._ceil_queue = int(cfg.max_queue)
        self._min_timeout = max(0.0, float(min_batch_timeout_ms))
        # Queue floor: at least one full batch — admission below the
        # batch size starves occupancy without improving the tail.
        floor = int(min_queue) if min_queue else max(int(cfg.max_batch), 1)
        self._min_queue = max(1, min(floor, self._ceil_queue))
        self._obs = obs
        self._clock = clock
        self._hist = engine.latency_histogram
        self._prev_counts = self._hist.snapshot()["counts"]
        self._prev_bad = self._bad_total()
        self._last = clock()
        self.adjustments = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        engine.registry.record("serve_controller_target_p99_ms",
                               self.target_p99_ms)

    # -- thread plumbing --------------------------------------------------

    def start(self) -> "ServeController":
        """Run :meth:`step` every ``interval_s`` on a daemon thread (the
        wait rides the stop event — lint check 10: no sleeps in serve/)."""
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:   # noqa: BLE001 — a controller fault must
                # degrade to "knobs stop adapting", never kill serving.
                log.exception("serve controller step failed; holding "
                              "current knobs")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)

    # -- the control loop -------------------------------------------------

    def _bad_total(self) -> float:
        counters = self.engine.registry.counters()
        return sum(counters.get(name, 0.0) for name in _BAD_COUNTERS)

    def window_p99(self) -> tuple[float | None, int]:
        """(p99 of the completions since the last call, count) — None
        when nothing completed in the window (no signal: hold)."""
        snap = self._hist.snapshot()
        delta = [a - b for a, b in zip(snap["counts"], self._prev_counts)]
        self._prev_counts = snap["counts"]
        completed = sum(delta)
        if completed <= 0:
            return None, 0
        return self._hist.quantile(0.99, counts=delta), completed

    def _decide(self, p99_ms: float | None, overloaded: bool, knobs: Any
                ) -> tuple[str, float, int] | None:
        """The pure state machine: (action, new_timeout, new_queue) or
        None (hold). Dead band [rearm_frac*target, target] = no action;
        both directions take ONE bounded multiplicative step, clamped to
        [floors, configured ceilings]. ``overloaded`` (any shed/reject/
        expiry in the window, or a pinned queue) VETOES relaxing: with
        tight admission, a low p99 is the tight knobs' doing, and
        relaxing while still shedding re-inflates the tail — the
        oscillation this veto exists to prevent (pinned by the
        no-oscillation test)."""
        if p99_ms is None:
            return None
        cur_t, cur_q = knobs.batch_timeout_ms, knobs.max_queue
        if p99_ms > self.target_p99_ms:
            # Over budget: cut the coalescing wait (the direct latency
            # lever) and the admission bound (queueing delay ~ depth /
            # service rate) together, one bounded step each.
            new_t = max(self._min_timeout, cur_t * self._shrink)
            if new_t < _TIMEOUT_SNAP_MS:
                new_t = self._min_timeout
            new_q = max(self._min_queue, int(cur_q * self._shrink))
            if new_t != cur_t or new_q != cur_q:
                return ("tighten", new_t, new_q)
            return None             # already at the floors: shed is the
            # remaining relief valve (admission control's territory)
        if (not overloaded
                and p99_ms < self._rearm_frac * self.target_p99_ms):
            # Clearly under budget (the hysteresis re-arm threshold) AND
            # a shed-free window: give back what was taken — toward the
            # ceilings, never past.
            new_t = min(self._ceil_timeout,
                        max(cur_t * self._grow,
                            min(_TIMEOUT_GROW_FLOOR_MS,
                                self._ceil_timeout)))
            new_q = min(self._ceil_queue,
                        max(int(cur_q * self._grow), cur_q + 1))
            if new_t != cur_t or new_q != cur_q:
                return ("relax", new_t, new_q)
        return None                 # dead band (or at the ceilings): hold

    def step(self, now: float | None = None) -> Adjustment | None:
        """One controller tick: window the objective, decide, actuate.
        Rate-limited — a call before ``interval_s`` has elapsed since the
        last ACTED tick returns None without reading the histogram (the
        window stays intact for the on-time tick). Returns the applied
        :class:`Adjustment` or None."""
        now = self._clock() if now is None else now
        if now - self._last < self.interval_s:
            return None
        self._last = now
        p99, completed = self.window_p99()
        bad = self._bad_total()
        bad_delta = bad - self._prev_bad
        self._prev_bad = bad
        knobs = self.engine.knobs
        registry = self.engine.registry
        overloaded = (bad_delta > 0
                      or self.engine.queue_depth() >= knobs.max_queue)
        gauges = {
            "serve_controller_window_completed": float(completed),
            "serve_controller_window_bad": float(bad_delta),
        }
        if p99 is not None:
            # The last objective reading, as a gauge (cli obs "tuning").
            gauges["serve_controller_p99_ms"] = p99
        registry.record_many(gauges)
        decision = self._decide(p99, overloaded, knobs)
        if decision is None:
            return None
        action, new_t, new_q = decision
        new = self.engine.set_knobs(batch_timeout_ms=new_t, max_queue=new_q)
        self.adjustments += 1
        registry.inc("serve_controller_adjustments_total")
        adj = Adjustment(action=action, p99_ms=float(p99),
                         batch_timeout_ms=new.batch_timeout_ms,
                         max_queue=new.max_queue)
        log.info("serve controller %s: p99 %.1f ms vs target %.1f -> "
                 "batch_timeout_ms=%.3g max_queue=%d", action, p99,
                 self.target_p99_ms, new.batch_timeout_ms, new.max_queue)
        if self._obs is not None:
            # Flight-ring visibility: every adjustment is an event, so a
            # post-incident bundle shows WHAT the controller did and on
            # which objective reading (gated off internally when the
            # recorder is off).
            self._obs.record("serve_controller_adjust", action=action,
                             p99_ms=round(float(p99), 3),
                             window_completed=completed,
                             window_bad=bad_delta,
                             batch_timeout_ms=new.batch_timeout_ms,
                             max_queue=new.max_queue)
        return adj
