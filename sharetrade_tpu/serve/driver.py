"""Synthetic portfolio-session load for the serving tier.

One :class:`SessionSim` is one "user": a cursor into a price series plus a
host-side portfolio that follows the served actions (the user obeys the
policy — the same trade rules as ``env/trading.py`` applied on the host).
Thousands of them replayed against a :class:`~sharetrade_tpu.serve.engine.
ServeEngine` are the load shape the ISSUE's soak generates, with staggered
series offsets so sessions are genuinely heterogeneous (different episode
clocks, different portfolios — exactly what the per-row serve batch
handles and a lockstep training batch cannot).

Two measurement harnesses, both engine-agnostic (anything with the
``submit(session_id, obs, callback=) -> handle`` surface):

- :func:`run_closed_loop` — ``concurrency`` sessions each keep exactly one
  request in flight (submit-on-completion). ``concurrency=1`` against
  :class:`BatchOneServer` is THE batch=1 closed-loop baseline: one
  dispatch, one blocking readback per request — the per-request server the
  continuous-batching engine replaces.
- :func:`run_open_loop` — arrivals at a fixed offered rate regardless of
  completions (the "heavy traffic" shape): sessions without an in-flight
  request are scheduled round-robin; when every session is busy the
  arrival is counted ``dropped`` (the queue already holds one request per
  live session — unbounded pile-up would measure the generator, not the
  server).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from sharetrade_tpu.env.trading import BUY, SELL
from sharetrade_tpu.serve.engine import latency_percentiles
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("serve.driver")


class SessionSim:
    """One synthetic user session over a price series."""

    def __init__(self, session_id: Any, prices: np.ndarray, window: int,
                 start: int, *, budget: float = 2400.0, shares: float = 0.0):
        self.session_id = session_id
        self.prices = prices
        self.window = window
        self.start = int(start)
        self.t = 0
        self.budget = float(budget)
        self.shares = float(shares)
        self.generation = 0         # bumps on wrap → fresh session id

    @property
    def sid(self) -> Any:
        """The WIRE session id: wraps restart the episode under a new id
        (user churn — naturally exercises eviction + cold re-admission)."""
        return (self.session_id if self.generation == 0
                else f"{self.session_id}#{self.generation}")

    def observation(self) -> np.ndarray:
        lo = self.start + self.t
        return np.concatenate(
            [self.prices[lo:lo + self.window],
             np.asarray([self.budget, self.shares], np.float32)]
        ).astype(np.float32)

    def advance(self, action: int) -> None:
        """Apply the served action with the env's trade rules, move one
        tick; restart (new generation, fresh portfolio) at series end."""
        price = float(self.prices[self.start + self.t + self.window])
        if action == BUY and self.budget >= price:
            self.budget -= price
            self.shares += 1.0
        elif action == SELL and self.shares > 0:
            self.budget += price
            self.shares -= 1.0
        self.t += 1
        if self.start + self.t + self.window >= len(self.prices):
            self.t = 0
            self.budget = 2400.0
            self.shares = 0.0
            self.generation += 1


def make_sessions(prices: Any, window: int, n: int, *,
                  seed: int = 0, prefix: str = "s") -> list[SessionSim]:
    """``n`` sessions with staggered starts across the series. ``prefix``
    namespaces the session ids — measurement phases that share one engine
    must not reuse ids, or a "fresh" session would silently hit its
    predecessor's still-warm slot carry instead of prefilling."""
    prices = np.asarray(prices, np.float32)
    horizon = len(prices) - window - 1
    if horizon < 1:
        raise ValueError(f"price series too short for window={window}")
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, max(horizon - 1, 1), size=n)
    return [SessionSim(f"{prefix}{i}", prices, window, starts[i])
            for i in range(n)]


class BatchOneServer:
    """The per-request-dispatch baseline: same submit surface as
    :class:`ServeEngine`, but every request is one jitted B=1 ``apply``
    with a blocking readback, carries threaded per session on the host —
    the closed-loop batch=1 server ``bench_serve`` compares against."""

    #: Bound on retained per-session carries: wrapped sessions mint fresh
    #: generation-suffixed ids, so an unbounded dict would leak every dead
    #: generation's K/V carry over a long soak — evicted LRU like the
    #: engine's slot pool (the baseline must not slow down from its own
    #: memory growth mid-comparison).
    MAX_CARRIES = 4096

    def __init__(self, model: Any, params: Any, *, precision=None):
        from collections import OrderedDict

        from sharetrade_tpu.precision import FP32
        precision = precision or FP32
        self.model = model
        self._params = jax.device_put(precision.cast_compute(params))
        self._carry0 = precision.cast_carry(model.init_carry(), model)
        self._apply = jax.jit(model.apply)
        self._carries: "OrderedDict[Any, Any]" = OrderedDict()
        self._q: "deque[tuple]" = deque()  # trace-buffer-ok: closed-loop
        # harness bounds in-flight requests at its concurrency
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(target=self._loop,
                                        name="b1-server", daemon=True)
        self._thread.start()

    def warmup(self) -> None:
        obs_dim = getattr(self.model, "obs_dim", 0) or 3
        out, _ = self._apply(self._params,
                             np.full((obs_dim,), 10.0, np.float32),
                             self._carry0)
        np.asarray(out.logits)

    def submit(self, session_id: Any, obs: Any,
               callback: Callable | None = None):
        event = threading.Event()
        slot: list = [None]
        with self._cv:
            self._q.append((session_id, np.asarray(obs, np.float32),
                            callback, event, slot, time.perf_counter()))
            self._cv.notify()

        class _H:                   # minimal handle mirroring _Request
            def wait(_self, timeout=None):
                event.wait(timeout)
                return slot[0]
        return _H()

    def drain(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._q:
                    return True
            time.sleep(0.002)   # serve-block-ok: baseline server's drain
            # poll, caller's thread — not an engine dispatch path.
        return False

    def stop(self, **_kw) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._thread.join(10.0)

    def _loop(self) -> None:
        from sharetrade_tpu.serve.engine import ServeResult
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait(0.05)
                if self._stopped and not self._q:
                    return
                if not self._q:
                    continue
                sid, obs, callback, event, slot, t_enq = self._q.popleft()
            carry = self._carries.get(sid)
            if carry is None:
                carry = self._carry0
            else:
                self._carries.move_to_end(sid)
            out, carry = self._apply(self._params, obs, carry)
            logits = np.asarray(out.logits)      # the per-request sync
            self._carries[sid] = carry
            if len(self._carries) > self.MAX_CARRIES:
                self._carries.popitem(last=False)
            result = ServeResult(
                session_id=sid, action=int(np.argmax(logits)),
                logits=logits, value=float(np.asarray(out.value)),
                params_step=0,
                latency_ms=(time.perf_counter() - t_enq) * 1e3)
            slot[0] = result
            event.set()
            if callback is not None:
                callback(result)


_percentiles = latency_percentiles   # one quantile convention, serve-wide


def run_closed_loop(server: Any, sessions: list[SessionSim], *,
                    concurrency: int, duration_s: float,
                    stop: threading.Event | None = None) -> dict:
    """``concurrency`` sessions each keep one request in flight for
    ``duration_s``; returns achieved QPS + latency percentiles (plus a
    ``failed`` count — requests that terminated without a result: batch
    failures, sheds, deadline expiries)."""
    lock = threading.Lock()
    lat: list[float] = []
    done_evt = threading.Event()
    state = {"inflight": 0, "failed": 0}
    #: Sessions whose request FAILED, parked for the main thread to
    #: resubmit. An overload-shedding engine completes a rejected submit
    #: synchronously on the submitting thread — resubmitting from inside
    #: the callback would recurse submit→reject→callback→submit without
    #: bound under sustained overload, so the failure path always defers.
    retry: deque[SessionSim] = deque()  # trace-buffer-ok: at most one
    # parked entry per session (submit-on-completion harness)
    t_end = time.perf_counter() + duration_s

    def cb_for(sess: SessionSim):
        def cb(result, _sess=sess):
            if result is not None:
                with lock:
                    lat.append(result.latency_ms)
                _sess.advance(result.action)
                now = time.perf_counter()
                if now < t_end and not (stop is not None
                                        and stop.is_set()):
                    try:
                        server.submit(_sess.sid, _sess.observation(), cb)
                        return
                    except Exception:   # noqa: BLE001 — engine stopped
                        # or terminally failed between the completion and
                        # this resubmit: retire the session below instead
                        # of letting the engine's callback guard swallow
                        # the raise and strand done_evt.
                        pass
            else:
                with lock:
                    state["failed"] += 1
                now = time.perf_counter()
                if now < t_end and not (stop is not None
                                        and stop.is_set()):
                    with lock:
                        retry.append(_sess)
                    return
            with lock:
                state["inflight"] -= 1
                if state["inflight"] == 0:
                    done_evt.set()
        return cb

    t0 = time.perf_counter()
    with lock:
        state["inflight"] = min(concurrency, len(sessions))
    for sess in sessions[:concurrency]:
        server.submit(sess.sid, sess.observation(), cb_for(sess))
    deadline = time.monotonic() + duration_s + 30.0
    while not done_evt.is_set() and time.monotonic() < deadline:
        with lock:
            parked = list(retry)
            retry.clear()
        if parked:
            now = time.perf_counter()
            for sess in parked:
                resubmitted = False
                if now < t_end and not (stop is not None
                                        and stop.is_set()):
                    try:
                        server.submit(sess.sid, sess.observation(),
                                      cb_for(sess))
                        resubmitted = True
                    except Exception:   # noqa: BLE001 — engine gone
                        # terminal mid-harness: retire the session, keep
                        # the measurement loop accountable.
                        pass
                if not resubmitted:
                    with lock:
                        state["inflight"] -= 1
                        if state["inflight"] == 0:
                            done_evt.set()
        done_evt.wait(0.01)
    elapsed = time.perf_counter() - t0
    with lock:
        n = len(lat)
        failed = state["failed"]
    return {"mode": "closed_loop", "concurrency": concurrency,
            "completed": n, "failed": failed, "elapsed_s": elapsed,
            "qps": n / max(elapsed, 1e-9), **_percentiles(lat)}


def run_open_loop(server: Any, sessions: list[SessionSim], *,
                  rate_qps: float, duration_s: float,
                  stop: threading.Event | None = None) -> dict:
    """Offered-rate arrivals for ``duration_s``: each arrival picks the
    next session with no request in flight (round-robin); arrivals finding
    every session busy count as ``dropped``. Returns offered vs achieved
    QPS + latency percentiles."""
    lock = threading.Lock()
    lat: list[float] = []
    ready: deque[SessionSim] = deque(sessions)  # trace-buffer-ok: holds at
    # most the fixed session population
    offered = dropped = 0
    inflight = {"n": 0, "failed": 0, "last_done": time.perf_counter()}
    idle_evt = threading.Event()

    def cb_for(sess: SessionSim):
        def cb(result, _sess=sess):
            with lock:
                if result is not None:
                    lat.append(result.latency_ms)
                    inflight["last_done"] = time.perf_counter()
                else:
                    inflight["failed"] += 1
                inflight["n"] -= 1
                if inflight["n"] == 0:
                    idle_evt.set()
            if result is not None:
                _sess.advance(result.action)
            with lock:
                ready.append(_sess)      # failed or not, back in rotation
        return cb

    spacing = 1.0 / max(rate_qps, 1e-9)
    t0 = time.perf_counter()
    t_end = t0 + duration_s
    gen_end = t_end
    issued = 0
    while True:
        now = time.perf_counter()
        if now >= t_end or (stop is not None and stop.is_set()):
            gen_end = now
            break
        # Burst catch-up: issue every arrival DUE by the wall clock in one
        # go, so sleep jitter and GIL contention shift arrival timing but
        # never silently lower the offered rate.
        due = int((now - t0) / spacing) + 1 - issued
        if due <= 0:
            time.sleep(min(t0 + issued * spacing - now, 0.001))  # serve-block-ok:
            # the load GENERATOR's pacing sleep — its own thread, not the engine.
            continue
        for _ in range(min(due, 512)):
            issued += 1
            offered += 1
            with lock:
                sess = ready.popleft() if ready else None
            if sess is None:
                dropped += 1
                continue
            with lock:
                inflight["n"] += 1
                idle_evt.clear()
            try:
                server.submit(sess.sid, sess.observation(), cb_for(sess))
            except Exception:   # noqa: BLE001 — engine stopped or
                # terminally failed mid-run: count the arrival as failed,
                # release the in-flight slot, keep the generator
                # accountable (cmd_serve still prints its summary).
                with lock:
                    inflight["failed"] += 1
                    inflight["n"] -= 1
                    if inflight["n"] == 0:
                        idle_evt.set()
                    ready.append(sess)
    # Let the tail of in-flight requests complete before measuring; QPS is
    # counted over [start, max(last completion, generation span)] — a long
    # drain tail doesn't dilute the achieved rate, a generator that idled
    # out its full window still divides by that window, and an
    # early-STOPPED run (SIGTERM preemption) divides by the span it
    # actually ran, not the requested duration.
    idle_evt.wait(10.0)
    with lock:
        n = len(lat)
        failed = inflight["failed"]
        elapsed = max(inflight["last_done"] - t0,
                      min(duration_s, gen_end - t0))
    return {"mode": "open_loop", "rate_qps": rate_qps,
            "offered": offered, "dropped": dropped, "completed": n,
            "failed": failed, "elapsed_s": elapsed,
            "qps": n / max(elapsed, 1e-9),
            **_percentiles(lat)}
