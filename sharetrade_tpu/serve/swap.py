"""Hot weight swaps for the serving tier.

A background watcher polls the TRAINING run's crash-safe tagged checkpoint
(``tag_best`` by default — the best-greedy-eval policy the orchestrator
retains) and, when it advances, restores it through the PR-5 verified path:
per-file SHA-256 checksums, deserializability against the template, finite
params, and the PR-7 precision-mode check (the :class:`CheckpointManager`
is constructed with the run's ``precision.mode``). The restored master
weights are handed to :meth:`ServeEngine.swap_params`, which installs them
ATOMICALLY between batches — no in-flight batch ever sees mixed weights,
and every response names the checkpoint step that produced it.

A candidate that fails verification is REFUSED without interrupting
serving: the engine keeps its current weights, the rejection is counted
(``serve_swap_rejected_total``), and the corrupt payload is quarantined by
the manager's own machinery (never deleted). The watcher marks the bad
candidate's stamp as seen so a wedged checkpoint is not re-verified every
poll — the next genuine save carries a fresh ``saved_at`` and is picked up
normally.

Circuit breaker (ISSUE 10): a training run writing a stream of bad
candidates (truncating disk, template drift, a flapping precision mode)
would otherwise make the watcher pay a full checksum+deserialize
verification for every fresh stamp, forever. After
``serve.swap_breaker_failures`` CONSECUTIVE rejections the breaker OPENS:
the watcher stops polling the wedged tag for
``serve.swap_breaker_cooldown_s`` (gauge ``serve_swap_breaker_open`` = 1,
counter ``serve_swap_breaker_opens_total``), then lets ONE probe poll
through — a successful swap closes the breaker, another rejection
re-opens it. ``breaker_failures=0`` (the direct-construction default)
disables the breaker entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from sharetrade_tpu.checkpoint.manager import (
    CheckpointCorruptError,
    CheckpointIntegrityError,
    CheckpointManager,
)
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("serve.swap")


class WeightSwapWatcher:
    """Poll ``tag_<tag>`` every ``poll_s`` seconds and hot-swap the engine.

    ``template`` is the TrainState pytree the checkpoint deserializes into
    (the same template a ``--resume`` would use). ``seen_meta`` seeds the
    already-applied stamp — pass the metadata of the checkpoint the engine
    was BOOTED from so the first poll doesn't redundantly re-swap it."""

    def __init__(self, engine: Any, manager: CheckpointManager,
                 template: Any, *, tag: str = "best",
                 poll_s: float = 5.0, seen_meta: dict | None = None,
                 breaker_failures: int = 0,
                 breaker_cooldown_s: float = 30.0):
        self._engine = engine
        self._manager = manager
        self._template = template
        self._tag = tag
        self._poll_s = max(float(poll_s), 0.05)
        self._seen = self._stamp(seen_meta)
        self._stop = threading.Event()
        self.swaps = 0
        self.rejected = 0
        #: Breaker state: 0 disables; the streak counts CONSECUTIVE
        #: rejections (any successful swap resets it).
        self._breaker_failures = max(int(breaker_failures), 0)
        self._breaker_cooldown_s = max(float(breaker_cooldown_s), 0.0)
        self._fail_streak = 0
        self._open_until = 0.0          # monotonic; 0 = closed
        self.breaker_opens = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-swap-watcher",
                                        daemon=True)

    @staticmethod
    def _stamp(meta: dict | None):
        if not meta:
            return None
        return (meta.get("saved_at"), meta.get("updates"), meta.get("step"))

    def start(self) -> "WeightSwapWatcher":
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout_s)

    # ------------------------------------------------------------------

    @property
    def breaker_open(self) -> bool:
        """True while the circuit breaker is holding polls off the tag."""
        return self._open_until > 0.0 and time.monotonic() < self._open_until

    def poll_once(self) -> bool:
        """One poll: True when a swap was applied. Public so tests (and a
        manual operator nudge) can drive the watcher synchronously."""
        registry = getattr(self._engine, "registry", None)
        if self._open_until > 0.0:
            if time.monotonic() < self._open_until:
                return False        # open: the wedged tag is not polled
            # Cooldown over — half-open: let exactly one probe through
            # (a rejection in _reject re-opens with a fresh cooldown).
            self._open_until = 0.0
            if registry is not None:
                registry.record("serve_swap_breaker_open", 0.0)
            log.info("hot-swap breaker half-open: probing tag %r",
                     self._tag)
        meta = self._manager.tagged_metadata(self._tag)
        stamp = self._stamp(meta)
        if stamp is None or stamp == self._seen:
            return False
        try:
            state, restored_meta = self._manager.restore_tagged(
                self._template, self._tag)
        except CheckpointCorruptError as exc:
            # Both the tag and its .old crash-window copy failed
            # verification: refuse, keep serving, don't re-hammer.
            self._reject(stamp, registry, exc)
            return False
        except FileNotFoundError:
            return False            # no tag yet (or quarantined away)
        except (CheckpointIntegrityError, ValueError) as exc:
            # ValueError = intact bytes that don't fit this run (template
            # shape change, precision-mode mismatch): a config problem,
            # refused loudly but serving continues.
            self._reject(stamp, registry, exc)
            return False
        step = restored_meta.get("updates", restored_meta.get("step", 0))
        self._engine.swap_params(state.params, int(step))
        self._seen = self._stamp(restored_meta)
        self.swaps += 1
        self._fail_streak = 0           # a good candidate heals the breaker
        if registry is not None:
            registry.record("serve_swap_breaker_open", 0.0)
        return True

    def _reject(self, stamp, registry, exc: BaseException) -> None:
        self.rejected += 1
        self._seen = stamp
        if registry is not None:
            registry.inc("serve_swap_rejected_total")
        log.warning("hot-swap candidate %r refused; serving continues on "
                    "step %d (%s: %s)", self._tag,
                    getattr(self._engine, "params_step", -1),
                    type(exc).__name__, exc)
        self._fail_streak += 1
        if (self._breaker_failures > 0
                and self._fail_streak >= self._breaker_failures):
            # The streak is NOT reset here: a rejected half-open probe
            # stays past the threshold and re-opens immediately.
            self._open_until = time.monotonic() + self._breaker_cooldown_s
            self.breaker_opens += 1
            if registry is not None:
                registry.record("serve_swap_breaker_open", 1.0)
                registry.inc("serve_swap_breaker_opens_total")
            log.error(
                "hot-swap circuit breaker OPEN: %d consecutive refused "
                "candidates on tag %r; not polling for %.1fs",
                self._breaker_failures, self._tag,
                self._breaker_cooldown_s)

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            except Exception:       # noqa: BLE001 — the watcher must
                log.exception("hot-swap poll failed; serving continues")
                # outlive any single bad poll.
