"""Continuous-batching inference engine: one device program per tick.

The training side of this repo compiles everything; nothing served. This
module is ROADMAP item 2's serving tier: a policy-inference engine that
coalesces per-user ``(window, portfolio)`` queries into padded device
batches under a deadline (``serve.max_batch`` / ``serve.batch_timeout_ms``)
and keeps a fixed-capacity device-resident SESSION SLOT POOL — a
``(slots + max_batch, ...)`` arena of per-session recurrent carries, the
episode transformer's incremental K/V cache repurposed as a per-session
serving cache — so steady-state serving is ONE jitted batched program per
tick instead of a dispatch per request. That is the TF-Agents
batched-simulation thesis (arxiv 1709.02878) applied to inference, and
RLAX's TPU inference/learner decoupling (arxiv 2512.06392): throughput
comes from keeping one big batched program resident, not from many small
calls.

Structure (mirrors ``runtime/pipeline.py``'s dispatcher/consumer split):

- **submit** (any thread): enqueue a request; returns a waitable handle.
- **dispatcher thread** (``_serve_loop``): coalesce a batch (first request
  waits at most ``batch_timeout_ms``; a full batch never waits), admit
  sessions into the slot pool (LRU eviction; evicted sessions restart COLD
  through the batched prefill), and dispatch the jitted program(s) for the
  tick — asynchronously, so collection of tick k+1 overlaps device compute
  of tick k. No blocking host work happens here (tools/lint_hot_loop.py
  check 8).
- **consumer thread** (``_complete_batch``): device readback, request
  completion (events + callbacks), latency accounting, SLO gauge
  publication through ``MetricsRegistry`` (→ ``metrics.prom`` when obs
  export is on). The dispatcher→consumer queue is bounded, so in-flight
  device buffers are bounded and dispatch backpressures instead of racing
  ahead.

Weight swaps are ATOMIC between batches: :meth:`ServeEngine.swap_params`
replaces one ``(params, step)`` reference; the dispatcher reads it exactly
once per tick, so every response is attributable to exactly one checkpoint
step and no batch ever sees mixed weights (serve/swap.py is the
``tag_best`` watcher that calls it through the verified restore path).

Model contract: models providing ``apply_prefill``/``apply_serve_batch``
(the episode transformer) get the two-program cold/warm split — per-row
episode clocks, heterogeneous sessions in one batch. Everything else is
served through ``apply_batched`` in one program with an in-program cold-row
carry reset (stateless models like the MLP carry ``()`` and the pool is
structurally empty).

Parity contract (tests/test_serve.py): under fp32 the batched engine
returns BIT-IDENTICAL logits/actions to threading each session one at a
time through ``model.apply`` — batching is a scheduling optimization,
never a numerics change. bf16_mixed serving inherits the PR-7 tolerance
contract instead.

Overload & failure semantics (ISSUE 10; tools/serve_chaos.py pins them):

- **Admission control**: the ingress queue is bounded at
  ``serve.max_queue``; a submit past the bound never blocks and never
  grows host memory — the new request is refused
  (``shed_policy="reject"``) or the oldest queued request is shed
  (``"oldest"``), the loser completing immediately with
  :class:`ServeRejected`. Counters ``serve_queue_rejected_total`` /
  ``serve_shed_total``, gauge ``serve_overload``.
- **Deadlines**: ``submit(..., deadline_ms=)`` (default
  ``serve.default_deadline_ms``) expires un-dispatched requests with
  :class:`ServeDeadlineExceeded` at batch-collection time, before they
  can occupy a padded device row; coalescing waits are clamped to the
  earliest surviving deadline. Counter ``serve_deadline_expired_total``.
- **Supervision** (``serve.max_restarts > 0``): a dispatch/consumer
  fault fails its batch, then the engine itself is retried — fresh
  jitted programs + fresh slot arena under seeded exponential backoff
  (``serve.restart_backoff_s``); sessions re-enter cold through the
  batched prefill (bitwise-equivalent to a fresh session, the PR-8
  eviction contract). More than ``max_restarts`` CONSECUTIVE faults trip
  a terminal failed state that fails all queued work loudly
  (:class:`ServeEngineFailed`) instead of wedging. Counter
  ``serve_restarts_total``, gauge ``serve_failed``.

Every submitted request reaches exactly one terminal outcome — result,
rejection, deadline error, batch failure, or engine failure — the chaos
soak's core invariant.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.config import ConfigError, ServeConfig
from sharetrade_tpu.models.core import apply_batched
from sharetrade_tpu.precision import FP32, PrecisionPolicy
from sharetrade_tpu.utils.logging import get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry

log = get_logger("serve")

_SHUTDOWN = object()


class ServeRejected(RuntimeError):
    """The request was refused admission (ingress queue at
    ``serve.max_queue`` under ``shed_policy="reject"``) or shed from the
    queue under overload (``shed_policy="oldest"``). Always delivered as a
    completed handle (``wait()`` returns None, :attr:`_Request.error`
    carries this), never as a silent block of the caller's thread.
    ``reason`` is ``"queue_full"`` / ``"shed_oldest"`` /
    ``"deferred_overflow"``."""

    def __init__(self, message: str, *, reason: str):
        super().__init__(message)
        self.reason = reason


class ServeDeadlineExceeded(RuntimeError):
    """The request's deadline (``submit(..., deadline_ms=)`` or
    ``serve.default_deadline_ms``) expired before it reached a device
    batch; it was completed with this error instead of occupying a padded
    device row."""


class ServeEngineFailed(RuntimeError):
    """The engine tripped its terminal failed state: more than
    ``serve.max_restarts`` consecutive dispatch/consumer faults. All
    queued and future work fails loudly with this error (wrapping the
    last underlying fault) instead of wedging."""


def latency_percentiles(values) -> dict[str, float]:
    """p50/p99/mean over a latency sample, ONE quantile convention for the
    whole serving tier (the SLO gauges here and the load harnesses in
    serve/driver.py — BASELINE.md compares the two directly, so their
    percentile math must never diverge)."""
    if not len(values):
        return {"p50_ms": 0.0, "p99_ms": 0.0, "mean_ms": 0.0}
    arr = np.sort(np.asarray(values, np.float64))
    return {
        "p50_ms": float(arr[int(0.50 * (len(arr) - 1))]),
        "p99_ms": float(arr[int(0.99 * (len(arr) - 1))]),
        "mean_ms": float(arr.mean()),
    }


class ServeResult(NamedTuple):
    """One completed inference: the action plus enough provenance to audit
    it (``params_step`` names the exact checkpoint that produced it — the
    hot-swap atomicity observable)."""

    session_id: Any
    action: int
    logits: np.ndarray
    value: float
    params_step: int
    latency_ms: float


class _Live(NamedTuple):
    """The serving weights as ONE immutable reference: swapped atomically
    (a single attribute store), read exactly once per dispatch tick."""

    params: Any
    step: int


class _Request:
    """A submitted query; completed by the consumer thread (or, for
    rejected/expired work, by the thread that discovered the terminal
    outcome)."""

    __slots__ = ("session_id", "obs", "t_enq", "t_deadline", "callback",
                 "_event", "result", "error")

    def __init__(self, session_id: Any, obs: np.ndarray,
                 callback: Callable[[ServeResult | None], None] | None,
                 deadline_ms: float = 0.0):
        self.session_id = session_id
        self.obs = obs
        self.t_enq = time.perf_counter()
        #: Absolute expiry on the perf_counter clock; None = no deadline.
        #: A NEGATIVE deadline_ms (a client whose latency budget already
        #: ran out before submit) means already-expired — clamped to the
        #: enqueue instant, NOT silently promoted to "no deadline".
        self.t_deadline = (self.t_enq + max(deadline_ms, 0.0) / 1e3
                           if deadline_ms else None)
        self.callback = callback
        self._event = threading.Event()
        self.result: ServeResult | None = None
        #: Set when the request failed terminally without a result —
        #: ServeRejected (admission/shedding), ServeDeadlineExceeded,
        #: ServeEngineFailed, or the dispatch fault that failed its batch
        #: — so callers can distinguish failure from a wait() timeout.
        self.error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> ServeResult | None:
        """Block until the response is ready; None on timeout or when the
        request failed (then :attr:`error` carries the cause)."""
        self._event.wait(timeout)
        return self.result


class _DoneBatch(NamedTuple):
    """One dispatched tick handed dispatcher→consumer: per-program request
    groups with their (still device-resident) outputs."""

    groups: list[tuple[list[_Request], Any, Any, Any]]  # (reqs, act, log, val)
    step: int
    n: int                 # real rows in the tick
    cold: int              # rows served through the prefill
    evicted: int           # sessions evicted to admit this tick's rows
    #: Supervision fault epoch at dispatch time: only a batch dispatched
    #: AFTER the latest fault may reset the consecutive-fault streak —
    #: pre-fault batches draining out of the done queue during a backoff
    #: attest nothing about post-fault engine health.
    epoch: int = 0


class SlotPool:
    """Host-side session→slot map with LRU eviction.

    The carries themselves live on DEVICE in the engine's arena; this class
    owns only the mapping and the recency order. ``admit`` never evicts a
    session pinned by the current batch (its slot is about to be read or
    written) — with ``capacity >= max_batch`` an unpinned victim or a free
    slot always exists."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lru: OrderedDict[Any, int] = OrderedDict()  # oldest first
        self._free = list(range(capacity))
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._lru)

    def lookup(self, session_id: Any) -> int | None:
        """Slot of a WARM session (refreshes its recency); None when the
        session is absent (never admitted, or evicted — cold either way)."""
        slot = self._lru.get(session_id)
        if slot is not None:
            self._lru.move_to_end(session_id)
        return slot

    def drop(self, session_id: Any) -> None:
        """Forget a session (its slot returns to the free list) — the
        dispatch-fault path, where an admitted slot may never have
        received its prefilled carry."""
        slot = self._lru.pop(session_id, None)
        if slot is not None:
            self._free.append(slot)

    def admit(self, session_id: Any, pinned: set) -> tuple[int, Any | None]:
        """Assign a slot to a NEW session; returns ``(slot, evicted_sid)``
        (``evicted_sid`` None when a free slot absorbed the admission)."""
        if self._free:
            slot = self._free.pop()
            self._lru[session_id] = slot
            return slot, None
        for victim in self._lru:                       # oldest first
            if victim not in pinned:
                slot = self._lru.pop(victim)
                self._lru[session_id] = slot
                self.evictions += 1
                return slot, victim
        raise RuntimeError(
            "slot pool exhausted by pinned sessions (capacity < max_batch "
            "should have been rejected at construction)")


class ServeEngine:
    """See the module docstring. Construct, :meth:`warmup` (optional but
    recommended — compiles the serving programs before traffic), submit
    from any thread, :meth:`stop` when done."""

    def __init__(self, model: Any, cfg: ServeConfig, params: Any, *,
                 params_step: int = 0,
                 precision: PrecisionPolicy = FP32,
                 registry: MetricsRegistry | None = None,
                 obs: Any = None,
                 done_depth: int = 4,
                 restart_seed: int | None = None):
        if cfg.max_batch < 1:
            raise ConfigError(
                f"serve.max_batch must be >= 1, got {cfg.max_batch}")
        if cfg.slots < cfg.max_batch:
            raise ConfigError(
                f"serve.slots ({cfg.slots}) must be >= serve.max_batch "
                f"({cfg.max_batch}): every session of a full batch needs a "
                "live slot")
        if cfg.batch_timeout_ms < 0:
            raise ConfigError(
                f"serve.batch_timeout_ms must be >= 0, got "
                f"{cfg.batch_timeout_ms}")
        if cfg.max_queue < 1:
            raise ConfigError(
                f"serve.max_queue must be >= 1 (an unbounded ingress queue "
                f"turns a request flood into unbounded host memory), got "
                f"{cfg.max_queue}")
        if cfg.shed_policy not in ("reject", "oldest"):
            raise ConfigError(
                f"serve.shed_policy must be 'reject' or 'oldest', got "
                f"{cfg.shed_policy!r}")
        if cfg.default_deadline_ms < 0:
            raise ConfigError(
                f"serve.default_deadline_ms must be >= 0 (0 = none), got "
                f"{cfg.default_deadline_ms}")
        if cfg.max_restarts < 0:
            raise ConfigError(
                f"serve.max_restarts must be >= 0 (0 = no engine rebuild), "
                f"got {cfg.max_restarts}")
        if cfg.restart_backoff_s <= 0 or cfg.restart_backoff_max_s <= 0:
            raise ConfigError(
                "serve.restart_backoff_s / restart_backoff_max_s must be "
                f"> 0, got {cfg.restart_backoff_s}/"
                f"{cfg.restart_backoff_max_s}")
        self.model = model
        self.cfg = cfg
        self._precision = precision
        self._registry = registry if registry is not None else MetricsRegistry()
        self._obs = obs
        self._episode = (model.apply_prefill is not None
                         and model.apply_serve_batch is not None)
        self._live = _Live(jax.device_put(precision.cast_compute(params)),
                           int(params_step))
        self._carry0 = precision.cast_carry(model.init_carry(), model)
        self._build_arena_and_programs()

        # Bounded ingress: depth caps at serve.max_queue, the overload
        # surface (submit sheds/rejects instead of growing host memory).
        self._q: queue.Queue = queue.Queue(maxsize=cfg.max_queue)
        self._deferred: deque[_Request] = deque()
        self._done_q: queue.Queue = queue.Queue(maxsize=done_depth)
        #: Sessions whose slot carry is suspect after a CONSUMER fault
        #: (the device program advanced their carries, the readback
        #: failed): appended by the consumer, drained — and dropped from
        #: the pool — by the DISPATCHER, which owns the SlotPool (a
        #: cross-thread drop would race admit()'s LRU iteration).
        self._poisoned: deque = deque()
        self._stop_event = threading.Event()
        self._pending = 0
        self._pending_lock = threading.Lock()

        # Supervision state (serve.max_restarts > 0): consecutive-fault
        # streak (guarded by _sup_lock — the dispatcher increments, the
        # consumer resets), the fault epoch gating those resets, a
        # consumer-side restart request, and the terminal fault.
        self._restart_streak = 0
        self._sup_lock = threading.Lock()
        self._fault_epoch = 0
        # Backoff jitter seed: None (the production default — cli serve
        # never passes one) draws per-process OS entropy, so a fleet of
        # replicas does NOT share a jitter sequence and restart in
        # lockstep; tests/the chaos soak pass an int for replayability.
        self._restart_rng = random.Random(restart_seed)
        self._restart_requested = threading.Event()
        self._consumer_fault: BaseException | None = None
        #: Fault epoch of the batch whose completion faulted: a fault
        #: from a batch dispatched BEFORE the latest restart is stale —
        #: the rebuild already cured it — and must not burn another
        #: restart from the streak.
        self._consumer_fault_epoch = 0
        self._failed: BaseException | None = None
        # Overload events since the last stats publication (guarded by
        # _pending_lock; feeds the serve_overload gauge).
        self._overload_events = 0

        # SLO accounting (consumer-thread-owned except the latency ring's
        # bounded deque, which is append-only from one thread anyway).
        self._lat: deque[float] = deque(maxlen=cfg.latency_window)
        self._stats_t = time.perf_counter()
        self._stats_completed = 0
        self._stats_occupancy: list[float] = []

        self._dispatcher = threading.Thread(
            target=self._serve_loop, name="serve-dispatcher", daemon=True)
        self._consumer = threading.Thread(
            target=self._complete_loop, name="serve-consumer", daemon=True)
        self._dispatcher.start()
        self._consumer.start()

    def _build_arena_and_programs(self) -> None:
        """Fresh slot pool, fresh device arena, fresh jitted programs —
        construction AND the supervised-restart rebuild path (a restart
        discards every compiled program and every slot carry; sessions
        re-enter cold through the batched prefill, which PR 8 pinned as
        bitwise-equivalent to a fresh session suffix).

        Device arena: one carry row per slot, plus max_batch SCRATCH rows
        (indices >= cfg.slots) that padding rows read/write so a partial
        batch can never touch a live session's slot.

        The arena is DONATED on every backend: scatter into an aliased
        buffer updates in place, a non-donated pool round-trips a full
        arena copy per tick (measured 5.5x tick cost at the soak shape).
        The PR-4 CPU donation carve-out (runtime/orchestrator.py) does
        not apply here: its segfault was a consumer device_get racing a
        dispatch that donated the very state the readback came from; the
        pool never leaves the device, and the consumer reads only the
        action/logit/value outputs, which are never donated."""
        cfg = self.cfg
        self._slots = SlotPool(cfg.slots)
        n_arena = cfg.slots + cfg.max_batch
        self._pool = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], n_arena, axis=0),
            self._carry0)
        # Per-row init carries for the generic path's in-program cold reset.
        self._carry0_rows = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], cfg.max_batch,
                                 axis=0), self._carry0)
        donate = (1,)
        if self._episode:
            self._warm_fn = jax.jit(self._warm_program, donate_argnums=donate)
            self._cold_fn = jax.jit(self._cold_program, donate_argnums=donate)
        else:
            self._step_fn = jax.jit(self._generic_program,
                                    donate_argnums=donate)

    # -- device programs --------------------------------------------------

    def _warm_program(self, params, pool, obs, idx):
        """One incremental step for a warm batch: gather slot carries,
        per-row-clock serve step, scatter back. THE steady-state program."""
        rows = jax.tree.map(lambda x: x[idx], pool)
        out, new_rows = self.model.apply_serve_batch(params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _cold_program(self, params, pool, obs, idx):
        """Batched re-prefill: cold sessions (fresh or evicted) compute
        their episode-start pass and land their carries in their slots."""
        out, new_rows = self.model.apply_prefill(params, obs)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _generic_program(self, params, pool, obs, idx, cold):
        """Single program for models without a prefill/incremental split:
        cold rows take a fresh init carry in-program, everything else runs
        ``apply_batched`` (no cross-row constraint to honor)."""
        rows = jax.tree.map(lambda x: x[idx], pool)

        def reset_cold(init_row, row):
            mask = cold.reshape((-1,) + (1,) * (row.ndim - 1))
            return jnp.where(mask, init_row, row)

        rows = jax.tree.map(reset_cold, self._carry0_rows, rows)
        out, new_rows = apply_batched(self.model, params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    # -- public surface ---------------------------------------------------

    def submit(self, session_id: Any, obs: Any,
               callback: Callable[[ServeResult], None] | None = None,
               *, deadline_ms: float | None = None) -> _Request:
        """Enqueue one ``(window, portfolio)`` query; thread-safe. Returns
        a handle whose :meth:`_Request.wait` blocks for the response;
        ``callback(result)`` additionally fires on the consumer thread.

        ``deadline_ms`` bounds how long the request may wait before it is
        completed with a :class:`ServeDeadlineExceeded` error instead of
        being served (None = ``serve.default_deadline_ms``; 0 = none).

        NEVER blocks on a full queue: past ``serve.max_queue`` the
        request is refused (``shed_policy="reject"``) or the oldest
        queued request is shed to make room (``"oldest"``) — either way
        the loser's handle completes immediately with
        :class:`ServeRejected` (its callback fires with None on the
        CALLER's thread, the one place completion doesn't ride the
        consumer)."""
        if self._stop_event.is_set():
            raise RuntimeError("serve engine is stopped")
        if self._failed is not None:
            raise ServeEngineFailed(
                "serve engine is in the terminal failed state "
                f"(last fault: {self._failed!r}); rebuild it") \
                from self._failed
        if deadline_ms is None:
            deadline_ms = self.cfg.default_deadline_ms
        req = _Request(session_id, np.asarray(obs, np.float32), callback,
                       deadline_ms=deadline_ms)
        with self._pending_lock:
            self._pending += 1
        self._registry.inc("serve_requests_total")
        while True:
            try:
                self._q.put_nowait(req)
                if (self._stop_event.is_set()
                        and not self._dispatcher.is_alive()):
                    # TOCTOU: stop() completed between our gate check at
                    # the top and this put — nobody will ever read the
                    # queue again, so sweep it ourselves (pop-ownership
                    # makes this race-safe against other sweepers).
                    self._fail_leftovers()
                return req
            except queue.Full:
                pass
            with self._pending_lock:
                self._overload_events += 1
            if self.cfg.shed_policy == "reject":
                self._registry.inc("serve_queue_rejected_total")
                self._registry.record("serve_overload", 1.0)
                self._finish_failed(req, ServeRejected(
                    f"ingress queue full ({self.cfg.max_queue}); request "
                    "rejected under shed_policy='reject'",
                    reason="queue_full"))
                return req
            # shed_policy == "oldest": drop the oldest queued request and
            # retry the admission (the dispatcher may race us for it —
            # an Empty get just means the queue drained; retry the put).
            try:
                victim = self._q.get_nowait()
            except queue.Empty:
                continue
            self._registry.inc("serve_shed_total")
            self._registry.record("serve_overload", 1.0)
            self._finish_failed(victim, ServeRejected(
                f"shed from the ingress queue under overload "
                f"(shed_policy='oldest', max_queue={self.cfg.max_queue})",
                reason="shed_oldest"))

    def _finish_failed(self, req: _Request, exc: BaseException) -> None:
        """Complete a request with a terminal error outcome (rejection,
        shed, deadline expiry, engine failure): release its waiter, fire
        its callback with None, and un-count it from the drain-pending
        total — a failed request must never strand :meth:`drain`."""
        with self._pending_lock:
            self._pending -= 1
        req.error = exc
        req._event.set()
        if req.callback is not None:
            try:
                req.callback(None)
            except Exception:   # noqa: BLE001
                log.exception("serve failure callback failed")

    @property
    def params_step(self) -> int:
        """Checkpoint step of the CURRENT serving weights."""
        return self._live.step

    @property
    def failed(self) -> BaseException | None:
        """The terminal fault, when the engine tripped its failed state
        (None while healthy). Terminal = submits raise ServeEngineFailed
        and all queued work has been failed loudly."""
        return self._failed

    def queue_depth(self) -> int:
        """Current ingress-queue depth (bounded by ``serve.max_queue`` —
        the chaos soak's queue invariant reads this)."""
        return self._q.qsize()

    @property
    def registry(self) -> MetricsRegistry:
        """The engine's metrics registry (counters + SLO gauges)."""
        return self._registry

    def swap_params(self, master_params: Any, step: int) -> None:
        """Atomically install new serving weights between batches. The
        dispatcher reads the live reference once per tick, so a batch
        computes entirely under one step's weights — in-flight ticks keep
        the old params alive until their buffers are read back."""
        params = jax.device_put(self._precision.cast_compute(master_params))
        self._live = _Live(params, int(step))
        self._registry.inc("serve_swaps_total")
        log.info("serving params swapped to step %d", int(step))

    def warmup(self) -> None:
        """Compile every serving program with a scratch-only batch (live
        slots untouched). Call before traffic so the first real request
        doesn't pay the compile. Must run before concurrent submits."""
        cfg = self.cfg
        obs_dim = getattr(self.model, "obs_dim", 0) or 3
        obs = np.full((cfg.max_batch, obs_dim), 10.0, np.float32)
        idx = np.arange(cfg.slots, cfg.slots + cfg.max_batch, dtype=np.int32)
        if self._episode:
            _, _, _, pool = self._cold_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
            _, _, _, pool = self._warm_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
        else:
            cold = np.ones((cfg.max_batch,), bool)
            _, _, _, pool = self._step_fn(self._live.params, self._pool,
                                          obs, idx, cold)
            self._pool = pool

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submitted request has been answered (the
        SIGTERM drain of ``cli serve``); False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._pending_lock:
                if self._pending == 0:
                    return True
            time.sleep(0.002)   # serve-block-ok: drain's bounded poll runs
            # on the CALLER's thread (cli shutdown), never the dispatch path.
        with self._pending_lock:
            return self._pending == 0

    def stop(self, *, drain: bool = True, timeout_s: float = 30.0) -> bool:
        """Drain (optionally), stop both threads, publish final gauges.

        Returns False — loudly — when either thread is still alive after
        its join timeout: a hung dispatcher/consumer means in-flight work
        may never complete, and the caller (``cli serve``'s SIGTERM path)
        must exit nonzero instead of reporting a clean shutdown."""
        if drain:
            self.drain(timeout_s)
        self._stop_event.set()
        self._dispatcher.join(timeout_s)
        if not self._dispatcher.is_alive():
            # The dispatcher failed its leftovers in its own exit path;
            # this sweep catches requests that raced in between that
            # sweep and its death (safe now — the owner is gone).
            self._fail_leftovers()
        try:
            # Bounded put: with the consumer hung behind a full done
            # queue, an unbounded put would hang stop() itself.
            self._done_q.put(_SHUTDOWN, timeout=timeout_s)
        except queue.Full:
            pass
        self._consumer.join(timeout_s)
        ok = True
        for thread in (self._dispatcher, self._consumer):
            if thread.is_alive():
                log.error(
                    "serve %s thread still alive %.1fs after stop(): "
                    "shutdown is NOT clean (in-flight requests may never "
                    "complete)", thread.name, timeout_s)
                ok = False
        self._publish_stats(force=True)
        return ok

    def latencies_ms(self) -> list[float]:
        """Snapshot of the per-request latency ring (percentile source)."""
        return list(self._lat)

    # -- dispatcher thread ------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop_event.is_set():
            if self._failed is not None:
                # Terminal failed state: never wedge — every request that
                # raced past the submit-side gate still gets a loud
                # terminal outcome.
                self._drain_failed()
                continue
            # Sessions a consumer fault poisoned (their slot carries
            # advanced but the responses were lost): drop them so their
            # next request re-enters cold instead of double-stepping a
            # warm carry. Best-effort — a same-session request already
            # in flight this tick may still read the advanced carry; the
            # supervision rebuild (max_restarts > 0) resets even that.
            while self._poisoned:
                self._slots.drop(self._poisoned.popleft())
            if self._restart_requested.is_set():
                self._restart_requested.clear()
                # Epoch-gate: a fault from a batch dispatched before the
                # latest restart was already cured by that rebuild; only
                # a current-epoch fault earns another restart.
                if self._consumer_fault_epoch >= self._fault_epoch:
                    self._supervise(self._consumer_fault
                                    or RuntimeError("serve consumer fault"))
                continue
            batch = self._collect_batch()
            if not batch:
                continue
            live = self._live       # ONE read per tick: the atomicity seam
            try:
                done = self._dispatch_batch(batch, live)
            except Exception as exc:    # noqa: BLE001 — one malformed
                # request (bad obs shape) must fail ITS batch, not wedge
                # the dispatcher and hang every later session.
                self._fail_batch(batch, exc)
                # ... and with supervision on, retry the ENGINE: rebuild
                # programs + arena under seeded backoff (no-op at the
                # default max_restarts=0, the PR-8 contract).
                self._supervise(exc)
                continue
            # Bounded handoff: blocking here is the backpressure that
            # keeps in-flight device buffers bounded (pipeline.py's put).
            self._done_q.put(done)
        # Dispatcher exit: whatever is still queued/deferred can never be
        # dispatched — fail it terminally HERE, on the thread that owns
        # these structures (stop() and submit() re-sweep only for racers,
        # and only once this thread is provably dead).
        self._fail_leftovers()

    def _fail_leftovers(self) -> None:
        """Fail every request still in the ingress/deferred queues with a
        terminal stopped error. Safe concurrently: items transfer to the
        caller one pop at a time, so each request is completed exactly
        once even when stop()/submit() racers sweep alongside the
        dispatcher's own exit sweep."""
        leftover = RuntimeError(
            "serve engine stopped before this request was dispatched")
        while True:
            try:
                req = self._deferred.popleft()
            except IndexError:
                break
            self._finish_failed(req, leftover)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            self._finish_failed(req, leftover)

    def _fail_batch(self, batch: list[_Request], exc: Exception) -> None:
        """Dispatch-fault path (off the lint-guarded closure): release the
        batch's waiters with no result and keep serving."""
        log.exception("serve dispatch failed for a %d-request batch: %s",
                      len(batch), exc)
        for req in batch:
            # An admitted slot may hold a stale/garbage carry (the prefill
            # may never have run): drop the session so its next request
            # re-enters cold instead of reading a poisoned slot. Callback-
            # driven clients (the load harnesses, a network front-end) see
            # the failure as a None result, or the session silently leaks
            # out of their bookkeeping.
            self._slots.drop(req.session_id)
            self._finish_failed(req, exc)

    # -- dispatch supervision (serve.max_restarts > 0) --------------------

    def _supervise(self, exc: BaseException) -> None:
        """Training-loop restart contract applied to serving: after a
        fault fails its batch, rebuild the engine (fresh jitted programs +
        fresh slot arena — sessions re-enter cold through the batched
        prefill) under seeded exponential backoff. A streak of more than
        ``max_restarts`` consecutive faults (reset by any completed batch)
        trips the terminal failed state instead of retrying forever."""
        if self.cfg.max_restarts <= 0:
            return                      # PR-8 behavior: no engine rebuild
        with self._sup_lock:
            # Bump under the SAME lock as the consumer's compare-and-
            # reset: either the consumer resets first (pre-fault streak,
            # harmless) or it sees the new epoch and leaves the streak
            # alone — a pre-fault completion can never erase this fault.
            self._fault_epoch += 1
        while not self._stop_event.is_set():
            with self._sup_lock:
                self._restart_streak += 1
                streak = self._restart_streak
            if streak > self.cfg.max_restarts:
                self._enter_failed(exc)
                return
            self._registry.inc("serve_restarts_total")
            self._backoff_sleep(streak)
            try:
                self._build_arena_and_programs()
                # Recompile NOW, on scratch rows, not on the first real
                # post-restart batch (seconds of XLA compile on the
                # dispatch path would blow every queued deadline and
                # shed at max rate); a compile failure folds into the
                # restart streak instead of failing an innocent batch.
                self.warmup()
                log.warning(
                    "serve engine rebuilt after fault (restart %d/%d): "
                    "fresh programs + slot arena, all sessions cold",
                    streak, self.cfg.max_restarts)
                return
            except Exception as rebuild_exc:    # noqa: BLE001 — a failed
                # rebuild is just the next fault in the streak.
                log.exception("serve engine rebuild failed")
                exc = rebuild_exc

    def _backoff_sleep(self, attempt: int) -> None:
        """Seeded exponential backoff between engine rebuilds:
        initial * 2^(attempt-1), capped, with seeded multiplicative jitter
        so a fleet of engines doesn't restart in lockstep. Deliberately
        NOT a ``time.sleep`` (which lint check 10 bans throughout serve/):
        waiting on the stop event keeps shutdown from blocking behind a
        backoff."""
        cfg = self.cfg
        delay = min(cfg.restart_backoff_s * (2.0 ** (attempt - 1)),
                    cfg.restart_backoff_max_s)
        delay *= 0.5 + self._restart_rng.random()
        self._stop_event.wait(delay)

    def _enter_failed(self, exc: BaseException) -> None:
        """Trip the terminal failed state: fail ALL queued work loudly and
        refuse future submits — a restart storm must end in a diagnosable
        corpse, never a silent wedge."""
        self._failed = exc
        self._registry.record("serve_failed", 1.0)
        log.error(
            "serve engine TERMINALLY FAILED: %d consecutive faults "
            "exceeded serve.max_restarts=%d (last: %r); failing all "
            "queued work", self._restart_streak, self.cfg.max_restarts,
            exc)
        self._drain_failed()

    def _drain_failed(self) -> None:
        """Fail everything queued/deferred with ServeEngineFailed (bounded
        wait on the empty queue so the loop stays responsive to stop)."""
        failure = ServeEngineFailed(
            f"serve engine is terminally failed (last fault: "
            f"{self._failed!r})")
        failure.__cause__ = self._failed
        while self._deferred:
            self._finish_failed(self._deferred.popleft(), failure)
        try:
            while True:
                self._finish_failed(self._q.get(timeout=0.05), failure)
        except queue.Empty:
            pass

    # -- batch collection -------------------------------------------------

    def _expire_if_dead(self, req: _Request, now: float) -> bool:
        """Deadline gate at collection time: a request whose deadline
        passed is completed with ServeDeadlineExceeded BEFORE it can
        occupy a padded device row. Returns True when the request was
        expired (caller must skip it)."""
        if req.t_deadline is None or now < req.t_deadline:
            return False
        self._registry.inc("serve_deadline_expired_total")
        self._finish_failed(req, ServeDeadlineExceeded(
            f"deadline expired {1e3 * (now - req.t_deadline):.1f} ms ago "
            "before the request reached a batch"))
        return True

    def _collect_batch(self) -> list[_Request]:
        """Coalesce one tick's batch: deferred same-session requests first
        (sequential consistency per session — a session's second in-flight
        request must see its first one's carry), then drain the queue until
        ``max_batch`` or the coalescing deadline — anchored at the FIRST
        request and clamped to the earliest surviving request's
        per-request deadline, so waiting for batch-mates never expires
        work the tick could have served. Expired requests are completed
        with a deadline error at pop time and never join the batch."""
        cfg = self.cfg
        batch: list[_Request] = []
        seen: set = set()
        kept: deque[_Request] = deque()
        now = time.perf_counter()
        while self._deferred:
            req = self._deferred.popleft()
            if self._expire_if_dead(req, now):
                continue
            if req.session_id in seen or len(batch) >= cfg.max_batch:
                kept.append(req)
            else:
                batch.append(req)
                seen.add(req.session_id)
        self._deferred = kept
        if not batch:
            try:
                req = self._q.get(timeout=0.05)
            except queue.Empty:
                return []
            if self._expire_if_dead(req, time.perf_counter()):
                return []
            batch.append(req)
            seen.add(req.session_id)
        deadline = time.perf_counter() + cfg.batch_timeout_ms / 1e3
        for req in batch:           # anchor to the earliest survivor
            if req.t_deadline is not None:
                deadline = min(deadline, req.t_deadline)
        while len(batch) < cfg.max_batch:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if self._expire_if_dead(req, time.perf_counter()):
                continue
            if req.session_id in seen:
                if len(self._deferred) >= cfg.max_queue:
                    # The deferred side-queue is bounded too: a single-
                    # session flood must not re-grow the memory the
                    # ingress bound just capped. The loser follows the
                    # configured policy: "oldest" sheds the STALEST
                    # deferred request and admits the new one (the
                    # brownout contract), "reject" refuses the arrival.
                    with self._pending_lock:
                        self._overload_events += 1
                    if cfg.shed_policy == "oldest":
                        victim = self._deferred.popleft()
                        self._registry.inc("serve_shed_total")
                        self._finish_failed(victim, ServeRejected(
                            "shed from the same-session backlog under "
                            "overload (shed_policy='oldest')",
                            reason="shed_oldest"))
                        self._deferred.append(req)
                    else:
                        self._registry.inc("serve_queue_rejected_total")
                        self._finish_failed(req, ServeRejected(
                            "same-session backlog exceeded "
                            "serve.max_queue", reason="deferred_overflow"))
                    continue
                self._deferred.append(req)
            else:
                batch.append(req)
                seen.add(req.session_id)
                if (req.t_deadline is not None
                        and req.t_deadline < deadline):
                    deadline = req.t_deadline
        return batch

    def _dispatch_batch(self, batch: list[_Request],
                        live: _Live) -> _DoneBatch:
        """Admit, partition cold/warm, dispatch the tick's program(s).
        Runs on the dispatch critical path: NO blocking host ops here
        (tools/lint_hot_loop.py check 8) — jit calls return asynchronously
        and readback belongs to ``_complete_batch``."""
        pinned = {r.session_id for r in batch}
        cold_reqs: list[_Request] = []
        cold_idx: list[int] = []
        warm_reqs: list[_Request] = []
        warm_idx: list[int] = []
        evicted = 0
        for req in batch:
            slot = self._slots.lookup(req.session_id)
            if slot is None:
                slot, victim = self._slots.admit(req.session_id, pinned)
                if victim is not None:
                    evicted += 1
                cold_reqs.append(req)
                cold_idx.append(slot)
            else:
                warm_reqs.append(req)
                warm_idx.append(slot)
        # self._pool is reassigned IMMEDIATELY after each program call:
        # the calls donate the arena, so holding the old reference across
        # a later failure (the warm group's _pad raising after the cold
        # program already consumed the buffer) would leave the field
        # pointing at a deleted array and wedge every future tick.
        groups: list[tuple[list[_Request], Any, Any, Any]] = []
        if self._episode:
            if cold_reqs:
                obs, idx = self._pad(cold_reqs, cold_idx)
                act, logit, val, self._pool = self._cold_fn(
                    live.params, self._pool, obs, idx)
                groups.append((cold_reqs, act, logit, val))
            if warm_reqs:
                obs, idx = self._pad(warm_reqs, warm_idx)
                act, logit, val, self._pool = self._warm_fn(
                    live.params, self._pool, obs, idx)
                groups.append((warm_reqs, act, logit, val))
        else:
            reqs = cold_reqs + warm_reqs
            cold_mask = np.zeros((self.cfg.max_batch,), bool)
            cold_mask[:len(cold_reqs)] = True
            obs, idx = self._pad(reqs, cold_idx + warm_idx)
            act, logit, val, self._pool = self._step_fn(
                live.params, self._pool, obs, idx, cold_mask)
            groups.append((reqs, act, logit, val))
        return _DoneBatch(groups=groups, step=live.step, n=len(batch),
                          cold=len(cold_reqs), evicted=evicted,
                          epoch=self._fault_epoch)

    def _pad(self, reqs: list[_Request],
             idx: list[int]) -> tuple[np.ndarray, np.ndarray]:
        """Pad a group to the static ``max_batch`` shape: padding rows
        repeat the first real observation (finite by construction) and
        index SCRATCH arena rows, never a live slot."""
        cfg = self.cfg
        obs = np.empty((cfg.max_batch, reqs[0].obs.shape[-1]), np.float32)
        out_idx = np.empty((cfg.max_batch,), np.int32)
        for i, req in enumerate(reqs):
            obs[i] = req.obs
            out_idx[i] = idx[i]
        for i in range(len(reqs), cfg.max_batch):
            obs[i] = reqs[0].obs
            out_idx[i] = cfg.slots + i
        return obs, out_idx

    # -- consumer thread --------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            try:
                item = self._done_q.get(timeout=0.2)
            except queue.Empty:
                # Normally the _SHUTDOWN sentinel ends this loop; the
                # timed poll covers the sentinel stop() had to DROP on a
                # full queue (consumer stalled past the put timeout) — a
                # later-recovering consumer drains what remains and then
                # exits here instead of parking forever on a sentinel
                # that will never arrive. Exit ONLY once the dispatcher
                # is gone too, and even then drain once more first: the
                # dispatcher may have put its final batch between our
                # empty get and its exit, and those waiters must still
                # reach a terminal outcome.
                if (self._stop_event.is_set()
                        and not self._dispatcher.is_alive()):
                    while True:
                        try:
                            item = self._done_q.get_nowait()
                        except queue.Empty:
                            return
                        if item is not _SHUTDOWN:
                            self._consume_done(item)
                continue
            if item is _SHUTDOWN:
                return
            self._consume_done(item)

    def _consume_done(self, item: _DoneBatch) -> None:
        try:
            self._complete_batch(item)
        except Exception as exc:  # noqa: BLE001 — a completion fault
            # (readback error, device fault) must neither wedge the
            # dispatcher behind a full done queue NOR leak the batch's
            # waiters: release every request not already completed,
            # mirroring the dispatcher's _fail_batch contract.
            log.exception("serve consumer failed completing a batch")
            for reqs, *_ in item.groups:
                for req in reqs:
                    # The dispatched program already ADVANCED these
                    # sessions' slot carries; hand them to the
                    # dispatcher to drop (it owns the SlotPool) so a
                    # client retry doesn't double-step a warm carry.
                    self._poisoned.append(req.session_id)
                    if req._event.is_set():
                        continue
                    req.error = exc
                    req._event.set()
                    if req.callback is not None:
                        try:
                            req.callback(None)
                        except Exception:   # noqa: BLE001
                            log.exception("serve failure callback failed")
            # A consumer fault is an ENGINE fault for the supervisor:
            # the readback path may hold poisoned device buffers, so ask
            # the dispatcher to run the restart/backoff contract (no-op
            # at the default max_restarts=0), stamped with the faulting
            # batch's epoch so a pre-restart batch draining out of the
            # done queue can't re-trip a restart the rebuild already
            # delivered.
            self._consumer_fault = exc
            self._consumer_fault_epoch = item.epoch
            self._restart_requested.set()

    def _complete_batch(self, done: _DoneBatch) -> None:
        """Readback + request completion + SLO accounting — the consumer
        side of the split; blocking host work is EXPECTED here. The
        pending count decrements in a finally so a mid-completion fault
        (handled by :meth:`_complete_loop`) can never strand
        :meth:`drain`."""
        try:
            for reqs, act_dev, logit_dev, val_dev in done.groups:
                # serve-host-ok: consumer-side readback — the dispatcher
                # never blocks on these buffers.
                actions, logits, values = jax.device_get(
                    (act_dev, logit_dev, val_dev))
                now = time.perf_counter()
                for i, req in enumerate(reqs):
                    result = ServeResult(
                        session_id=req.session_id,
                        action=int(actions[i]),
                        logits=logits[i],
                        value=float(values[i]),
                        params_step=done.step,
                        latency_ms=(now - req.t_enq) * 1e3)
                    req.result = result
                    req._event.set()
                    self._lat.append(result.latency_ms)
                    if req.callback is not None:
                        try:
                            req.callback(result)
                        except Exception:   # noqa: BLE001
                            log.exception("serve result callback failed")
        finally:
            with self._pending_lock:
                self._pending -= done.n
        # A completed batch heals the supervisor's consecutive-fault
        # streak (mirrors the training loop's restart accounting) — but
        # ONLY a batch dispatched after the latest fault: pre-fault
        # batches draining out of the done queue during a backoff say
        # nothing about the rebuilt engine.
        with self._sup_lock:
            if done.epoch == self._fault_epoch:
                self._restart_streak = 0
        self._stats_completed += done.n
        self._stats_occupancy.append(done.n / self.cfg.max_batch)
        reg = self._registry
        reg.inc("serve_responses_total", done.n)
        reg.inc("serve_batches_total")
        if done.cold:
            reg.inc("serve_prefills_total", done.cold)
        if done.evicted:
            reg.inc("serve_evictions_total", done.evicted)
        self._publish_stats()

    def _publish_stats(self, *, force: bool = False) -> None:
        """SLO gauges at ``stats_interval_s`` cadence (consumer thread)."""
        now = time.perf_counter()
        interval = now - self._stats_t
        if not force and interval < self.cfg.stats_interval_s:
            return
        if interval <= 0:
            return
        with self._pending_lock:
            overload_events = self._overload_events
            self._overload_events = 0
        depth = self._q.qsize()
        row: dict[str, float] = {
            "serve_qps": self._stats_completed / interval,
            "serve_queue_depth": float(depth),
            # Overload gauge: 1 while the engine is shedding/rejecting or
            # the ingress queue is pinned at its bound, else 0.
            "serve_overload": float(overload_events > 0
                                    or depth >= self.cfg.max_queue),
        }
        if self._lat:
            pct = latency_percentiles(list(self._lat))
            row["serve_p50_ms"] = pct["p50_ms"]
            row["serve_p99_ms"] = pct["p99_ms"]
        if self._stats_occupancy:
            row["serve_batch_occupancy"] = (
                sum(self._stats_occupancy) / len(self._stats_occupancy))
        self._registry.record_many(row)
        self._stats_t = now
        self._stats_completed = 0
        self._stats_occupancy = []
