"""Continuous-batching inference engine: one device program per tick.

The training side of this repo compiles everything; nothing served. This
module is ROADMAP item 2's serving tier: a policy-inference engine that
coalesces per-user ``(window, portfolio)`` queries into padded device
batches under a deadline (``serve.max_batch`` / ``serve.batch_timeout_ms``)
and keeps a fixed-capacity device-resident SESSION SLOT POOL — a
``(slots + max_batch, ...)`` arena of per-session recurrent carries, the
episode transformer's incremental K/V cache repurposed as a per-session
serving cache — so steady-state serving is ONE jitted batched program per
tick instead of a dispatch per request. That is the TF-Agents
batched-simulation thesis (arxiv 1709.02878) applied to inference, and
RLAX's TPU inference/learner decoupling (arxiv 2512.06392): throughput
comes from keeping one big batched program resident, not from many small
calls.

Structure (mirrors ``runtime/pipeline.py``'s dispatcher/consumer split):

- **submit** (any thread): enqueue a request; returns a waitable handle.
- **dispatcher thread** (``_serve_loop``): coalesce a batch (first request
  waits at most ``batch_timeout_ms``; a full batch never waits), admit
  sessions into the slot pool (LRU eviction; evicted sessions restart COLD
  through the batched prefill), and dispatch the jitted program(s) for the
  tick — asynchronously, so collection of tick k+1 overlaps device compute
  of tick k. No blocking host work happens here (tools/lint_hot_loop.py
  check 8).
- **consumer thread** (``_complete_batch``): device readback, request
  completion (events + callbacks), latency accounting, SLO gauge
  publication through ``MetricsRegistry`` (→ ``metrics.prom`` when obs
  export is on). The dispatcher→consumer queue is bounded, so in-flight
  device buffers are bounded and dispatch backpressures instead of racing
  ahead.

Weight swaps are ATOMIC between batches: :meth:`ServeEngine.swap_params`
replaces one ``(params, step)`` reference; the dispatcher reads it exactly
once per tick, so every response is attributable to exactly one checkpoint
step and no batch ever sees mixed weights (serve/swap.py is the
``tag_best`` watcher that calls it through the verified restore path).

Model contract: models providing ``apply_prefill``/``apply_serve_batch``
(the episode transformer) get the two-program cold/warm split — per-row
episode clocks, heterogeneous sessions in one batch. Everything else is
served through ``apply_batched`` in one program with an in-program cold-row
carry reset (stateless models like the MLP carry ``()`` and the pool is
structurally empty).

Parity contract (tests/test_serve.py): under fp32 the batched engine
returns BIT-IDENTICAL logits/actions to threading each session one at a
time through ``model.apply`` — batching is a scheduling optimization,
never a numerics change. bf16_mixed serving inherits the PR-7 tolerance
contract instead.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.config import ConfigError, ServeConfig
from sharetrade_tpu.models.core import apply_batched
from sharetrade_tpu.precision import FP32, PrecisionPolicy
from sharetrade_tpu.utils.logging import get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry

log = get_logger("serve")

_SHUTDOWN = object()


def latency_percentiles(values) -> dict[str, float]:
    """p50/p99/mean over a latency sample, ONE quantile convention for the
    whole serving tier (the SLO gauges here and the load harnesses in
    serve/driver.py — BASELINE.md compares the two directly, so their
    percentile math must never diverge)."""
    if not len(values):
        return {"p50_ms": 0.0, "p99_ms": 0.0, "mean_ms": 0.0}
    arr = np.sort(np.asarray(values, np.float64))
    return {
        "p50_ms": float(arr[int(0.50 * (len(arr) - 1))]),
        "p99_ms": float(arr[int(0.99 * (len(arr) - 1))]),
        "mean_ms": float(arr.mean()),
    }


class ServeResult(NamedTuple):
    """One completed inference: the action plus enough provenance to audit
    it (``params_step`` names the exact checkpoint that produced it — the
    hot-swap atomicity observable)."""

    session_id: Any
    action: int
    logits: np.ndarray
    value: float
    params_step: int
    latency_ms: float


class _Live(NamedTuple):
    """The serving weights as ONE immutable reference: swapped atomically
    (a single attribute store), read exactly once per dispatch tick."""

    params: Any
    step: int


class _Request:
    """A submitted query; completed by the consumer thread."""

    __slots__ = ("session_id", "obs", "t_enq", "callback", "_event",
                 "result", "error")

    def __init__(self, session_id: Any, obs: np.ndarray,
                 callback: Callable[[ServeResult | None], None] | None):
        self.session_id = session_id
        self.obs = obs
        self.t_enq = time.perf_counter()
        self.callback = callback
        self._event = threading.Event()
        self.result: ServeResult | None = None
        #: Set when the request's batch failed to dispatch — lets callers
        #: distinguish a served-nothing failure from a wait() timeout.
        self.error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> ServeResult | None:
        """Block until the response is ready; None on timeout or when the
        request's batch failed (then :attr:`error` carries the cause)."""
        self._event.wait(timeout)
        return self.result


class _DoneBatch(NamedTuple):
    """One dispatched tick handed dispatcher→consumer: per-program request
    groups with their (still device-resident) outputs."""

    groups: list[tuple[list[_Request], Any, Any, Any]]  # (reqs, act, log, val)
    step: int
    n: int                 # real rows in the tick
    cold: int              # rows served through the prefill
    evicted: int           # sessions evicted to admit this tick's rows


class SlotPool:
    """Host-side session→slot map with LRU eviction.

    The carries themselves live on DEVICE in the engine's arena; this class
    owns only the mapping and the recency order. ``admit`` never evicts a
    session pinned by the current batch (its slot is about to be read or
    written) — with ``capacity >= max_batch`` an unpinned victim or a free
    slot always exists."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lru: OrderedDict[Any, int] = OrderedDict()  # oldest first
        self._free = list(range(capacity))
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._lru)

    def lookup(self, session_id: Any) -> int | None:
        """Slot of a WARM session (refreshes its recency); None when the
        session is absent (never admitted, or evicted — cold either way)."""
        slot = self._lru.get(session_id)
        if slot is not None:
            self._lru.move_to_end(session_id)
        return slot

    def drop(self, session_id: Any) -> None:
        """Forget a session (its slot returns to the free list) — the
        dispatch-fault path, where an admitted slot may never have
        received its prefilled carry."""
        slot = self._lru.pop(session_id, None)
        if slot is not None:
            self._free.append(slot)

    def admit(self, session_id: Any, pinned: set) -> tuple[int, Any | None]:
        """Assign a slot to a NEW session; returns ``(slot, evicted_sid)``
        (``evicted_sid`` None when a free slot absorbed the admission)."""
        if self._free:
            slot = self._free.pop()
            self._lru[session_id] = slot
            return slot, None
        for victim in self._lru:                       # oldest first
            if victim not in pinned:
                slot = self._lru.pop(victim)
                self._lru[session_id] = slot
                self.evictions += 1
                return slot, victim
        raise RuntimeError(
            "slot pool exhausted by pinned sessions (capacity < max_batch "
            "should have been rejected at construction)")


class ServeEngine:
    """See the module docstring. Construct, :meth:`warmup` (optional but
    recommended — compiles the serving programs before traffic), submit
    from any thread, :meth:`stop` when done."""

    def __init__(self, model: Any, cfg: ServeConfig, params: Any, *,
                 params_step: int = 0,
                 precision: PrecisionPolicy = FP32,
                 registry: MetricsRegistry | None = None,
                 obs: Any = None,
                 done_depth: int = 4):
        if cfg.max_batch < 1:
            raise ConfigError(
                f"serve.max_batch must be >= 1, got {cfg.max_batch}")
        if cfg.slots < cfg.max_batch:
            raise ConfigError(
                f"serve.slots ({cfg.slots}) must be >= serve.max_batch "
                f"({cfg.max_batch}): every session of a full batch needs a "
                "live slot")
        if cfg.batch_timeout_ms < 0:
            raise ConfigError(
                f"serve.batch_timeout_ms must be >= 0, got "
                f"{cfg.batch_timeout_ms}")
        self.model = model
        self.cfg = cfg
        self._precision = precision
        self._registry = registry if registry is not None else MetricsRegistry()
        self._obs = obs
        self._episode = (model.apply_prefill is not None
                         and model.apply_serve_batch is not None)
        self._live = _Live(jax.device_put(precision.cast_compute(params)),
                           int(params_step))
        self._slots = SlotPool(cfg.slots)

        # Device arena: one carry row per slot, plus max_batch SCRATCH rows
        # (indices >= cfg.slots) that padding rows read/write so a partial
        # batch can never touch a live session's slot.
        carry0 = precision.cast_carry(model.init_carry(), model)
        n_arena = cfg.slots + cfg.max_batch
        self._pool = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], n_arena, axis=0),
            carry0)
        # Per-row init carries for the generic path's in-program cold reset.
        self._carry0_rows = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], cfg.max_batch,
                                 axis=0), carry0)

        # The arena is DONATED on every backend: scatter into an aliased
        # buffer updates in place, a non-donated pool round-trips a full
        # arena copy per tick (measured 5.5x tick cost at the soak shape).
        # The PR-4 CPU donation carve-out (runtime/orchestrator.py) does
        # not apply here: its segfault was a consumer device_get racing a
        # dispatch that donated the very state the readback came from; the
        # pool never leaves the device, and the consumer reads only the
        # action/logit/value outputs, which are never donated.
        donate = (1,)
        if self._episode:
            self._warm_fn = jax.jit(self._warm_program, donate_argnums=donate)
            self._cold_fn = jax.jit(self._cold_program, donate_argnums=donate)
        else:
            self._step_fn = jax.jit(self._generic_program,
                                    donate_argnums=donate)

        self._q: queue.Queue = queue.Queue()
        self._deferred: deque[_Request] = deque()
        self._done_q: queue.Queue = queue.Queue(maxsize=done_depth)
        self._stop_event = threading.Event()
        self._pending = 0
        self._pending_lock = threading.Lock()

        # SLO accounting (consumer-thread-owned except the latency ring's
        # bounded deque, which is append-only from one thread anyway).
        self._lat: deque[float] = deque(maxlen=cfg.latency_window)
        self._stats_t = time.perf_counter()
        self._stats_completed = 0
        self._stats_occupancy: list[float] = []

        self._dispatcher = threading.Thread(
            target=self._serve_loop, name="serve-dispatcher", daemon=True)
        self._consumer = threading.Thread(
            target=self._complete_loop, name="serve-consumer", daemon=True)
        self._dispatcher.start()
        self._consumer.start()

    # -- device programs --------------------------------------------------

    def _warm_program(self, params, pool, obs, idx):
        """One incremental step for a warm batch: gather slot carries,
        per-row-clock serve step, scatter back. THE steady-state program."""
        rows = jax.tree.map(lambda x: x[idx], pool)
        out, new_rows = self.model.apply_serve_batch(params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _cold_program(self, params, pool, obs, idx):
        """Batched re-prefill: cold sessions (fresh or evicted) compute
        their episode-start pass and land their carries in their slots."""
        out, new_rows = self.model.apply_prefill(params, obs)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _generic_program(self, params, pool, obs, idx, cold):
        """Single program for models without a prefill/incremental split:
        cold rows take a fresh init carry in-program, everything else runs
        ``apply_batched`` (no cross-row constraint to honor)."""
        rows = jax.tree.map(lambda x: x[idx], pool)

        def reset_cold(init_row, row):
            mask = cold.reshape((-1,) + (1,) * (row.ndim - 1))
            return jnp.where(mask, init_row, row)

        rows = jax.tree.map(reset_cold, self._carry0_rows, rows)
        out, new_rows = apply_batched(self.model, params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    # -- public surface ---------------------------------------------------

    def submit(self, session_id: Any, obs: Any,
               callback: Callable[[ServeResult], None] | None = None
               ) -> _Request:
        """Enqueue one ``(window, portfolio)`` query; thread-safe. Returns
        a handle whose :meth:`_Request.wait` blocks for the response;
        ``callback(result)`` additionally fires on the consumer thread."""
        if self._stop_event.is_set():
            raise RuntimeError("serve engine is stopped")
        req = _Request(session_id, np.asarray(obs, np.float32), callback)
        with self._pending_lock:
            self._pending += 1
        self._registry.inc("serve_requests_total")
        self._q.put(req)
        return req

    @property
    def params_step(self) -> int:
        """Checkpoint step of the CURRENT serving weights."""
        return self._live.step

    @property
    def registry(self) -> MetricsRegistry:
        """The engine's metrics registry (counters + SLO gauges)."""
        return self._registry

    def swap_params(self, master_params: Any, step: int) -> None:
        """Atomically install new serving weights between batches. The
        dispatcher reads the live reference once per tick, so a batch
        computes entirely under one step's weights — in-flight ticks keep
        the old params alive until their buffers are read back."""
        params = jax.device_put(self._precision.cast_compute(master_params))
        self._live = _Live(params, int(step))
        self._registry.inc("serve_swaps_total")
        log.info("serving params swapped to step %d", int(step))

    def warmup(self) -> None:
        """Compile every serving program with a scratch-only batch (live
        slots untouched). Call before traffic so the first real request
        doesn't pay the compile. Must run before concurrent submits."""
        cfg = self.cfg
        obs_dim = getattr(self.model, "obs_dim", 0) or 3
        obs = np.full((cfg.max_batch, obs_dim), 10.0, np.float32)
        idx = np.arange(cfg.slots, cfg.slots + cfg.max_batch, dtype=np.int32)
        if self._episode:
            _, _, _, pool = self._cold_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
            _, _, _, pool = self._warm_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
        else:
            cold = np.ones((cfg.max_batch,), bool)
            _, _, _, pool = self._step_fn(self._live.params, self._pool,
                                          obs, idx, cold)
            self._pool = pool

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submitted request has been answered (the
        SIGTERM drain of ``cli serve``); False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._pending_lock:
                if self._pending == 0:
                    return True
            time.sleep(0.002)
        with self._pending_lock:
            return self._pending == 0

    def stop(self, *, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Drain (optionally), stop both threads, publish final gauges."""
        if drain:
            self.drain(timeout_s)
        self._stop_event.set()
        self._dispatcher.join(timeout_s)
        self._done_q.put(_SHUTDOWN)
        self._consumer.join(timeout_s)
        self._publish_stats(force=True)

    def latencies_ms(self) -> list[float]:
        """Snapshot of the per-request latency ring (percentile source)."""
        return list(self._lat)

    # -- dispatcher thread ------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop_event.is_set():
            batch = self._collect_batch()
            if not batch:
                continue
            live = self._live       # ONE read per tick: the atomicity seam
            try:
                done = self._dispatch_batch(batch, live)
            except Exception as exc:    # noqa: BLE001 — one malformed
                # request (bad obs shape) must fail ITS batch, not wedge
                # the dispatcher and hang every later session.
                self._fail_batch(batch, exc)
                continue
            # Bounded handoff: blocking here is the backpressure that
            # keeps in-flight device buffers bounded (pipeline.py's put).
            self._done_q.put(done)

    def _fail_batch(self, batch: list[_Request], exc: Exception) -> None:
        """Dispatch-fault path (off the lint-guarded closure): release the
        batch's waiters with no result and keep serving."""
        log.exception("serve dispatch failed for a %d-request batch: %s",
                      len(batch), exc)
        with self._pending_lock:
            self._pending -= len(batch)
        for req in batch:
            # An admitted slot may hold a stale/garbage carry (the prefill
            # may never have run): drop the session so its next request
            # re-enters cold instead of reading a poisoned slot.
            self._slots.drop(req.session_id)
            req.error = exc
            req._event.set()        # result stays None: waiters unblock
            if req.callback is not None:
                # Callback-driven clients (the load harnesses, a network
                # front-end) must see the failure too, or the session
                # silently leaks out of their bookkeeping.
                try:
                    req.callback(None)
                except Exception:   # noqa: BLE001
                    log.exception("serve failure callback failed")

    def _collect_batch(self) -> list[_Request]:
        """Coalesce one tick's batch: deferred same-session requests first
        (sequential consistency per session — a session's second in-flight
        request must see its first one's carry), then drain the queue until
        ``max_batch`` or the deadline anchored at the FIRST request."""
        cfg = self.cfg
        batch: list[_Request] = []
        seen: set = set()
        kept: deque[_Request] = deque()
        while self._deferred:
            req = self._deferred.popleft()
            if req.session_id in seen or len(batch) >= cfg.max_batch:
                kept.append(req)
            else:
                batch.append(req)
                seen.add(req.session_id)
        self._deferred = kept
        if not batch:
            try:
                req = self._q.get(timeout=0.05)
            except queue.Empty:
                return []
            batch.append(req)
            seen.add(req.session_id)
        deadline = time.perf_counter() + cfg.batch_timeout_ms / 1e3
        while len(batch) < cfg.max_batch:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if req.session_id in seen:
                self._deferred.append(req)
            else:
                batch.append(req)
                seen.add(req.session_id)
        return batch

    def _dispatch_batch(self, batch: list[_Request],
                        live: _Live) -> _DoneBatch:
        """Admit, partition cold/warm, dispatch the tick's program(s).
        Runs on the dispatch critical path: NO blocking host ops here
        (tools/lint_hot_loop.py check 8) — jit calls return asynchronously
        and readback belongs to ``_complete_batch``."""
        pinned = {r.session_id for r in batch}
        cold_reqs: list[_Request] = []
        cold_idx: list[int] = []
        warm_reqs: list[_Request] = []
        warm_idx: list[int] = []
        evicted = 0
        for req in batch:
            slot = self._slots.lookup(req.session_id)
            if slot is None:
                slot, victim = self._slots.admit(req.session_id, pinned)
                if victim is not None:
                    evicted += 1
                cold_reqs.append(req)
                cold_idx.append(slot)
            else:
                warm_reqs.append(req)
                warm_idx.append(slot)
        # self._pool is reassigned IMMEDIATELY after each program call:
        # the calls donate the arena, so holding the old reference across
        # a later failure (the warm group's _pad raising after the cold
        # program already consumed the buffer) would leave the field
        # pointing at a deleted array and wedge every future tick.
        groups: list[tuple[list[_Request], Any, Any, Any]] = []
        if self._episode:
            if cold_reqs:
                obs, idx = self._pad(cold_reqs, cold_idx)
                act, logit, val, self._pool = self._cold_fn(
                    live.params, self._pool, obs, idx)
                groups.append((cold_reqs, act, logit, val))
            if warm_reqs:
                obs, idx = self._pad(warm_reqs, warm_idx)
                act, logit, val, self._pool = self._warm_fn(
                    live.params, self._pool, obs, idx)
                groups.append((warm_reqs, act, logit, val))
        else:
            reqs = cold_reqs + warm_reqs
            cold_mask = np.zeros((self.cfg.max_batch,), bool)
            cold_mask[:len(cold_reqs)] = True
            obs, idx = self._pad(reqs, cold_idx + warm_idx)
            act, logit, val, self._pool = self._step_fn(
                live.params, self._pool, obs, idx, cold_mask)
            groups.append((reqs, act, logit, val))
        return _DoneBatch(groups=groups, step=live.step, n=len(batch),
                          cold=len(cold_reqs), evicted=evicted)

    def _pad(self, reqs: list[_Request],
             idx: list[int]) -> tuple[np.ndarray, np.ndarray]:
        """Pad a group to the static ``max_batch`` shape: padding rows
        repeat the first real observation (finite by construction) and
        index SCRATCH arena rows, never a live slot."""
        cfg = self.cfg
        obs = np.empty((cfg.max_batch, reqs[0].obs.shape[-1]), np.float32)
        out_idx = np.empty((cfg.max_batch,), np.int32)
        for i, req in enumerate(reqs):
            obs[i] = req.obs
            out_idx[i] = idx[i]
        for i in range(len(reqs), cfg.max_batch):
            obs[i] = reqs[0].obs
            out_idx[i] = cfg.slots + i
        return obs, out_idx

    # -- consumer thread --------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            item = self._done_q.get()
            if item is _SHUTDOWN:
                return
            try:
                self._complete_batch(item)
            except Exception as exc:  # noqa: BLE001 — a completion fault
                # (readback error, device fault) must neither wedge the
                # dispatcher behind a full done queue NOR leak the batch's
                # waiters: release every request not already completed,
                # mirroring the dispatcher's _fail_batch contract.
                log.exception("serve consumer failed completing a batch")
                for reqs, *_ in item.groups:
                    for req in reqs:
                        if req._event.is_set():
                            continue
                        req.error = exc
                        req._event.set()
                        if req.callback is not None:
                            try:
                                req.callback(None)
                            except Exception:   # noqa: BLE001
                                log.exception(
                                    "serve failure callback failed")

    def _complete_batch(self, done: _DoneBatch) -> None:
        """Readback + request completion + SLO accounting — the consumer
        side of the split; blocking host work is EXPECTED here. The
        pending count decrements in a finally so a mid-completion fault
        (handled by :meth:`_complete_loop`) can never strand
        :meth:`drain`."""
        try:
            for reqs, act_dev, logit_dev, val_dev in done.groups:
                # serve-host-ok: consumer-side readback — the dispatcher
                # never blocks on these buffers.
                actions, logits, values = jax.device_get(
                    (act_dev, logit_dev, val_dev))
                now = time.perf_counter()
                for i, req in enumerate(reqs):
                    result = ServeResult(
                        session_id=req.session_id,
                        action=int(actions[i]),
                        logits=logits[i],
                        value=float(values[i]),
                        params_step=done.step,
                        latency_ms=(now - req.t_enq) * 1e3)
                    req.result = result
                    req._event.set()
                    self._lat.append(result.latency_ms)
                    if req.callback is not None:
                        try:
                            req.callback(result)
                        except Exception:   # noqa: BLE001
                            log.exception("serve result callback failed")
        finally:
            with self._pending_lock:
                self._pending -= done.n
        self._stats_completed += done.n
        self._stats_occupancy.append(done.n / self.cfg.max_batch)
        reg = self._registry
        reg.inc("serve_responses_total", done.n)
        reg.inc("serve_batches_total")
        if done.cold:
            reg.inc("serve_prefills_total", done.cold)
        if done.evicted:
            reg.inc("serve_evictions_total", done.evicted)
        self._publish_stats()

    def _publish_stats(self, *, force: bool = False) -> None:
        """SLO gauges at ``stats_interval_s`` cadence (consumer thread)."""
        now = time.perf_counter()
        interval = now - self._stats_t
        if not force and interval < self.cfg.stats_interval_s:
            return
        if interval <= 0:
            return
        row: dict[str, float] = {
            "serve_qps": self._stats_completed / interval,
            "serve_queue_depth": float(self._q.qsize()),
        }
        if self._lat:
            pct = latency_percentiles(list(self._lat))
            row["serve_p50_ms"] = pct["p50_ms"]
            row["serve_p99_ms"] = pct["p99_ms"]
        if self._stats_occupancy:
            row["serve_batch_occupancy"] = (
                sum(self._stats_occupancy) / len(self._stats_occupancy))
        self._registry.record_many(row)
        self._stats_t = now
        self._stats_completed = 0
        self._stats_occupancy = []
