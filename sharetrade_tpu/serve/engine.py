"""Continuous-batching inference engine: one device program per tick.

The training side of this repo compiles everything; nothing served. This
module is ROADMAP item 2's serving tier: a policy-inference engine that
coalesces per-user ``(window, portfolio)`` queries into padded device
batches under a deadline (``serve.max_batch`` / ``serve.batch_timeout_ms``)
and keeps a fixed-capacity device-resident SESSION SLOT POOL — a
``(slots + max_batch, ...)`` arena of per-session recurrent carries, the
episode transformer's incremental K/V cache repurposed as a per-session
serving cache — so steady-state serving is ONE jitted batched program per
tick instead of a dispatch per request. That is the TF-Agents
batched-simulation thesis (arxiv 1709.02878) applied to inference, and
RLAX's TPU inference/learner decoupling (arxiv 2512.06392): throughput
comes from keeping one big batched program resident, not from many small
calls.

Structure (mirrors ``runtime/pipeline.py``'s dispatcher/consumer split):

- **submit** (any thread): enqueue a request; returns a waitable handle.
- **dispatcher thread** (``_serve_loop``): coalesce a batch (first request
  waits at most ``batch_timeout_ms``; a full batch never waits), admit
  sessions into the slot pool (LRU eviction; evicted sessions restart COLD
  through the batched prefill), and dispatch the jitted program(s) for the
  tick — asynchronously, so collection of tick k+1 overlaps device compute
  of tick k. No blocking host work happens here (tools/lint_hot_loop.py
  check 8).
- **consumer thread** (``_complete_batch``): device readback, request
  completion (events + callbacks), latency accounting, SLO gauge
  publication through ``MetricsRegistry`` (→ ``metrics.prom`` when obs
  export is on). The dispatcher→consumer queue is bounded, so in-flight
  device buffers are bounded and dispatch backpressures instead of racing
  ahead.

Weight swaps are ATOMIC between batches: :meth:`ServeEngine.swap_params`
replaces one ``(params, step)`` reference; the dispatcher reads it exactly
once per tick, so every response is attributable to exactly one checkpoint
step and no batch ever sees mixed weights (serve/swap.py is the
``tag_best`` watcher that calls it through the verified restore path).

Model contract: models providing ``apply_prefill``/``apply_serve_batch``
(the episode transformer) get the two-program cold/warm split — per-row
episode clocks, heterogeneous sessions in one batch. Everything else is
served through ``apply_batched`` in one program with an in-program cold-row
carry reset (stateless models like the MLP carry ``()`` and the pool is
structurally empty).

Parity contract (tests/test_serve.py): under fp32 the batched engine
returns BIT-IDENTICAL logits/actions to threading each session one at a
time through ``model.apply`` — batching is a scheduling optimization,
never a numerics change. bf16_mixed serving inherits the PR-7 tolerance
contract instead.

Overload & failure semantics (ISSUE 10; tools/serve_chaos.py pins them):

- **Admission control**: the ingress queue is bounded at
  ``serve.max_queue``; a submit past the bound never blocks and never
  grows host memory — the new request is refused
  (``shed_policy="reject"``) or the oldest queued request is shed
  (``"oldest"``), the loser completing immediately with
  :class:`ServeRejected`. Counters ``serve_queue_rejected_total`` /
  ``serve_shed_total``, gauge ``serve_overload``.
- **Deadlines**: ``submit(..., deadline_ms=)`` (default
  ``serve.default_deadline_ms``) expires un-dispatched requests with
  :class:`ServeDeadlineExceeded` at batch-collection time, before they
  can occupy a padded device row; coalescing waits are clamped to the
  earliest surviving deadline. Counter ``serve_deadline_expired_total``.
- **Supervision** (``serve.max_restarts > 0``): a dispatch/consumer
  fault fails its batch, then the engine itself is retried — fresh
  jitted programs + fresh slot arena under seeded exponential backoff
  (``serve.restart_backoff_s``); sessions re-enter cold through the
  batched prefill (bitwise-equivalent to a fresh session, the PR-8
  eviction contract). More than ``max_restarts`` CONSECUTIVE faults trip
  a terminal failed state that fails all queued work loudly
  (:class:`ServeEngineFailed`) instead of wedging. Counter
  ``serve_restarts_total``, gauge ``serve_failed``.

Every submitted request reaches exactly one terminal outcome — result,
rejection, deadline error, batch failure, or engine failure — the chaos
soak's core invariant.

Observability (ISSUE 11). Every request carries a :class:`RequestTrace`
stamped at each lifecycle edge (submitted → collected → dispatched →
device-complete → callback-complete, plus the shed/expired/failed
terminal edges and deferral counts). From the stamps the engine derives,
ALWAYS (they are the SLO gauges' source):

- **per-stage histograms** (obs/hist.py; fixed log buckets, exact
  merge): ``serve_queue_wait_ms`` / ``serve_batch_wait_ms`` /
  ``serve_device_ms`` / ``serve_readback_ms`` / ``serve_request_ms`` —
  and the ``serve_p50_ms``/``serve_p99_ms`` gauges are now quantiles of
  the end-to-end histogram's per-window bucket DELTA (cumulative counts
  subtract exactly), replacing the old sample-ring percentiles;
- **stage decomposition invariant**: for every completed request
  queue_wait + batch_wait + device == latency_ms by construction
  (telescoping perf_counter stamps); a violation increments
  ``serve_trace_decomposition_error_total``, which the soaks assert
  stays 0 (``ServeResult.stages`` carries the breakdown per response);
- **exemplars**: a bounded ring of the K slowest requests per stats
  window with their full stage breakdown (``obs.exemplar_k``), written
  to ``serve_exemplars.json`` when obs is on and recorded into the
  flight ring on overload onset / SLO burn / supervised restart /
  terminal failure;
- **SLO burn rates** (``obs.slo_*``): rolling error-budget burn gauges
  ``serve_slo_availability_burn`` (sheds/expiries/failures against the
  availability objective) and ``serve_slo_latency_burn`` (fraction over
  the target p99 against the 1% allowance), with a flight-recorder
  event on threshold crossing — the per-engine signal a fleet router
  aggregates.

Session tiers (ISSUE 18; ``serve.warm_bytes``). The slot pool is the
HOT tier of a hot/warm/cold hierarchy that lets one engine serve a
session POPULATION far larger than its device arena:

- **hot**: a device slot — the carry lives in the arena, steady-state
  requests run the warm program (unchanged).
- **warm**: a PARKED carry in :class:`WarmStore`, a bounded
  byte-budgeted host-RAM LRU. On eviction the victim's arena row is
  batch-gathered on the dispatch thread (async device op, never a
  readback), and the CONSUMER thread pages it out (``device_get`` —
  blocking host work belongs there, lint check 17) into the dispatcher's
  park inbox; the dispatcher commits it to the store, dropping entries
  whose session already re-entered (stale). A returning session's
  parked carry is reinstalled through the batched scatter path
  (``device_put`` + one jitted donated scatter) and the session
  continues BITWISE-identically to one that was never evicted — the
  round trip is an exact byte copy, the tier's acceptance oracle.
- **spill** (ISSUE 20; ``serve.spill_dir``): the warm store's overflow
  — and every live/parked carry at drain — seals into a
  crash-consistent on-disk parked-carry arena (serve/spill.py: CRC +
  step stamp + atomic rename), so RAM stops being the warm bound and a
  carry survives its writer's SIGKILL. The arena directory is SHARED
  across a fleet (fleet/pool.py): after an engine dies or drains, the
  engine the router reassigns a session to ADOPTS its carry — paged in
  iff the record's step stamp equals the session's expected clock (the
  router-forwarded completed-response count; an engine-local take with
  no clock accepts only its own incarnation's records). A stale, torn,
  or CRC-bad record demotes to cold — injected corruption can change
  latency, never bytes. Spill disk I/O rides the CONSUMER thread like
  page-out readback does (the dispatcher enqueues put/take/delete ops
  and only ever pays one ``os.stat`` probe); an adopted carry lands in
  the warm store and re-enters through the same batched scatter path,
  so an adopted session is bitwise an uninterrupted one.
- **cold**: everything else — the pre-existing
  restart-through-batched-prefill path, unchanged, and still what a
  warm-tier overflow demotes to (stalest parked carry first) when the
  spill tier is off or refuses the record.

``warm_bytes=0`` (default) disables the tier: every eviction is a cold
restart, bitwise-identical to the PR-8 contract. Eviction economics is
a live gauge: ``serve_warm_econ_ms_per_mb`` — prefill-recompute
milliseconds avoided by warm hits this stats window, per MB of carry
bytes held (EWMA'd cold device time × window hits / held MB). A spill
adoption flows through the warm store and counts as a warm hit at
admission, so the econ gauge prices spill hits too.

With obs enabled (``obs.request_trace``), the lifecycle additionally
emits through obs/trace.py as nested ASYNC spans keyed by
request/batch/session ids, so Perfetto renders request flows through the
batches the dispatcher coalesced them into; off by default, zero
artifacts, and the stamps themselves are a few ``perf_counter`` calls
per request (<2% measured — ``bench_obs_overhead`` serve arm).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import queue
import random
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.config import ConfigError, ServeConfig
from sharetrade_tpu.models.core import apply_batched
from sharetrade_tpu.obs import SERVE_STAGES
from sharetrade_tpu.obs.hist import Histogram
from sharetrade_tpu.precision import FP32, PrecisionPolicy
from sharetrade_tpu.serve.spill import SpillArena
from sharetrade_tpu.utils.logging import get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry

log = get_logger("serve")

_SHUTDOWN = object()
#: Done-queue nudge: the dispatcher enqueues spill ops for the consumer
#: and pokes this sentinel (put_nowait — best-effort; a full queue means
#: the consumer is already awake) so an IDLE consumer executes the disk
#: ops now instead of after its 200 ms poll.
_SPILL_TICK = object()

#: Session ids made only of these characters embed into trace JSON
#: without escaping (the fast path — harness/CLI ids are all of this
#: shape); anything else routes through json.dumps.
_SID_SAFE = re.compile(r"[A-Za-z0-9_\-#.:]*\Z").match


class ServeRejected(RuntimeError):
    """The request was refused admission (ingress queue at
    ``serve.max_queue`` under ``shed_policy="reject"``) or shed from the
    queue under overload (``shed_policy="oldest"``). Always delivered as a
    completed handle (``wait()`` returns None, :attr:`_Request.error`
    carries this), never as a silent block of the caller's thread.
    ``reason`` is ``"queue_full"`` / ``"shed_oldest"`` /
    ``"deferred_overflow"``."""

    def __init__(self, message: str, *, reason: str):
        super().__init__(message)
        self.reason = reason


class ServeDeadlineExceeded(RuntimeError):
    """The request's deadline (``submit(..., deadline_ms=)`` or
    ``serve.default_deadline_ms``) expired before it reached a device
    batch; it was completed with this error instead of occupying a padded
    device row."""


class ServeEngineFailed(RuntimeError):
    """The engine tripped its terminal failed state: more than
    ``serve.max_restarts`` consecutive dispatch/consumer faults. All
    queued and future work fails loudly with this error (wrapping the
    last underlying fault) instead of wedging."""


def latency_percentiles(values) -> dict[str, float]:
    """p50/p99/mean over a latency sample, ONE quantile convention for the
    whole serving tier (the SLO gauges here, the load harnesses in
    serve/driver.py, and the histogram quantiles in obs/hist.py —
    BASELINE.md compares them directly, so the percentile math must never
    diverge).

    Convention: NEAREST-RANK, rank = ceil(q·n), 1-indexed. The old
    ``int(q * (n - 1))`` floored the rank and systematically UNDERSTATED
    the tail at small n (with n=10 its "p99" was the 9th value — really
    p90); ceil(q·n) is the standard nearest-rank estimator whose reported
    p99 is a value at least 99% of the sample does not exceed."""
    if not len(values):
        return {"p50_ms": 0.0, "p99_ms": 0.0, "mean_ms": 0.0}
    arr = np.sort(np.asarray(values, np.float64))
    n = len(arr)

    def nearest_rank(q: float) -> float:
        return float(arr[min(max(math.ceil(q * n), 1), n) - 1])

    return {
        "p50_ms": nearest_rank(0.50),
        "p99_ms": nearest_rank(0.99),
        "mean_ms": float(arr.mean()),
    }


class RequestTrace:
    """Lifecycle stamps of one request, on the ``perf_counter`` clock.

    Stamps telescope, so the stage decomposition of a completed request
    sums EXACTLY to its end-to-end latency:

    ``queue_wait`` (t_enq→t_collected) + ``batch_wait``
    (t_collected→t_dispatched) + ``device`` (t_dispatched→t_device,
    device compute + readback of its group) == ``latency_ms``
    (t_device - t_enq); ``readback`` (t_device→t_done) is the
    completion/callback wait on top — the trace span shows that
    client-observable wall wait, while the ``serve_readback_ms``
    histogram charges each request only its OWN completion slice (the
    consumer serializes a batch's callbacks). Unstamped edges stay
    None (a shed request never collected; an expired one never
    dispatched)."""

    __slots__ = ("rid", "t_enq", "t_collected", "t_dispatched", "t_device",
                 "t_done", "deferrals", "cold", "batch", "outcome",
                 "trace_id", "parent_span")

    def __init__(self, rid: int, t_enq: float):
        self.rid = rid
        self.t_enq = t_enq
        self.t_collected: float | None = None
        self.t_dispatched: float | None = None
        self.t_device: float | None = None
        self.t_done: float | None = None
        self.deferrals = 0          # same-session ticks waited out
        self.cold = False           # served through the batched prefill
        self.batch: int | None = None   # dispatch tick serial
        self.outcome: str | None = None
        #: Fleet-wide trace identity (ISSUE 17): set by the wire backend
        #: (fleet/frontend.py) when the request arrived with trace
        #: headers, None for local/untraced submits — stitches this
        #: engine's chrome-trace spans to the cross-process trace.
        self.trace_id: str | None = None
        self.parent_span: str | None = None


class ServeResult(NamedTuple):
    """One completed inference: the action plus enough provenance to audit
    it (``params_step`` names the exact checkpoint that produced it — the
    hot-swap atomicity observable). ``stages`` is the request's latency
    decomposition (``queue_wait_ms``/``batch_wait_ms``/``device_ms``,
    summing exactly to ``latency_ms`` — the invariant the soaks assert);
    None only from servers that don't stage-stamp (BatchOneServer)."""

    session_id: Any
    action: int
    logits: np.ndarray
    value: float
    params_step: int
    latency_ms: float
    stages: dict | None = None


class _Live(NamedTuple):
    """The serving weights as ONE immutable reference: swapped atomically
    (a single attribute store), read exactly once per dispatch tick."""

    params: Any
    step: int


class _LiveKnobs(NamedTuple):
    """The engine's RUNTIME-TUNABLE knobs as one immutable reference —
    the same atomicity pattern as :class:`_Live` weights: swapped by
    :meth:`ServeEngine.set_knobs` (the online controller's actuator,
    serve/controller.py), read once per decision site, so a tick never
    sees a half-applied knob vector. The CONFIGURED values are the
    ceilings: the controller only ever tightens below them (config is
    the operator's safety rail, never something the controller can
    exceed)."""

    batch_timeout_ms: float
    max_queue: int


class _Request:
    """A submitted query; completed by the consumer thread (or, for
    rejected/expired work, by the thread that discovered the terminal
    outcome)."""

    __slots__ = ("session_id", "obs", "t_enq", "t_deadline", "callback",
                 "_event", "result", "error", "trace", "clock")

    def __init__(self, session_id: Any, obs: np.ndarray,
                 callback: Callable[[ServeResult | None], None] | None,
                 deadline_ms: float = 0.0, rid: int = 0,
                 clock: int | None = None):
        self.session_id = session_id
        self.obs = obs
        #: The session's EXPECTED step clock (ISSUE 20): the router's
        #: completed-response count, forwarded over the wire on
        #: migration so the adopting engine accepts a spilled carry iff
        #: its step stamp matches. None = local submit, no fleet clock —
        #: adoption falls back to the engine's own incarnation check.
        self.clock = clock
        self.t_enq = time.perf_counter()
        #: Lifecycle stamps (always kept — the per-stage histograms' and
        #: SLO gauges' source; the async trace spans ride them when obs
        #: request tracing is on).
        self.trace = RequestTrace(rid, self.t_enq)
        #: Absolute expiry on the perf_counter clock; None = no deadline.
        #: A NEGATIVE deadline_ms (a client whose latency budget already
        #: ran out before submit) means already-expired — clamped to the
        #: enqueue instant, NOT silently promoted to "no deadline".
        self.t_deadline = (self.t_enq + max(deadline_ms, 0.0) / 1e3
                           if deadline_ms else None)
        self.callback = callback
        self._event = threading.Event()
        self.result: ServeResult | None = None
        #: Set when the request failed terminally without a result —
        #: ServeRejected (admission/shedding), ServeDeadlineExceeded,
        #: ServeEngineFailed, or the dispatch fault that failed its batch
        #: — so callers can distinguish failure from a wait() timeout.
        self.error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> ServeResult | None:
        """Block until the response is ready; None on timeout or when the
        request failed (then :attr:`error` carries the cause)."""
        self._event.wait(timeout)
        return self.result


class _DoneBatch(NamedTuple):
    """One dispatched tick handed dispatcher→consumer: per-program request
    groups with their (still device-resident) outputs."""

    groups: list[tuple[list[_Request], Any, Any, Any]]  # (reqs, act, log, val)
    step: int
    n: int                 # real rows in the tick
    cold: int              # rows served through the prefill
    evicted: int           # sessions evicted to admit this tick's rows
    #: Supervision fault epoch at dispatch time: only a batch dispatched
    #: AFTER the latest fault may reset the consecutive-fault streak —
    #: pre-fault batches draining out of the done queue during a backoff
    #: attest nothing about post-fault engine health.
    epoch: int = 0
    #: Page-out payload (warm tier on, this tick evicted someone): the
    #: victims' session ids and their still-device-resident carry rows
    #: (stacked at the max_batch shape; only the first len(parked_sids)
    #: rows are real). The CONSUMER device_gets the rows and hands the
    #: host copies back through the dispatcher's park inbox.
    parked_sids: tuple = ()
    parked_rows: Any = None
    #: The victims' dispatched-step stamps (parallel to parked_sids):
    #: popped by the dispatcher at eviction time and carried through the
    #:  readback so the committed warm entry — and any spill record it
    #: later demotes into — is sealed with the right adoption clock.
    parked_steps: tuple = ()


class SlotPool:
    """Host-side session→slot map with LRU eviction.

    The carries themselves live on DEVICE in the engine's arena; this class
    owns only the mapping and the recency order. ``admit`` never evicts a
    session pinned by the current batch (its slot is about to be read or
    written) — with ``capacity >= max_batch`` an unpinned victim or a free
    slot always exists."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lru: OrderedDict[Any, int] = OrderedDict()  # oldest first
        self._free = list(range(capacity))
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._lru)

    def lookup(self, session_id: Any) -> int | None:
        """Slot of a WARM session (refreshes its recency); None when the
        session is absent (never admitted, or evicted — cold either way)."""
        slot = self._lru.get(session_id)
        if slot is not None:
            self._lru.move_to_end(session_id)
        return slot

    def contains(self, session_id: Any) -> bool:
        """Membership WITHOUT a recency refresh — the park-inbox
        staleness check (a session that re-entered the pool before its
        page-out committed makes that parked carry stale)."""
        return session_id in self._lru

    def drop(self, session_id: Any) -> None:
        """Forget a session (its slot returns to the free list) — the
        dispatch-fault path, where an admitted slot may never have
        received its prefilled carry."""
        slot = self._lru.pop(session_id, None)
        if slot is not None:
            self._free.append(slot)

    def admit(self, session_id: Any, pinned: set) -> tuple[int, Any | None]:
        """Assign a slot to a NEW session; returns ``(slot, evicted_sid)``
        (``evicted_sid`` None when a free slot absorbed the admission)."""
        if self._free:
            slot = self._free.pop()
            self._lru[session_id] = slot
            return slot, None
        for victim in self._lru:                       # oldest first
            if victim not in pinned:
                slot = self._lru.pop(victim)
                self._lru[session_id] = slot
                self.evictions += 1
                return slot, victim
        raise RuntimeError(
            "slot pool exhausted by pinned sessions (capacity < max_batch "
            "should have been rejected at construction)")


class WarmStore:
    """The WARM session tier: a bounded, byte-budgeted LRU of PARKED
    carries (host numpy trees read back by the consumer thread's
    page-out). Owned by ONE thread — the dispatcher commits, hits, and
    demotes; no lock guards the map. The stats other threads publish
    (``bytes``/``len``) read single references, atomic under the GIL.

    Bounded by construction (lint check 17): every ``put`` demotes
    stalest-first until BOTH the byte budget and the session bound hold
    again, and a single carry larger than the whole budget is refused
    outright (that session pages straight to cold)."""

    def __init__(self, max_bytes: int, max_sessions: int):
        self.max_bytes = int(max_bytes)
        self.max_sessions = max(1, int(max_sessions))
        #: session -> (rows, nbytes, steps): the carry, its footprint,
        #: and the session's dispatched-step stamp at park time (ISSUE
        #: 20 — the stamp travels with the carry so a demotion to the
        #: spill tier seals the right adoption clock into the record).
        self._lru: OrderedDict[Any, tuple[Any, int, int]] = OrderedDict()
        self.bytes = 0
        # Event totals (dispatcher-thread writes; readers see ints).
        self.demotions = 0
        self.refusals = 0
        self.stale_drops = 0

    def __len__(self) -> int:
        return len(self._lru)

    def contains(self, session_id: Any) -> bool:
        """Membership WITHOUT a recency refresh or removal — the
        dispatcher's spill-probe gate (a RAM-parked session never needs
        a disk take)."""
        return session_id in self._lru

    def pop(self, session_id: Any) -> tuple[Any, int] | None:
        """Remove and return a parked ``(carry, steps)`` (the warm HIT —
        unpark); None on a miss (never parked, demoted, or page-out
        still in flight — cold either way)."""
        entry = self._lru.pop(session_id, None)
        if entry is None:
            return None
        rows, nbytes, steps = entry
        self.bytes -= nbytes
        return rows, steps

    def discard(self, session_id: Any) -> None:
        """Forget a parked carry without returning it (poisoned/dropped
        sessions must not resurrect an old episode state)."""
        self.pop(session_id)

    def put(self, session_id: Any, rows: Any, nbytes: int,
            steps: int = 0) -> list:
        """Park one carry; returns the ENTRIES demoted to make room
        (stalest first, as ``(session, rows, nbytes, steps)`` tuples —
        the caller spills them to disk when the spill tier is on, or
        lets them fall to cold). A carry that cannot fit the budget at
        all is refused — the caller's session simply stays cold."""
        nbytes = int(nbytes)
        if nbytes <= 0 or nbytes > self.max_bytes:
            self.refusals += 1
            return []
        old = self._lru.pop(session_id, None)
        if old is not None:
            self.bytes -= old[1]
        self._lru[session_id] = (rows, nbytes, int(steps))
        self.bytes += nbytes
        demoted = []
        # The boundedness contract: demote stalest-first until both the
        # byte budget and the session bound hold (terminates — the entry
        # just parked fits the budget on its own).
        while (self.bytes > self.max_bytes
               or len(self._lru) > self.max_sessions):
            victim, (vrows, vbytes, vsteps) = self._lru.popitem(last=False)
            self.bytes -= vbytes
            self.demotions += 1
            demoted.append((victim, vrows, vbytes, vsteps))
        return demoted


class ServeEngine:
    """See the module docstring. Construct, :meth:`warmup` (optional but
    recommended — compiles the serving programs before traffic), submit
    from any thread, :meth:`stop` when done."""

    def __init__(self, model: Any, cfg: ServeConfig, params: Any, *,
                 params_step: int = 0,
                 precision: PrecisionPolicy = FP32,
                 registry: MetricsRegistry | None = None,
                 obs: Any = None,
                 obs_cfg: Any = None,
                 done_depth: int = 4,
                 restart_seed: int | None = None):
        if cfg.max_batch < 1:
            raise ConfigError(
                f"serve.max_batch must be >= 1, got {cfg.max_batch}")
        if cfg.slots < cfg.max_batch:
            raise ConfigError(
                f"serve.slots ({cfg.slots}) must be >= serve.max_batch "
                f"({cfg.max_batch}): every session of a full batch needs a "
                "live slot")
        if cfg.batch_timeout_ms < 0:
            raise ConfigError(
                f"serve.batch_timeout_ms must be >= 0, got "
                f"{cfg.batch_timeout_ms}")
        if cfg.max_queue < 1:
            raise ConfigError(
                f"serve.max_queue must be >= 1 (an unbounded ingress queue "
                f"turns a request flood into unbounded host memory), got "
                f"{cfg.max_queue}")
        if cfg.shed_policy not in ("reject", "oldest"):
            raise ConfigError(
                f"serve.shed_policy must be 'reject' or 'oldest', got "
                f"{cfg.shed_policy!r}")
        if cfg.default_deadline_ms < 0:
            raise ConfigError(
                f"serve.default_deadline_ms must be >= 0 (0 = none), got "
                f"{cfg.default_deadline_ms}")
        if cfg.max_restarts < 0:
            raise ConfigError(
                f"serve.max_restarts must be >= 0 (0 = no engine rebuild), "
                f"got {cfg.max_restarts}")
        if cfg.restart_backoff_s <= 0 or cfg.restart_backoff_max_s <= 0:
            raise ConfigError(
                "serve.restart_backoff_s / restart_backoff_max_s must be "
                f"> 0, got {cfg.restart_backoff_s}/"
                f"{cfg.restart_backoff_max_s}")
        if cfg.warm_bytes < 0:
            raise ConfigError(
                f"serve.warm_bytes must be >= 0 (0 disables the warm "
                f"tier), got {cfg.warm_bytes}")
        if cfg.warm_max_sessions < 1:
            raise ConfigError(
                f"serve.warm_max_sessions must be >= 1, got "
                f"{cfg.warm_max_sessions}")
        if cfg.spill_bytes < 0:
            raise ConfigError(
                f"serve.spill_bytes must be >= 0 (the spill tier is "
                f"byte-bounded like warm_bytes), got {cfg.spill_bytes}")
        if cfg.spill_dir and cfg.warm_bytes <= 0:
            raise ConfigError(
                "serve.spill_dir requires the warm tier "
                "(serve.warm_bytes > 0): the spill arena is the warm "
                "store's overflow and an adopted carry re-enters through "
                "it")
        self.model = model
        self.cfg = cfg
        self._precision = precision
        self._registry = registry if registry is not None else MetricsRegistry()
        self._obs = obs
        self._episode = (model.apply_prefill is not None
                         and model.apply_serve_batch is not None)
        self._live = _Live(jax.device_put(precision.cast_compute(params)),
                           int(params_step))
        self._carry0 = precision.cast_carry(model.init_carry(), model)
        #: One session's carry footprint in bytes — the warm tier's
        #: accounting unit (static per model/precision) and the
        #: numerator of the eviction-economics gauge.
        self._carry_nbytes = sum(
            int(leaf.size) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(self._carry0))
        #: Warm tier on only when budgeted AND the model has a carry to
        #: park (a stateless MLP's pool is structurally empty — there is
        #: nothing a warm tier could preserve).
        self._warm_enabled = (cfg.warm_bytes > 0
                              and self._carry_nbytes > 0)
        #: Spill tier on only with a configured arena directory AND a
        #: live warm tier to overflow from / adopt into.
        self._spill_enabled = bool(cfg.spill_dir) and self._warm_enabled
        self._build_arena_and_programs()

        # Live tunable knobs (tuned-knob-ok: seeded from config — the
        # ceiling — then adjusted only DOWNWARD by the online controller
        # through set_knobs). Read via self._knobs at each decision site.
        self._knobs = _LiveKnobs(
            batch_timeout_ms=float(cfg.batch_timeout_ms),
            max_queue=int(cfg.max_queue))
        # Current-knob gauges: every adjustment is VISIBLE (the ISSUE-14
        # contract — the controller may never move a knob silently).
        self._registry.record_many({
            "serve_knob_batch_timeout_ms": self._knobs.batch_timeout_ms,
            "serve_knob_max_queue": float(self._knobs.max_queue)})
        # Bounded ingress: depth caps at the live max_queue knob (seeded
        # from serve.max_queue, the hard ceiling), the overload surface
        # (submit sheds/rejects instead of growing host memory).
        # set_knobs() retargets the bound in place under the queue mutex.
        self._q: queue.Queue = queue.Queue(maxsize=cfg.max_queue)
        # trace-buffer-ok: bounded by logic, not maxlen — _collect_batch
        # sheds/rejects past cfg.max_queue (the deferred-overflow branch)
        self._deferred: deque[_Request] = deque()
        self._done_q: queue.Queue = queue.Queue(maxsize=done_depth)
        #: Sessions whose slot carry is suspect after a CONSUMER fault
        #: (the device program advanced their carries, the readback
        #: failed): appended by the consumer, drained — and dropped from
        #: the pool — by the DISPATCHER, which owns the SlotPool (a
        #: cross-thread drop would race admit()'s LRU iteration).
        self._poisoned: deque = deque()  # trace-buffer-ok: drained to empty
        # by the dispatcher every tick; growth is bounded by in-flight
        # batches (done_depth * max_batch)
        self._stop_event = threading.Event()
        self._pending = 0
        self._pending_lock = threading.Lock()

        # Supervision state (serve.max_restarts > 0): consecutive-fault
        # streak (guarded by _sup_lock — the dispatcher increments, the
        # consumer resets), the fault epoch gating those resets, a
        # consumer-side restart request, and the terminal fault.
        self._restart_streak = 0
        self._sup_lock = threading.Lock()
        self._fault_epoch = 0
        # Backoff jitter seed: None (the production default — cli serve
        # never passes one) draws per-process OS entropy, so a fleet of
        # replicas does NOT share a jitter sequence and restart in
        # lockstep; tests/the chaos soak pass an int for replayability.
        self._restart_rng = random.Random(restart_seed)
        self._restart_requested = threading.Event()
        self._consumer_fault: BaseException | None = None
        #: Fault epoch of the batch whose completion faulted: a fault
        #: from a batch dispatched BEFORE the latest restart is stale —
        #: the rebuild already cured it — and must not burn another
        #: restart from the streak.
        self._consumer_fault_epoch = 0
        self._failed: BaseException | None = None
        # Overload events since the last stats publication (guarded by
        # _pending_lock; feeds the serve_overload gauge).
        self._overload_events = 0

        # SLO accounting (consumer-thread-owned).
        self._stats_t = time.perf_counter()
        self._stats_completed = 0
        self._stats_occupancy: list[float] = []
        # Eviction-economics inputs (survive a supervised rebuild — they
        # are measurements, not session state): EWMA cold-re-entry cost
        # and the warm-hit counter base of the last stats window.
        self._ewma_prefill_ms = 0.0
        self._prev_warm_hits = 0.0
        #: Serializes _publish_stats: the consumer thread publishes after
        #: every batch, but terminal FAILURES (shed/reject/expiry/engine-
        #: failed) also publish from their own threads — during a total
        #: outage nothing completes, and the availability burn gauge must
        #: climb DURING the incident, not after the first post-recovery
        #: batch. Non-force callers skip instead of blocking.
        self._stats_lock = threading.Lock()

        # ---- request-level observability (ISSUE 11) ------------------
        # obs_cfg carries the obs.request_trace / exemplar_k / slo_*
        # knobs; None (library users without an ObsConfig) = tracing off,
        # default exemplars, SLO disabled. The stage stamps + histograms
        # below are ALWAYS on: they are the serve_p50/p99 gauges' source.
        self._obs_cfg = obs_cfg
        slo_avail = float(getattr(obs_cfg, "slo_availability", 0.0) or 0.0)
        slo_p99 = float(getattr(obs_cfg, "slo_target_p99_ms", 0.0) or 0.0)
        slo_window = float(getattr(obs_cfg, "slo_window_s", 60.0))
        slo_burn_thr = float(getattr(obs_cfg, "slo_burn_threshold", 2.0))
        if not 0.0 <= slo_avail < 1.0:
            raise ConfigError(
                f"obs.slo_availability must be in [0, 1) (0 disables), "
                f"got {slo_avail}")
        if slo_p99 < 0 or slo_window <= 0 or slo_burn_thr <= 0:
            raise ConfigError(
                "obs.slo_target_p99_ms must be >= 0 and slo_window_s / "
                f"slo_burn_threshold > 0, got {slo_p99}/{slo_window}/"
                f"{slo_burn_thr}")
        self._slo = (slo_avail, slo_p99, slo_window, slo_burn_thr)
        self._slo_on = slo_avail > 0 or slo_p99 > 0
        #: Terminal-outcome totals (cumulative; guarded by _pending_lock,
        #: which both terminal paths already hold): the burn-rate window
        #: diffs these.
        self._term_total = 0
        self._term_bad = 0
        self._term_completed = 0
        self._term_slow = 0
        #: Rolling window of cumulative snapshots, one per stats publish,
        #: SEEDED with an all-zero snapshot at construction: without it
        #: the first publish's own append is the delta base (d == 0), so
        #: a run — or an incident — that terminates entirely within the
        #: first stats interval would never publish a burn rate at all.
        # trace-buffer-ok: bounded ring (maxlen) of per-window snapshots
        self._slo_win: deque[tuple] = deque(maxlen=4096)
        self._slo_win.append((self._stats_t, 0, 0, 0, 0))
        self._burn_alarm = False
        # Request/batch serials: itertools.count.__next__ is atomic under
        # CPython, so submit stays lock-free for the id.
        self._rid = itertools.count(1)
        self._batch_serial = 0          # dispatcher-thread-owned
        # Per-stage histograms (obs/hist.py; the default fixed ms-bucket
        # layout, so every engine's export merges exactly): attached to
        # the registry for metrics.prom export, observed via these direct
        # references off the registry lock.
        self._hists = {
            name: self._registry.attach_histogram(name, Histogram())
            for name in ("serve_request_ms",
                         *(f"serve_{s}_ms" for s in SERVE_STAGES))}
        self._h_e2e = self._hists["serve_request_ms"]
        #: End-to-end bucket counts at the last stats publish — the
        #: per-window delta the p50/p99 gauges are quantiled over.
        self._p50_prev_counts = self._h_e2e.snapshot()["counts"]
        # Exemplars: top-K slowest of the current window (consumer-thread
        # list, trimmed to K), folded per publish into a bounded ring.
        self._exemplar_k = max(0, int(getattr(obs_cfg, "exemplar_k", 8)
                                      if obs_cfg is not None else 8))
        self._window_slowest: list[dict] = []
        # trace-buffer-ok: bounded exemplar ring (maxlen = 4 windows of K)
        self._exemplars: deque[dict] = deque(
            maxlen=max(1, 4 * self._exemplar_k))
        #: Guards _window_slowest/_exemplars: the consumer appends while
        #: failure-path publishes fold the window from their own threads
        #: and _supervise/cli snapshot the ring — an unlocked deque
        #: iteration concurrent with extend() raises and would kill the
        #: reading thread. Ordering: _stats_lock may take _ex_lock,
        #: never the reverse.
        self._ex_lock = threading.Lock()
        #: Ring changed since the last serve_exemplars.json write (folds
        #: with io_ok=False — failure-path publishes — defer the file IO
        #: to the next consumer/stop publish).
        self._ex_dirty = False
        self._overload_flagged = False
        # Per-request trace emission: cached tracer reference, None unless
        # obs is enabled with the span trace + request_trace knob on — the
        # zero-artifact default costs one attribute check per request.
        tracer = getattr(obs, "tracer", None)
        self._req_tracer = (
            tracer if (obs is not None and getattr(obs, "enabled", False)
                       and tracer is not None and tracer.enabled
                       and (obs_cfg is None
                            or getattr(obs_cfg, "request_trace", True)))
            else None)

        self._dispatcher = threading.Thread(
            target=self._serve_loop, name="serve-dispatcher", daemon=True)
        self._consumer = threading.Thread(
            target=self._complete_loop, name="serve-consumer", daemon=True)
        self._dispatcher.start()
        self._consumer.start()

    def _build_arena_and_programs(self) -> None:
        """Fresh slot pool, fresh device arena, fresh jitted programs —
        construction AND the supervised-restart rebuild path (a restart
        discards every compiled program and every slot carry; sessions
        re-enter cold through the batched prefill, which PR 8 pinned as
        bitwise-equivalent to a fresh session suffix).

        Device arena: one carry row per slot, plus max_batch SCRATCH rows
        (indices >= cfg.slots) that padding rows read/write so a partial
        batch can never touch a live session's slot.

        The arena is DONATED on every backend: scatter into an aliased
        buffer updates in place, a non-donated pool round-trips a full
        arena copy per tick (measured 5.5x tick cost at the soak shape).
        The PR-4 CPU donation carve-out (runtime/orchestrator.py) does
        not apply here: its segfault was a consumer device_get racing a
        dispatch that donated the very state the readback came from; the
        pool never leaves the device, and the consumer reads only the
        action/logit/value outputs, which are never donated."""
        cfg = self.cfg
        self._slots = SlotPool(cfg.slots)
        # Fresh warm tier too: the restart contract is ALL sessions cold
        # (a parked carry would survive the rebuild bit-exactly, but the
        # documented supervision semantics — and the soak's assertions —
        # say a rebuilt engine serves only cold re-entries).
        self._warm = WarmStore(cfg.warm_bytes, cfg.warm_max_sessions)
        # Page-outs the consumer has read back but the dispatcher has
        # not yet committed to the store (single-owner handoff: the
        # consumer appends host carries, the dispatcher — who owns ALL
        # admission state — drains at the top of each tick and drops
        # entries whose session already re-entered).
        # trace-buffer-ok: bounded by in-flight batches
        # (done_depth * max_batch entries at most)
        self._park_inbox: deque = deque()
        # ---- spill tier (ISSUE 20) ----------------------------------
        #: Per-session dispatched-step counts for HOT sessions (the
        #: adoption-clock source; travels into WarmStore entries and
        #: spill records at park time). Dispatcher-owned; bounded by
        #: the slot-pool capacity — entries are popped at eviction.
        self._steps: dict[Any, int] = {}
        #: Disk-op FIFO dispatcher -> consumer ("put"/"del"/"take"
        #: tuples): the dispatcher NEVER touches the arena files beyond
        #: an os.stat probe — all real I/O rides the consumer, like
        #: page-out readback (lint checks 8/17/19).
        # Puts are warm-store demotions (bounded by the park inbox);
        # takes are capped by _spill_inflight — one per distinct
        # deferred session, itself capped by the ingress bound.
        # trace-buffer-ok: bounded by park inbox + _spill_inflight
        self._spill_ops: deque = deque()
        #: Completed takes consumer -> dispatcher: (sid, rows|None,
        #: steps, reason) — drained at the top of batch collection.
        # trace-buffer-ok: bounded by _spill_inflight
        self._spill_inbox: deque = deque()
        #: Sessions with a take in flight: their requests DEFER (the
        #: carry is coming — admitting them cold would fork the
        #: episode). Dispatcher-owned.
        self._spill_inflight: set = set()
        if self._spill_enabled:
            # A fresh incarnation per (re)build: an engine-local take
            # with no fleet clock accepts only same-incarnation records,
            # so the supervised-restart contract (a rebuilt engine
            # serves only cold re-entries) survives the spill tier —
            # every pre-fault record reads as stale to the rebuilt
            # engine, while a CLOCKED fleet take can still adopt it.
            self._incarnation = os.urandom(8).hex()
            self._arena: SpillArena | None = SpillArena(
                cfg.spill_dir, max_bytes=cfg.spill_bytes,
                record_nbytes=self._carry_nbytes,
                incarnation=self._incarnation)
        else:
            self._arena = None
        #: Last spill-gauge re-anchor (perf_counter): shared cadence
        #: between the consumer's stats publish and the health-probe
        #: refresh, so the two never double-scan one window.
        self._spill_scan_t = 0.0
        n_arena = cfg.slots + cfg.max_batch
        self._pool = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], n_arena, axis=0),
            self._carry0)
        # Per-row init carries for the generic path's in-program cold reset.
        self._carry0_rows = jax.tree.map(
            lambda x: jnp.repeat(jnp.asarray(x)[None], cfg.max_batch,
                                 axis=0), self._carry0)
        donate = (1,)
        if self._episode:
            self._warm_fn = jax.jit(self._warm_program, donate_argnums=donate)
            self._cold_fn = jax.jit(self._cold_program, donate_argnums=donate)
        else:
            self._step_fn = jax.jit(self._generic_program,
                                    donate_argnums=donate)
        if self._warm_enabled:
            # Paging programs, both at the static max_batch shape (one
            # compile each). The park gather does NOT donate — the arena
            # must survive it for the tick's programs; the unpark
            # install donates like every other arena writer.
            self._park_fn = jax.jit(self._park_program)
            self._install_fn = jax.jit(self._install_program,
                                       donate_argnums=(0,))

    # -- device programs --------------------------------------------------

    def _warm_program(self, params, pool, obs, idx):
        """One incremental step for a warm batch: gather slot carries,
        per-row-clock serve step, scatter back. THE steady-state program."""
        rows = jax.tree.map(lambda x: x[idx], pool)
        out, new_rows = self.model.apply_serve_batch(params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _cold_program(self, params, pool, obs, idx):
        """Batched re-prefill: cold sessions (fresh or evicted) compute
        their episode-start pass and land their carries in their slots."""
        out, new_rows = self.model.apply_prefill(params, obs)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    def _park_program(self, pool, idx):
        """Batch-gather the tick's eviction victims' carry rows (page-out
        step 1). Async device compute, never a readback — legal on the
        dispatch thread; the CONSUMER device_gets the result."""
        return jax.tree.map(lambda x: x[idx], pool)

    def _install_program(self, pool, rows, idx):
        """Scatter parked carries back into their (re-)admitted slots
        (unpark): the same ``.at[idx].set`` path every program writes
        through, so a warm re-entry is bitwise a never-evicted session."""
        return jax.tree.map(lambda p, r: p.at[idx].set(r), pool, rows)

    def _generic_program(self, params, pool, obs, idx, cold):
        """Single program for models without a prefill/incremental split:
        cold rows take a fresh init carry in-program, everything else runs
        ``apply_batched`` (no cross-row constraint to honor)."""
        rows = jax.tree.map(lambda x: x[idx], pool)

        def reset_cold(init_row, row):
            mask = cold.reshape((-1,) + (1,) * (row.ndim - 1))
            return jnp.where(mask, init_row, row)

        rows = jax.tree.map(reset_cold, self._carry0_rows, rows)
        out, new_rows = apply_batched(self.model, params, obs, rows)
        new_pool = jax.tree.map(lambda p, r: p.at[idx].set(r), pool,
                                new_rows)
        actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
        return actions, out.logits, out.value, new_pool

    # -- public surface ---------------------------------------------------

    def submit(self, session_id: Any, obs: Any,
               callback: Callable[[ServeResult], None] | None = None,
               *, deadline_ms: float | None = None,
               session_clock: int | None = None) -> _Request:
        """Enqueue one ``(window, portfolio)`` query; thread-safe. Returns
        a handle whose :meth:`_Request.wait` blocks for the response;
        ``callback(result)`` additionally fires on the consumer thread.

        ``deadline_ms`` bounds how long the request may wait before it is
        completed with a :class:`ServeDeadlineExceeded` error instead of
        being served (None = ``serve.default_deadline_ms``; 0 = none).

        ``session_clock`` (ISSUE 20) is the session's expected
        completed-response count, forwarded by the fleet router on
        migration: a spilled carry is adopted warm iff its step stamp
        matches this; None (local submits) restricts adoption to records
        this engine incarnation wrote.

        NEVER blocks on a full queue: past ``serve.max_queue`` the
        request is refused (``shed_policy="reject"``) or the oldest
        queued request is shed to make room (``"oldest"``) — either way
        the loser's handle completes immediately with
        :class:`ServeRejected` (its callback fires with None on the
        CALLER's thread, the one place completion doesn't ride the
        consumer)."""
        if self._stop_event.is_set():
            raise RuntimeError("serve engine is stopped")
        if self._failed is not None:
            raise ServeEngineFailed(
                "serve engine is in the terminal failed state "
                f"(last fault: {self._failed!r}); rebuild it") \
                from self._failed
        if deadline_ms is None:
            deadline_ms = self.cfg.default_deadline_ms
        req = _Request(session_id, np.asarray(obs, np.float32), callback,
                       deadline_ms=deadline_ms, rid=next(self._rid),
                       clock=(int(session_clock)
                              if session_clock is not None else None))
        with self._pending_lock:
            self._pending += 1
        self._registry.inc("serve_requests_total")
        while True:
            try:
                self._q.put_nowait(req)
                if (self._stop_event.is_set()
                        and not self._dispatcher.is_alive()):
                    # TOCTOU: stop() completed between our gate check at
                    # the top and this put — nobody will ever read the
                    # queue again, so sweep it ourselves (pop-ownership
                    # makes this race-safe against other sweepers).
                    self._fail_leftovers()
                return req
            except queue.Full:
                pass
            with self._pending_lock:
                self._overload_events += 1
            if self.cfg.shed_policy == "reject":
                self._registry.inc("serve_queue_rejected_total")
                self._registry.record("serve_overload", 1.0)
                self._finish_failed(req, ServeRejected(
                    f"ingress queue full ({self._knobs.max_queue}); "
                    "request rejected under shed_policy='reject'",
                    reason="queue_full"))
                return req
            # shed_policy == "oldest": drop the oldest queued request and
            # retry the admission (the dispatcher may race us for it —
            # an Empty get just means the queue drained; retry the put).
            try:
                victim = self._q.get_nowait()
            except queue.Empty:
                continue
            self._registry.inc("serve_shed_total")
            self._registry.record("serve_overload", 1.0)
            self._finish_failed(victim, ServeRejected(
                f"shed from the ingress queue under overload "
                f"(shed_policy='oldest', "
                f"max_queue={self._knobs.max_queue})",
                reason="shed_oldest"))

    def _finish_failed(self, req: _Request, exc: BaseException) -> None:
        """Complete a request with a terminal error outcome (rejection,
        shed, deadline expiry, engine failure): release its waiter, fire
        its callback with None, and un-count it from the drain-pending
        total — a failed request must never strand :meth:`drain`."""
        with self._pending_lock:
            self._pending -= 1
            self._term_total += 1
            self._term_bad += 1
        req.error = exc
        req._event.set()
        if req.callback is not None:
            try:
                req.callback(None)
            except Exception:   # noqa: BLE001
                log.exception("serve failure callback failed")
        if isinstance(exc, ServeRejected):
            outcome = exc.reason            # queue_full / shed_oldest / ...
        elif isinstance(exc, ServeDeadlineExceeded):
            outcome = "expired"
        elif isinstance(exc, ServeEngineFailed):
            outcome = "engine_failed"
        else:
            outcome = "failed"
        self._trace_request(req, outcome, time.perf_counter())
        # Terminal failures drive the stats cadence too: under a total
        # outage (restart storm, flood of sheds) no batch ever completes,
        # and the availability-burn gauge/alert must fire mid-incident.
        # io_ok=False: this runs on the submit caller's or dispatcher's
        # thread — the exemplar file write must not ride either.
        self._publish_stats(io_ok=False)

    #: Request-flow lanes: request spans render on synthetic tids (one of
    #: 64 lanes by request id) so overlapping lifecycles draw as parallel
    #: tracks in Perfetto, with the envelope span time-containing its
    #: stage children (track-local nesting). Base offset keeps lanes away
    #: from real thread ids.
    _TRACE_LANE_BASE = 1_000_000
    _TRACE_LANES = 64

    def _trace_request(self, req: _Request, outcome: str,
                       t_end: float, lines: list[str] | None = None
                       ) -> None:
        """Emit the request's whole lifecycle — one ``serve_request``
        envelope plus one child span per stamped stage, keyed by
        request/batch/session ids in the args — called exactly once per
        terminal outcome, from whichever thread discovered it. The events
        are PRE-SERIALIZED f-string lines (per-event ``json.dumps`` on
        the completion thread measured ~40 µs/request — a 3x throughput
        tax at CPU-MLP request costs); ``lines`` (the batch-completion
        path) accumulates them for ONE bulk tracer append per batch.
        No-op (one attribute check) when request tracing is off."""
        tracer = self._req_tracer
        if tracer is None:
            return
        tr = req.trace
        tr.outcome = outcome
        to_us = tracer.to_us
        pid = tracer.pid
        lane = self._TRACE_LANE_BASE + tr.rid % self._TRACE_LANES
        ts0 = to_us(tr.t_enq)
        sid = req.session_id
        session = (f'"{sid}"' if type(sid) is str and _SID_SAFE(sid)
                   else json.dumps(str(sid)))
        own = lines is None
        if own:
            lines = []
        # The fleet trace id rides along when the wire set one, so a
        # per-engine chrome trace cross-references the stitched
        # cross-process trace (obs/collect.py) by id.
        fleet = (f',"trace":"{tr.trace_id}"'
                 if tr.trace_id is not None else "")
        lines.append(
            f'{{"name":"serve_request","cat":"serve","ph":"X",'
            f'"ts":{ts0:.3f},"dur":{to_us(t_end) - ts0:.3f},'
            f'"pid":{pid},"tid":{lane},"args":{{"request":{tr.rid},'
            f'"session":{session},"outcome":"{outcome}",'
            f'"batch":{tr.batch if tr.batch is not None else 0},'
            f'"cold":{"true" if tr.cold else "false"},'
            f'"deferrals":{tr.deferrals}{fleet}}}}}')
        for name, t0, t1 in (("queue_wait", tr.t_enq, tr.t_collected),
                             ("batch_wait", tr.t_collected,
                              tr.t_dispatched),
                             ("device", tr.t_dispatched, tr.t_device),
                             ("readback", tr.t_device, tr.t_done)):
            if t0 is not None and t1 is not None:
                za = to_us(t0)
                lines.append(
                    f'{{"name":"{name}","cat":"serve","ph":"X",'
                    f'"ts":{za:.3f},"dur":{to_us(t1) - za:.3f},'
                    f'"pid":{pid},"tid":{lane},'
                    f'"args":{{"request":{tr.rid}}}}}')
        if own:
            tracer.emit_lines(lines)

    @property
    def params_step(self) -> int:
        """Checkpoint step of the CURRENT serving weights."""
        return self._live.step

    @property
    def failed(self) -> BaseException | None:
        """The terminal fault, when the engine tripped its failed state
        (None while healthy). Terminal = submits raise ServeEngineFailed
        and all queued work has been failed loudly."""
        return self._failed

    def queue_depth(self) -> int:
        """Current ingress-queue depth (bounded by ``serve.max_queue`` —
        the chaos soak's queue invariant reads this)."""
        return self._q.qsize()

    @property
    def registry(self) -> MetricsRegistry:
        """The engine's metrics registry (counters + SLO gauges)."""
        return self._registry

    @property
    def knobs(self) -> _LiveKnobs:
        """The CURRENT live knob vector (one immutable reference — the
        controller's read side)."""
        return self._knobs

    @property
    def latency_histogram(self):
        """The end-to-end request-latency histogram (obs/hist.py): the
        online controller windows its p99 objective off snapshot deltas
        of this — the same bucket math as the ``serve_p99_ms`` gauge."""
        return self._h_e2e

    def set_knobs(self, *, batch_timeout_ms: float | None = None,
                  max_queue: int | None = None) -> _LiveKnobs:
        """Atomically install new runtime knob values (the online
        controller's actuator; also usable by hand). Both knobs are
        clamped to the CONFIGURED values as ceilings — ``serve.
        batch_timeout_ms`` / ``serve.max_queue`` are the operator's
        safety rails, and a controller that could raise the queue bound
        above config would re-open the unbounded-ingress memory hole
        admission control closed. Values are validated loudly; the new
        vector is returned and published as gauges."""
        cur = self._knobs
        if batch_timeout_ms is None:
            batch_timeout_ms = cur.batch_timeout_ms
        if max_queue is None:
            max_queue = cur.max_queue
        batch_timeout_ms = float(batch_timeout_ms)
        max_queue = int(max_queue)
        if batch_timeout_ms < 0:
            raise ConfigError(
                f"batch_timeout_ms must be >= 0, got {batch_timeout_ms}")
        if max_queue < 1:
            raise ConfigError(f"max_queue must be >= 1, got {max_queue}")
        batch_timeout_ms = min(batch_timeout_ms, self.cfg.batch_timeout_ms)
        max_queue = min(max_queue, self.cfg.max_queue)
        new = _LiveKnobs(batch_timeout_ms=batch_timeout_ms,
                         max_queue=max_queue)
        self._knobs = new
        if max_queue != cur.max_queue:
            # Retarget the physical ingress bound in place: put_nowait
            # checks maxsize under this mutex, so the new bound applies
            # to the very next admission. Shrinking below the current
            # depth is safe — admissions fail (shed/reject) until the
            # dispatcher drains back under the bound, which is exactly
            # the brownout behavior the shrink asked for.
            with self._q.mutex:
                self._q.maxsize = max_queue
                self._q.not_full.notify_all()
        self._registry.record_many({
            "serve_knob_batch_timeout_ms": new.batch_timeout_ms,
            "serve_knob_max_queue": float(new.max_queue)})
        return new

    def swap_params(self, master_params: Any, step: int) -> None:
        """Atomically install new serving weights between batches. The
        dispatcher reads the live reference once per tick, so a batch
        computes entirely under one step's weights — in-flight ticks keep
        the old params alive until their buffers are read back."""
        params = jax.device_put(self._precision.cast_compute(master_params))
        self._live = _Live(params, int(step))
        self._registry.inc("serve_swaps_total")
        log.info("serving params swapped to step %d", int(step))

    def warmup(self) -> None:
        """Compile every serving program with a scratch-only batch (live
        slots untouched). Call before traffic so the first real request
        doesn't pay the compile. Must run before concurrent submits."""
        cfg = self.cfg
        obs_dim = getattr(self.model, "obs_dim", 0) or 3
        obs = np.full((cfg.max_batch, obs_dim), 10.0, np.float32)
        idx = np.arange(cfg.slots, cfg.slots + cfg.max_batch, dtype=np.int32)
        if self._episode:
            _, _, _, pool = self._cold_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
            _, _, _, pool = self._warm_fn(self._live.params, self._pool,
                                          obs, idx)
            self._pool = pool
        else:
            cold = np.ones((cfg.max_batch,), bool)
            _, _, _, pool = self._step_fn(self._live.params, self._pool,
                                          obs, idx, cold)
            self._pool = pool
        if self._warm_enabled:
            # Compile the paging programs too — a first-eviction compile
            # on the dispatch thread would stall every queued deadline.
            # Scratch-only, like everything else here: the gather pads
            # to scratch row 0, the install writes only scratch rows.
            pidx = np.full((cfg.max_batch,), cfg.slots, np.int32)
            self._park_fn(self._pool, pidx)
            row0 = jax.tree.map(np.asarray, self._carry0)
            self._pool = self._install_parked([row0], [cfg.slots])

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submitted request has been answered (the
        SIGTERM drain of ``cli serve``); False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._pending_lock:
                if self._pending == 0:
                    return True
            time.sleep(0.002)   # serve-block-ok: drain's bounded poll runs
            # on the CALLER's thread (cli shutdown), never the dispatch path.
        with self._pending_lock:
            return self._pending == 0

    def stop(self, *, drain: bool = True, timeout_s: float = 30.0) -> bool:
        """Drain (optionally), stop both threads, publish final gauges.

        Returns False — loudly — when either thread is still alive after
        its join timeout: a hung dispatcher/consumer means in-flight work
        may never complete, and the caller (``cli serve``'s SIGTERM path)
        must exit nonzero instead of reporting a clean shutdown."""
        if drain:
            self.drain(timeout_s)
        self._stop_event.set()
        self._dispatcher.join(timeout_s)
        if not self._dispatcher.is_alive():
            # The dispatcher failed its leftovers in its own exit path;
            # this sweep catches requests that raced in between that
            # sweep and its death (safe now — the owner is gone).
            self._fail_leftovers()
        try:
            # Bounded put: with the consumer hung behind a full done
            # queue, an unbounded put would hang stop() itself.
            self._done_q.put(_SHUTDOWN, timeout=timeout_s)
        except queue.Full:
            pass
        self._consumer.join(timeout_s)
        ok = True
        for thread in (self._dispatcher, self._consumer):
            if thread.is_alive():
                log.error(
                    "serve %s thread still alive %.1fs after stop(): "
                    "shutdown is NOT clean (in-flight requests may never "
                    "complete)", thread.name, timeout_s)
                ok = False
        self._publish_stats(force=True)
        return ok

    def page_out_all(self) -> dict[str, int]:
        """Drain-time warm handoff (ISSUE 20): seal EVERY surviving
        carry — RAM-parked, hot slot rows, and in-flight page-outs/
        adoptions — into the spill arena, so the engines this one's
        sessions are reassigned to adopt them warm instead of paying the
        cold-restart prefill for the whole population.

        ORDERING CONTRACT (the drain test asserts it): drain →
        ``stop()`` → ``page_out_all()`` → exit 75. This method REFUSES
        while either worker thread is alive — a live dispatcher still
        mutates the stores and a live consumer still owes page-out
        readbacks; only after ``stop()`` does the caller's thread own
        every structure (and may block on device readback freely).

        Returns ``{"written", "refused", "skipped_takes"}`` for the cli
        shutdown summary; all-zero without a spill arena."""
        if self._dispatcher.is_alive() or self._consumer.is_alive():
            raise RuntimeError(
                "page_out_all() before stop(): the dispatcher/consumer "
                "threads still own the session stores — the drain "
                "ordering is drain -> stop() -> page_out_all() -> exit")
        counts = {"written": 0, "refused": 0, "skipped_takes": 0}
        arena = self._arena
        if arena is None:
            return counts
        counts["skipped_takes"] = sum(
            1 for op in self._spill_ops if op[0] == "take")
        # Settle queued ops first: puts seal, deletes tombstone, takes
        # skip (stop_event is set — the records stay for adopters).
        self._drain_spill_ops()

        def _seal(sid: Any, rows: Any, steps: int) -> None:
            if arena.put(sid, jax.tree.leaves(rows), steps):
                counts["written"] += 1
                self._registry.inc("serve_spill_puts_total")
            else:
                counts["refused"] += 1
                self._registry.inc("serve_spill_put_refusals_total")

        # Page-outs the consumer read back that never committed, and
        # adopted takes that never reached a batch: their state exists
        # ONLY in these inboxes now — seal or the carry dies here.
        while self._park_inbox:
            sid, rows, steps = self._park_inbox.popleft()
            if not self._slots.contains(sid):
                _seal(sid, rows, steps)
        while self._spill_inbox:
            sid, rows, steps, _reason = self._spill_inbox.popleft()
            if rows is not None and not self._slots.contains(sid):
                _seal(sid, rows, steps)
        # The RAM-warm population (single-owner map — the dispatcher
        # that owned it is provably dead).
        for sid, (rows, _nbytes, steps) in list(self._warm._lru.items()):
            _seal(sid, rows, steps)
        # The hot population: ONE bulk arena readback, then per-session
        # row copies. serve-host-ok: post-stop, the caller's thread.
        if len(self._slots):
            host_pool = jax.device_get(self._pool)
            for sid, slot in self._slots._lru.items():
                rows = jax.tree.map(
                    lambda x: np.asarray(x[slot]).copy(), host_pool)
                _seal(sid, rows, self._steps.get(sid, 0))
        log.info(
            "drain page-out sealed %d carr%s to the spill arena "
            "(%d refused, %d takes left for adopters)",
            counts["written"], "y" if counts["written"] == 1 else "ies",
            counts["refused"], counts["skipped_takes"])
        self._publish_stats(force=True)
        return counts

    # -- dispatcher thread ------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop_event.is_set():
            if self._failed is not None:
                # Terminal failed state: never wedge — every request that
                # raced past the submit-side gate still gets a loud
                # terminal outcome.
                self._drain_failed()
                continue
            # Sessions a consumer fault poisoned (their slot carries
            # advanced but the responses were lost): drop them so their
            # next request re-enters cold instead of double-stepping a
            # warm carry. Best-effort — a same-session request already
            # in flight this tick may still read the advanced carry; the
            # supervision rebuild (max_restarts > 0) resets even that.
            while self._poisoned:
                sid = self._poisoned.popleft()
                self._slots.drop(sid)
                self._steps.pop(sid, None)
            if self._restart_requested.is_set():
                self._restart_requested.clear()
                # Epoch-gate: a fault from a batch dispatched before the
                # latest restart was already cured by that rebuild; only
                # a current-epoch fault earns another restart.
                if self._consumer_fault_epoch >= self._fault_epoch:
                    self._supervise(self._consumer_fault
                                    or RuntimeError("serve consumer fault"))
                continue
            batch = self._collect_batch()
            if not batch:
                continue
            live = self._live       # ONE read per tick: the atomicity seam
            try:
                done = self._dispatch_batch(batch, live)
            except Exception as exc:    # noqa: BLE001 — one malformed
                # request (bad obs shape) must fail ITS batch, not wedge
                # the dispatcher and hang every later session.
                self._fail_batch(batch, exc)
                # ... and with supervision on, retry the ENGINE: rebuild
                # programs + arena under seeded backoff (no-op at the
                # default max_restarts=0, the PR-8 contract).
                self._supervise(exc)
                continue
            # Bounded handoff: blocking here is the backpressure that
            # keeps in-flight device buffers bounded (pipeline.py's put).
            self._done_q.put(done)
        # Dispatcher exit: whatever is still queued/deferred can never be
        # dispatched — fail it terminally HERE, on the thread that owns
        # these structures (stop() and submit() re-sweep only for racers,
        # and only once this thread is provably dead).
        self._fail_leftovers()

    def _fail_leftovers(self) -> None:
        """Fail every request still in the ingress/deferred queues with a
        terminal stopped error. Safe concurrently: items transfer to the
        caller one pop at a time, so each request is completed exactly
        once even when stop()/submit() racers sweep alongside the
        dispatcher's own exit sweep."""
        leftover = RuntimeError(
            "serve engine stopped before this request was dispatched")
        while True:
            try:
                req = self._deferred.popleft()
            except IndexError:
                break
            self._finish_failed(req, leftover)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            self._finish_failed(req, leftover)

    def _fail_batch(self, batch: list[_Request], exc: Exception) -> None:
        """Dispatch-fault path (off the lint-guarded closure): release the
        batch's waiters with no result and keep serving."""
        log.exception("serve dispatch failed for a %d-request batch: %s",
                      len(batch), exc)
        for req in batch:
            # An admitted slot may hold a stale/garbage carry (the prefill
            # may never have run): drop the session so its next request
            # re-enters cold instead of reading a poisoned slot. Callback-
            # driven clients (the load harnesses, a network front-end) see
            # the failure as a None result, or the session silently leaks
            # out of their bookkeeping.
            self._slots.drop(req.session_id)
            self._steps.pop(req.session_id, None)
            self._finish_failed(req, exc)

    # -- dispatch supervision (serve.max_restarts > 0) --------------------

    def _supervise(self, exc: BaseException) -> None:
        """Training-loop restart contract applied to serving: after a
        fault fails its batch, rebuild the engine (fresh jitted programs +
        fresh slot arena — sessions re-enter cold through the batched
        prefill) under seeded exponential backoff. A streak of more than
        ``max_restarts`` consecutive faults (reset by any completed batch)
        trips the terminal failed state instead of retrying forever."""
        if self.cfg.max_restarts <= 0:
            return                      # PR-8 behavior: no engine rebuild
        with self._sup_lock:
            # Bump under the SAME lock as the consumer's compare-and-
            # reset: either the consumer resets first (pre-fault streak,
            # harmless) or it sees the new epoch and leaves the streak
            # alone — a pre-fault completion can never erase this fault.
            self._fault_epoch += 1
        while not self._stop_event.is_set():
            with self._sup_lock:
                self._restart_streak += 1
                streak = self._restart_streak
            if streak > self.cfg.max_restarts:
                self._enter_failed(exc)
                return
            self._registry.inc("serve_restarts_total")
            if self._obs is not None:
                # Forensics for the eventual bundle: which restart, why,
                # and what the tail looked like going in (flight-ring
                # append — gated off internally when the recorder is off).
                self._obs.record("serve_restart", streak=streak,
                                 error=repr(exc),
                                 exemplars=self.exemplars()[:4])
            self._backoff_sleep(streak)
            try:
                self._build_arena_and_programs()
                # Recompile NOW, on scratch rows, not on the first real
                # post-restart batch (seconds of XLA compile on the
                # dispatch path would blow every queued deadline and
                # shed at max rate); a compile failure folds into the
                # restart streak instead of failing an innocent batch.
                self.warmup()
                log.warning(
                    "serve engine rebuilt after fault (restart %d/%d): "
                    "fresh programs + slot arena, all sessions cold",
                    streak, self.cfg.max_restarts)
                return
            except Exception as rebuild_exc:    # noqa: BLE001 — a failed
                # rebuild is just the next fault in the streak.
                log.exception("serve engine rebuild failed")
                exc = rebuild_exc

    def _backoff_sleep(self, attempt: int) -> None:
        """Seeded exponential backoff between engine rebuilds:
        initial * 2^(attempt-1), capped, with seeded multiplicative jitter
        so a fleet of engines doesn't restart in lockstep. Deliberately
        NOT a ``time.sleep`` (which lint check 10 bans throughout serve/):
        waiting on the stop event keeps shutdown from blocking behind a
        backoff."""
        cfg = self.cfg
        delay = min(cfg.restart_backoff_s * (2.0 ** (attempt - 1)),
                    cfg.restart_backoff_max_s)
        delay *= 0.5 + self._restart_rng.random()
        self._stop_event.wait(delay)

    def _enter_failed(self, exc: BaseException) -> None:
        """Trip the terminal failed state: fail ALL queued work loudly and
        refuse future submits — a restart storm must end in a diagnosable
        corpse, never a silent wedge."""
        self._failed = exc
        self._registry.record("serve_failed", 1.0)
        log.error(
            "serve engine TERMINALLY FAILED: %d consecutive faults "
            "exceeded serve.max_restarts=%d (last: %r); failing all "
            "queued work", self._restart_streak, self.cfg.max_restarts,
            exc)
        if self._obs is not None and getattr(self._obs, "enabled", False):
            # The serve-side black box: the terminal corpse dumps the
            # flight ring (restart trail, overload exemplars, WARNING+
            # logs) plus the current slowest-request exemplars.
            self._obs.record("serve_exemplars",
                             exemplars=self.exemplars()[:8])
            self._obs.dump_flight(reason="serve_failed", error=repr(exc),
                                  restart_streak=self._restart_streak)
        self._drain_failed()

    def _drain_failed(self) -> None:
        """Fail everything queued/deferred with ServeEngineFailed (bounded
        wait on the empty queue so the loop stays responsive to stop)."""
        failure = ServeEngineFailed(
            f"serve engine is terminally failed (last fault: "
            f"{self._failed!r})")
        failure.__cause__ = self._failed
        while self._deferred:
            self._finish_failed(self._deferred.popleft(), failure)
        try:
            while True:
                self._finish_failed(self._q.get(timeout=0.05), failure)
        except queue.Empty:
            pass

    # -- batch collection -------------------------------------------------

    def _expire_if_dead(self, req: _Request, now: float) -> bool:
        """Deadline gate at collection time: a request whose deadline
        passed is completed with ServeDeadlineExceeded BEFORE it can
        occupy a padded device row. Returns True when the request was
        expired (caller must skip it)."""
        if req.t_deadline is None or now < req.t_deadline:
            return False
        self._registry.inc("serve_deadline_expired_total")
        self._finish_failed(req, ServeDeadlineExceeded(
            f"deadline expired {1e3 * (now - req.t_deadline):.1f} ms ago "
            "before the request reached a batch"))
        return True

    def _collect_batch(self) -> list[_Request]:
        """Coalesce one tick's batch: deferred same-session requests first
        (sequential consistency per session — a session's second in-flight
        request must see its first one's carry), then drain the queue until
        ``max_batch`` or the coalescing deadline — anchored at the FIRST
        request and clamped to the earliest surviving request's
        per-request deadline, so waiting for batch-mates never expires
        work the tick could have served. Expired requests are completed
        with a deadline error at pop time and never join the batch."""
        cfg = self.cfg
        # ONE knob read per tick (the _Live atomicity pattern): a
        # mid-collection set_knobs never hands this tick a mixed vector.
        knobs = self._knobs
        # Commit parked rows BEFORE adopted disk takes: both land in the
        # WarmStore, and when the warm budget overflows the store demotes
        # its stalest entry — a carry adopted this tick must be the
        # freshest so the park-inbox commit can never demote it back to
        # disk before its deferred request re-collects.
        self._drain_park_inbox()
        # Commit any completed disk takes next: their sessions' deferred
        # requests un-defer this very tick (and the drain below must see
        # an up-to-date _spill_inflight).
        self._drain_spill_inbox()
        batch: list[_Request] = []
        seen: set = set()
        kept: deque[_Request] = deque()  # trace-buffer-ok: re-queued subset
        # of _deferred, which _collect_batch bounds at the max_queue knob
        now = time.perf_counter()
        while self._deferred:
            req = self._deferred.popleft()
            if self._expire_if_dead(req, now):
                continue
            if (req.session_id in seen or len(batch) >= cfg.max_batch
                    or self._maybe_begin_spill_take(req)):
                req.trace.deferrals += 1
                kept.append(req)
            else:
                req.trace.t_collected = now
                batch.append(req)
                seen.add(req.session_id)
        self._deferred = kept
        if not batch:
            # Idle poll — EXCEPT while a disk take is in flight: the
            # consumer resolves one in µs, and sleeping the full idle
            # interval would bill that 50ms to the adopting session's
            # first response (the spill soak's recovery p99 would eat
            # it whole). _spill_inflight is dispatcher-owned state, so
            # this read races nothing.
            timeout = 0.002 if self._spill_inflight else 0.05
            try:
                req = self._q.get(timeout=timeout)
            except queue.Empty:
                return []
            if self._expire_if_dead(req, time.perf_counter()):
                return []
            if self._maybe_begin_spill_take(req):
                req.trace.deferrals += 1
                self._deferred.append(req)
                return []
            req.trace.t_collected = time.perf_counter()
            batch.append(req)
            seen.add(req.session_id)
        deadline = time.perf_counter() + knobs.batch_timeout_ms / 1e3
        for req in batch:           # anchor to the earliest survivor
            if req.t_deadline is not None:
                deadline = min(deadline, req.t_deadline)
        while len(batch) < cfg.max_batch:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if self._expire_if_dead(req, time.perf_counter()):
                continue
            if req.session_id in seen:
                if len(self._deferred) >= knobs.max_queue:
                    # The deferred side-queue is bounded too: a single-
                    # session flood must not re-grow the memory the
                    # ingress bound just capped. The loser follows the
                    # configured policy: "oldest" sheds the STALEST
                    # deferred request and admits the new one (the
                    # brownout contract), "reject" refuses the arrival.
                    with self._pending_lock:
                        self._overload_events += 1
                    if cfg.shed_policy == "oldest":
                        victim = self._deferred.popleft()
                        self._registry.inc("serve_shed_total")
                        self._finish_failed(victim, ServeRejected(
                            "shed from the same-session backlog under "
                            "overload (shed_policy='oldest')",
                            reason="shed_oldest"))
                        req.trace.deferrals += 1
                        self._deferred.append(req)
                    else:
                        self._registry.inc("serve_queue_rejected_total")
                        self._finish_failed(req, ServeRejected(
                            "same-session backlog exceeded "
                            "serve.max_queue", reason="deferred_overflow"))
                    continue
                req.trace.deferrals += 1
                self._deferred.append(req)
            else:
                if self._maybe_begin_spill_take(req):
                    req.trace.deferrals += 1
                    self._deferred.append(req)
                    continue
                req.trace.t_collected = time.perf_counter()
                batch.append(req)
                seen.add(req.session_id)
                if (req.t_deadline is not None
                        and req.t_deadline < deadline):
                    deadline = req.t_deadline
        return batch

    def _dispatch_batch(self, batch: list[_Request],
                        live: _Live) -> _DoneBatch:
        """Admit, partition cold/warm, dispatch the tick's program(s).
        Runs on the dispatch critical path: NO blocking host ops here
        (tools/lint_hot_loop.py check 8) — jit calls return asynchronously
        and readback belongs to ``_complete_batch``.  Park-inbox rows are
        committed twice per tick: by ``_collect_batch`` BEFORE the
        spill-inbox drain (so a carry adopted from disk lands freshest in
        the WarmStore and cannot be demoted by an older park), and again
        here for any readback that completed during the collection wait —
        otherwise a session evicted last tick could miss its own parked
        carry at admission and restart cold.  The order keeps both
        invariants: every pre-admission park is committed, and adopted
        takes (committed between the two park drains) stay ahead of every
        park that was pending when they landed."""
        pinned = {r.session_id for r in batch}
        # Batch-pinned carries this drain's commits pushed out of the
        # warm budget come back here — admission consumes them below in
        # place of a warm pop (see _drain_park_inbox).
        rescued = self._drain_park_inbox(pinned=pinned)
        cold_reqs: list[_Request] = []
        cold_idx: list[int] = []
        warm_reqs: list[_Request] = []
        warm_idx: list[int] = []
        evicted = 0
        park_sids: list[Any] = []       # this tick's eviction victims …
        park_slots: list[int] = []      # … and the arena rows they held
        park_steps: list[int] = []      # … and their step stamps
        unpark_slots: list[int] = []    # slots taking a parked carry back
        unpark_rows: list[Any] = []     # the parked host carries
        warm_on = self._warm_enabled
        for req in batch:
            sid = req.session_id
            slot = self._slots.lookup(sid)
            if slot is not None:
                if warm_on:
                    # Dispatched-step clock of a hot session: +1 per
                    # dispatch, so a later park stamps the record with
                    # exactly the completed-response count the router
                    # tracks for the session (the adoption rendezvous).
                    self._steps[sid] = self._steps.get(sid, 0) + 1
                warm_reqs.append(req)
                warm_idx.append(slot)
                continue
            parked = rescued.pop(sid, None) if warm_on else None
            if parked is None and warm_on:
                parked = self._warm.pop(sid)
            if (parked is not None and req.clock is not None
                    and parked[1] != req.clock):
                # RAM-parked carry from an earlier stint of this session
                # on THIS engine, superseded while the session lived
                # elsewhere (the router's clock outran the stamp):
                # serving it warm would change bytes — drop it and
                # restart cold, the same stale demotion disk records get.
                self._warm.stale_drops += 1
                self._registry.inc("serve_warm_stale_drops_total")
                parked = None
            slot, victim = self._slots.admit(sid, pinned)
            if victim is not None:
                evicted += 1
                if warm_on:
                    # The victim's carry still sits in the arena row the
                    # admission just reassigned: remember it for the
                    # batched park gather below (which runs BEFORE any
                    # program or install writes the row).
                    park_sids.append(victim)
                    park_slots.append(slot)
                    park_steps.append(self._steps.pop(victim, 0))
            if parked is not None:
                # Warm HIT: the parked carry reinstalls into the new
                # slot and the session continues through the warm path,
                # bitwise as if never evicted. (A spill-adopted carry
                # landed in the warm store first, so it arrives here —
                # the econ gauge prices spill hits for free.)
                rows, psteps = parked
                self._registry.inc("serve_warm_hits_total")
                self._steps[sid] = psteps + 1
                unpark_slots.append(slot)
                unpark_rows.append(rows)
                warm_reqs.append(req)
                warm_idx.append(slot)
            else:
                if warm_on:
                    self._registry.inc("serve_warm_misses_total")
                    # Cold (re)start: re-anchor the step clock to the
                    # router's view when one was forwarded — the carry
                    # built from here on corresponds to clock+1 completed
                    # responses, so later spills stamp adoptably even
                    # after a mid-life cold restart.
                    self._steps[sid] = (req.clock + 1
                                        if req.clock is not None else 1)
                    if req.clock:
                        # A session the fleet believes has history is
                        # restarting through prefill: a COLD adoption
                        # (counted against warm ones per migration).
                        self._registry.inc("serve_adopt_cold_total")
                    if self._spill_enabled:
                        # Unconditional tombstone: a cold (re)start
                        # invalidates any record the arena still holds
                        # for this session (e.g. one sealed by a racing
                        # put after our probe missed) — stale episode
                        # state must never outlive the restart.
                        self._spill_ops.append(("del", sid))
                        self._kick_consumer()
                cold_reqs.append(req)
                cold_idx.append(slot)
        for sid, (rows, psteps) in rescued.items():
            # Defensive: a rescued carry whose session somehow took the
            # hot path (slots and warm store are disjoint, so this
            # should be unreachable) re-parks instead of silently dying.
            self._commit_warm(sid, rows, psteps)
        parked_rows = None
        if park_sids:
            # Page-out step 1 (dispatch side): ONE batched gather of the
            # victims' rows at the static max_batch shape — async device
            # compute; the consumer does the host readback (check 17).
            pidx = np.full((self.cfg.max_batch,), self.cfg.slots,
                           np.int32)
            pidx[:len(park_slots)] = park_slots
            parked_rows = self._park_fn(self._pool, pidx)
        if unpark_rows:
            self._pool = self._install_parked(unpark_rows, unpark_slots)
        # self._pool is reassigned IMMEDIATELY after each program call:
        # the calls donate the arena, so holding the old reference across
        # a later failure (the warm group's _pad raising after the cold
        # program already consumed the buffer) would leave the field
        # pointing at a deleted array and wedge every future tick.
        self._batch_serial += 1         # dispatcher-thread-owned serial
        bid = self._batch_serial

        def _stamp(reqs: list[_Request], cold: bool) -> None:
            # Dispatch edge: the jit call below returns asynchronously, so
            # this stamp marks "handed to the device", and the device
            # stage absorbs compute + queueing behind earlier programs.
            t = time.perf_counter()
            for req in reqs:
                req.trace.t_dispatched = t
                req.trace.batch = bid
                req.trace.cold = cold

        groups: list[tuple[list[_Request], Any, Any, Any]] = []
        if self._episode:
            if cold_reqs:
                obs, idx = self._pad(cold_reqs, cold_idx)
                _stamp(cold_reqs, True)
                act, logit, val, self._pool = self._cold_fn(
                    live.params, self._pool, obs, idx)
                groups.append((cold_reqs, act, logit, val))
            if warm_reqs:
                obs, idx = self._pad(warm_reqs, warm_idx)
                _stamp(warm_reqs, False)
                act, logit, val, self._pool = self._warm_fn(
                    live.params, self._pool, obs, idx)
                groups.append((warm_reqs, act, logit, val))
        else:
            reqs = cold_reqs + warm_reqs
            cold_mask = np.zeros((self.cfg.max_batch,), bool)
            cold_mask[:len(cold_reqs)] = True
            obs, idx = self._pad(reqs, cold_idx + warm_idx)
            _stamp(reqs, False)
            for req in cold_reqs:
                req.trace.cold = True
            act, logit, val, self._pool = self._step_fn(
                live.params, self._pool, obs, idx, cold_mask)
            groups.append((reqs, act, logit, val))
        return _DoneBatch(groups=groups, step=live.step, n=len(batch),
                          cold=len(cold_reqs), evicted=evicted,
                          epoch=self._fault_epoch,
                          parked_sids=tuple(park_sids),
                          parked_rows=parked_rows,
                          parked_steps=tuple(park_steps))

    def _pad(self, reqs: list[_Request],
             idx: list[int]) -> tuple[np.ndarray, np.ndarray]:
        """Pad a group to the static ``max_batch`` shape: padding rows
        repeat the first real observation (finite by construction) and
        index SCRATCH arena rows, never a live slot."""
        cfg = self.cfg
        obs = np.empty((cfg.max_batch, reqs[0].obs.shape[-1]), np.float32)
        out_idx = np.empty((cfg.max_batch,), np.int32)
        for i, req in enumerate(reqs):
            obs[i] = req.obs
            out_idx[i] = idx[i]
        for i in range(len(reqs), cfg.max_batch):
            obs[i] = reqs[0].obs
            out_idx[i] = cfg.slots + i
        return obs, out_idx

    # -- session paging (dispatch side) -----------------------------------

    def _drain_park_inbox(self, pinned: set | None = None
                          ) -> dict[Any, tuple[Any, int]]:
        """Commit consumer-read-back page-outs into the warm store.
        Dispatcher-only, so ALL admission state (slot pool + warm store)
        has one owner and no insert can race an unpark. An entry whose
        session re-entered the slot pool before its page-out committed
        is STALE — that session already restarted cold and its old
        episode state must never resurrect — and is dropped.

        ``pinned`` is the pre-admission call's batch membership: a
        commit here may overflow the warm budget and demote a carry
        whose session is about to be admitted THIS tick (with a 1-carry
        budget, any park between a spill-take commit and its deferred
        request's admission would bounce the adopted carry straight
        back out). Such victims are RESCUED — returned as
        ``{sid: (rows, steps)}`` for admission to consume directly —
        instead of spilled/dropped; everyone else demotes normally."""
        rescued: dict[Any, tuple[Any, int]] = {}
        while self._park_inbox:
            sid, rows, steps = self._park_inbox.popleft()
            if self._slots.contains(sid):
                self._warm.stale_drops += 1
                self._registry.inc("serve_warm_stale_drops_total")
                continue
            self._commit_warm(sid, rows, steps, pinned=pinned,
                              rescued=rescued)
        return rescued

    def _commit_warm(self, sid: Any, rows: Any, steps: int, *,
                     pinned: set | None = None,
                     rescued: dict | None = None) -> None:
        """Park one host carry in the warm store; overflow demotes to
        the spill arena (tier on) or to cold (off — the ISSUE-18
        contract, unchanged), except batch-pinned victims, which land
        in ``rescued`` for this tick's admission. Dispatcher-only."""
        demoted = self._warm.put(sid, rows, self._carry_nbytes, steps)
        if demoted and pinned:
            kept = []
            for victim, vrows, _vnbytes, vsteps in demoted:
                if victim in pinned and rescued is not None:
                    rescued[victim] = (vrows, vsteps)
                    # Not a real demotion — admission consumes it in a
                    # moment, exactly as a warm pop would have.
                    self._warm.demotions -= 1
                else:
                    kept.append((victim, vrows, _vnbytes, vsteps))
            demoted = kept
        if demoted:
            self._registry.inc("serve_warm_demotions_total",
                               len(demoted))
            self._spill_demoted(demoted)

    def _spill_demoted(self, demoted: list) -> None:
        """Route warm-store overflow toward the disk arena: enqueue one
        put op per demoted entry for the CONSUMER to seal (dispatch
        never touches the files). With the spill tier off the entries
        simply fall to cold."""
        if not self._spill_enabled:
            return
        for sid, rows, _nbytes, steps in demoted:
            self._spill_ops.append(("put", sid, rows, steps))
        self._kick_consumer()

    def _kick_consumer(self) -> None:
        """Nudge an idle consumer to run the queued spill ops now
        (best-effort: a full done queue means it is already awake and
        drains the op FIFO after its current batch)."""
        try:
            self._done_q.put_nowait(_SPILL_TICK)
        except queue.Full:
            pass

    def _drain_spill_inbox(self) -> None:
        """Commit completed disk takes into the warm store and release
        their sessions from the deferral set. Dispatcher-only (the
        admission-state single-owner rule); the consumer only appends.
        A hit whose session somehow re-entered the pool meanwhile is
        dropped like a stale page-out — never overwrite a live episode."""
        while self._spill_inbox:
            sid, rows, steps, _reason = self._spill_inbox.popleft()
            self._spill_inflight.discard(sid)
            if rows is None:
                continue        # miss/stale/corrupt: the session lands cold
            if self._slots.contains(sid):
                self._warm.stale_drops += 1
                self._registry.inc("serve_warm_stale_drops_total")
                continue
            self._commit_warm(sid, rows, steps)

    def _maybe_begin_spill_take(self, req: _Request) -> bool:
        """Collection-time spill gate: True when the request must DEFER
        (the caller re-queues it) behind a disk take — either one
        already in flight for its session, or the one this call just
        enqueued. The only dispatch-side arena touch is probe()'s
        ``os.stat`` (µs — the read itself rides the consumer, lint
        checks 8/19); sessions with no sealed record admit cold on this
        very tick and pay nothing."""
        if not self._spill_enabled:
            return False
        sid = req.session_id
        if sid in self._spill_inflight:
            return True
        if self._slots.contains(sid) or self._warm.contains(sid):
            return False        # hot or RAM-warm: no disk involved
        if not self._arena.probe(sid):
            return False
        self._spill_ops.append(("take", sid, req.clock))
        self._spill_inflight.add(sid)
        self._kick_consumer()
        return True

    def _install_parked(self, rows: list[Any], slots: list[int]) -> Any:
        """Unpark: stack the tick's parked host carries, pad to the
        static ``max_batch`` shape (padding rows repeat row 0 and write
        SCRATCH arena rows, mirroring :meth:`_pad`), and scatter-install
        into the (re-)admitted slots. ``device_put`` of host rows is an
        async H2D enqueue — legal on the dispatch thread; no readback
        happens here."""
        cfg = self.cfg
        n = len(rows)
        idx = np.empty((cfg.max_batch,), np.int32)
        idx[:n] = slots
        for i in range(n, cfg.max_batch):
            idx[i] = cfg.slots + i
        pad = cfg.max_batch - n
        stacked = jax.tree.map(
            lambda *leaves: np.stack(leaves + (leaves[0],) * pad),
            *rows)
        return self._install_fn(self._pool, stacked, idx)

    # -- consumer thread --------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            try:
                item = self._done_q.get(timeout=0.2)
            except queue.Empty:
                # Normally the _SHUTDOWN sentinel ends this loop; the
                # timed poll covers the sentinel stop() had to DROP on a
                # full queue (consumer stalled past the put timeout) — a
                # later-recovering consumer drains what remains and then
                # exits here instead of parking forever on a sentinel
                # that will never arrive. Exit ONLY once the dispatcher
                # is gone too, and even then drain once more first: the
                # dispatcher may have put its final batch between our
                # empty get and its exit, and those waiters must still
                # reach a terminal outcome.
                if (self._stop_event.is_set()
                        and not self._dispatcher.is_alive()):
                    while True:
                        try:
                            item = self._done_q.get_nowait()
                        except queue.Empty:
                            # Exit debt: queued spill PUTS still seal
                            # (demoted carries must not die with the
                            # process); takes skip — their requesters
                            # were failed, and a consumed record would
                            # be lost to the adopting engine.
                            self._drain_spill_ops()
                            return
                        if (item is not _SHUTDOWN
                                and item is not _SPILL_TICK):
                            self._consume_done(item)
                continue
            if item is _SHUTDOWN:
                self._drain_spill_ops()
                return
            if item is _SPILL_TICK:
                self._drain_spill_ops()
                continue
            self._consume_done(item)
            # Safety net behind the best-effort _kick_consumer: ops
            # enqueued while the done queue was full drain here.
            self._drain_spill_ops()

    def _consume_done(self, item: _DoneBatch) -> None:
        try:
            self._complete_batch(item)
        except Exception as exc:  # noqa: BLE001 — a completion fault
            # (readback error, device fault) must neither wedge the
            # dispatcher behind a full done queue NOR leak the batch's
            # waiters: release every request not already completed,
            # mirroring the dispatcher's _fail_batch contract.
            log.exception("serve consumer failed completing a batch")
            for reqs, *_ in item.groups:
                for req in reqs:
                    # The dispatched program already ADVANCED these
                    # sessions' slot carries; hand them to the
                    # dispatcher to drop (it owns the SlotPool) so a
                    # client retry doesn't double-step a warm carry.
                    self._poisoned.append(req.session_id)
                    if req._event.is_set():
                        continue
                    req.error = exc
                    req._event.set()
                    with self._pending_lock:
                        # Pending was already decremented by the batch-
                        # level finally; only the SLO outcome accounting
                        # is per-request here.
                        self._term_total += 1
                        self._term_bad += 1
                    if req.callback is not None:
                        try:
                            req.callback(None)
                        except Exception:   # noqa: BLE001
                            log.exception("serve failure callback failed")
                    self._trace_request(req, "failed",
                                        time.perf_counter())
            # A consumer fault is an ENGINE fault for the supervisor:
            # the readback path may hold poisoned device buffers, so ask
            # the dispatcher to run the restart/backoff contract (no-op
            # at the default max_restarts=0), stamped with the faulting
            # batch's epoch so a pre-restart batch draining out of the
            # done queue can't re-trip a restart the rebuild already
            # delivered.
            self._consumer_fault = exc
            self._consumer_fault_epoch = item.epoch
            self._restart_requested.set()

    #: Arena take verdicts -> registry counters (the fleet router folds
    #: these per engine into fleet_spill_* — ISSUE 20 observability).
    _SPILL_REASON_COUNTERS = {
        "hit": "serve_spill_hits_total",
        "miss": "serve_spill_misses_total",
        "stale": "serve_spill_stale_total",
        "corrupt": "serve_spill_corrupt_total",
    }

    def _drain_spill_ops(self) -> None:
        """Execute queued arena ops — the ONLY place spill disk I/O
        happens while the engine runs (consumer thread; dispatch only
        enqueues, lint checks 8/17/19). Once the stop event is set,
        takes are SKIPPED instead of executed: their requesters are
        being failed, and consuming the record here would steal the
        carry from whichever engine adopts the session next."""
        arena = self._arena
        if arena is None:
            return
        reg = self._registry
        skip_takes = self._stop_event.is_set()
        while self._spill_ops:
            op = self._spill_ops.popleft()
            kind = op[0]
            if kind == "put":
                _, sid, rows, steps = op
                ok = arena.put(sid, jax.tree.leaves(rows), steps)
                reg.inc("serve_spill_puts_total" if ok
                        else "serve_spill_put_refusals_total")
            elif kind == "del":
                arena.delete(op[1])
            elif skip_takes:
                self._spill_inbox.append((op[1], None, 0, "skipped"))
            else:
                _, sid, clock = op
                payload, steps, reason, foreign = arena.take(sid, clock)
                reg.inc(self._SPILL_REASON_COUNTERS[reason])
                if reason == "hit" and clock is not None and foreign:
                    # A clocked hit on ANOTHER incarnation's record is
                    # a cross-engine warm ADOPTION (this engine's own
                    # re-reads — spill thrash — deliberately don't
                    # count; the soak reconciles this exactly).
                    reg.inc("serve_adopt_warm_total")
                rows = (self._rows_from_payload(payload)
                        if payload is not None else None)
                self._spill_inbox.append((sid, rows, steps, reason))

    def _rows_from_payload(self, payload: bytes) -> Any:
        """Rebuild a carry tree from a spill record's raw payload: split
        against this engine's carry template in ``jax.tree`` order (the
        order the writer concatenated; the arena already validated the
        total byte length, so a foreign-model record never reaches
        here)."""
        leaves, treedef = jax.tree.flatten(self._carry0)
        out, off = [], 0
        for leaf in leaves:
            n = int(leaf.size)
            arr = np.frombuffer(payload, dtype=leaf.dtype, count=n,
                                offset=off)
            out.append(arr.reshape(leaf.shape).copy())
            off += n * leaf.dtype.itemsize
        return jax.tree.unflatten(treedef, out)

    def _complete_batch(self, done: _DoneBatch) -> None:
        """Readback + request completion + SLO accounting — the consumer
        side of the split; blocking host work is EXPECTED here. The
        pending count decrements in a finally so a mid-completion fault
        (handled by :meth:`_complete_loop`) can never strand
        :meth:`drain`."""
        n_done = slow = 0
        slo_target = self._slo[1]
        hists = self._hists
        if done.parked_sids:
            # Page-out step 2: the host readback of the victims' carry
            # rows rides HERE, on the consumer — the dispatch loop never
            # blocks on a device_get (lint check 17). The copies detach
            # each session's rows from the stacked transfer buffer so a
            # later partial demotion frees real memory.
            # serve-host-ok: consumer-side page-out readback.
            host_rows = jax.device_get(done.parked_rows)
            for i, sid in enumerate(done.parked_sids):
                row = jax.tree.map(lambda x: np.asarray(x[i]).copy(),
                                   host_rows)
                self._park_inbox.append((sid, row, done.parked_steps[i]))
            self._registry.inc("serve_warm_parks_total",
                               len(done.parked_sids))
        # Batch-level trace buffer: one bulk tracer append per completed
        # batch instead of one lock round-trip per request.
        trace_lines: list[str] | None = (
            [] if self._req_tracer is not None else None)
        try:
            for reqs, act_dev, logit_dev, val_dev in done.groups:
                # serve-host-ok: consumer-side readback — the dispatcher
                # never blocks on these buffers.
                actions, logits, values = jax.device_get(
                    (act_dev, logit_dev, val_dev))
                now = time.perf_counter()
                # The consumer serializes a batch's completions, so the
                # readback HISTOGRAM charges each request only its own
                # completion slice (t_prev→t_done): billing t_done minus
                # the group readback stamp would blame every request for
                # its earlier batch-mates' callbacks and regress the
                # serve_readback_p99_ms gate row as occupancy rises. The
                # trace's readback child span keeps the client-observable
                # t_device→t_done wait.
                t_prev = now
                for i, req in enumerate(reqs):
                    tr = req.trace
                    tr.t_device = now
                    # Telescoping stage decomposition: the three stages
                    # share their interior stamps, so their sum IS the
                    # end-to-end latency (the soak-asserted invariant).
                    # The None-guards are defensive only — every request
                    # that reaches here was collected and dispatched — a
                    # missing stamp must degrade one request's breakdown,
                    # never fail the whole batch on this thread.
                    t_coll = tr.t_collected or tr.t_enq
                    t_disp = tr.t_dispatched or t_coll
                    latency_ms = (now - req.t_enq) * 1e3
                    stages = {
                        "queue_wait_ms": (t_coll - tr.t_enq) * 1e3,
                        "batch_wait_ms": (t_disp - t_coll) * 1e3,
                        "device_ms": (now - t_disp) * 1e3,
                    }
                    if tr.cold:
                        # EWMA of what a cold re-entry COSTS (device
                        # time incl. queueing behind the tick's other
                        # programs — the amortized, honest figure): the
                        # recompute side of the eviction-economics
                        # gauge.
                        prev_ewma = self._ewma_prefill_ms
                        self._ewma_prefill_ms = (
                            stages["device_ms"] if prev_ewma == 0.0
                            else 0.9 * prev_ewma
                            + 0.1 * stages["device_ms"])
                    result = ServeResult(
                        session_id=req.session_id,
                        action=int(actions[i]),
                        logits=logits[i],
                        value=float(values[i]),
                        params_step=done.step,
                        latency_ms=latency_ms,
                        stages=stages)
                    req.result = result
                    req._event.set()
                    if req.callback is not None:
                        try:
                            req.callback(result)
                        except Exception:   # noqa: BLE001
                            log.exception("serve result callback failed")
                    tr.t_done = time.perf_counter()
                    hists["serve_queue_wait_ms"].observe(
                        stages["queue_wait_ms"])
                    hists["serve_batch_wait_ms"].observe(
                        stages["batch_wait_ms"])
                    hists["serve_device_ms"].observe(stages["device_ms"])
                    hists["serve_readback_ms"].observe(
                        (tr.t_done - t_prev) * 1e3)
                    t_prev = tr.t_done
                    self._h_e2e.observe(latency_ms)
                    if abs(sum(stages.values()) - latency_ms) > 1e-6:
                        # Structural self-check: the decomposition is
                        # exact by construction, so any drift means a
                        # refactor broke a stamp — the soaks assert this
                        # counter stays 0.
                        self._registry.inc(
                            "serve_trace_decomposition_error_total")
                    if slo_target and latency_ms > slo_target:
                        slow += 1
                    n_done += 1
                    if self._exemplar_k:
                        self._note_exemplar(req, latency_ms, stages,
                                            done.step)
                    self._trace_request(req, "completed", tr.t_done,
                                        lines=trace_lines)
        finally:
            if trace_lines:
                self._req_tracer.emit_lines(trace_lines)
            with self._pending_lock:
                self._pending -= done.n
                self._term_total += n_done
                self._term_completed += n_done
                self._term_slow += slow
        # A completed batch heals the supervisor's consecutive-fault
        # streak (mirrors the training loop's restart accounting) — but
        # ONLY a batch dispatched after the latest fault: pre-fault
        # batches draining out of the done queue during a backoff say
        # nothing about the rebuilt engine.
        with self._sup_lock:
            if done.epoch == self._fault_epoch:
                self._restart_streak = 0
        with self._pending_lock:
            # Locked: failure-path publishes snapshot-and-reset these
            # from other threads (the qps/occupancy window).
            self._stats_completed += done.n
            self._stats_occupancy.append(done.n / self.cfg.max_batch)
        reg = self._registry
        reg.inc("serve_responses_total", done.n)
        reg.inc("serve_batches_total")
        if done.cold:
            reg.inc("serve_prefills_total", done.cold)
        if done.evicted:
            reg.inc("serve_evictions_total", done.evicted)
        self._publish_stats()

    def _note_exemplar(self, req: _Request, latency_ms: float,
                       stages: dict, step: int) -> None:
        """Track the window's K slowest completed requests with their full
        stage breakdown (consumer thread; K is small, so the min-replace
        scan is a handful of comparisons)."""
        tr = req.trace
        with self._ex_lock:
            w = self._window_slowest
            if len(w) >= self._exemplar_k:
                m = min(range(len(w)), key=lambda j: w[j]["latency_ms"])
                if latency_ms <= w[m]["latency_ms"]:
                    return
                del w[m]
            w.append({
                "session": str(req.session_id),
                "latency_ms": round(latency_ms, 3),
                "stages": {k: round(v, 3) for k, v in stages.items()},
                "batch": tr.batch,
                "cold": tr.cold,
                "deferrals": tr.deferrals,
                "params_step": step,
            })

    def exemplars(self) -> list[dict]:
        """The slowest-request exemplar ring (recent windows' top-K plus
        the in-progress window), slowest first — the ``cli serve`` summary
        and flight-recorder payload. Safe from any thread."""
        with self._ex_lock:
            merged = list(self._exemplars) + list(self._window_slowest)
        return sorted(merged, key=lambda e: -e["latency_ms"])

    def refresh_spill_gauges(self) -> None:
        """Health-probe hook (the fleet scrape path calls this): re-
        anchor and republish the spill-arena census gauges even while
        no batch is completing. The stats cadence rides batch
        completions, so an idle engine's last in-traffic publish would
        otherwise freeze ``serve_spill_bytes/sessions`` exactly when a
        drain or kill decision wants them (the population quiesces,
        THEN someone reads the fleet sums). One bounded scandir at the
        stats cadence, callable from any scrape thread — the same
        budget class as the dispatcher's admission-time ``probe``."""
        arena = self._arena
        if arena is None:
            return
        now = time.perf_counter()
        if now - self._spill_scan_t < self.cfg.stats_interval_s:
            return
        self._spill_scan_t = now
        arena.scan_usage()
        self._registry.record_many({
            "serve_spill_bytes": float(arena.bytes),
            "serve_spill_sessions": float(arena.sessions)})

    def _publish_stats(self, *, force: bool = False,
                       io_ok: bool = True) -> None:
        """SLO gauges at ``stats_interval_s`` cadence. Callers: the
        consumer thread (every completed batch), terminal-failure paths
        (any thread — see ``_stats_lock``; they pass ``io_ok=False`` so
        the never-blocks submit/dispatcher contract survives the exemplar
        file write), and ``stop`` (force). A non-force caller that loses
        the lock race simply skips: someone else is publishing this
        window."""
        now = time.perf_counter()
        if not force and now - self._stats_t < self.cfg.stats_interval_s:
            return
        if not self._stats_lock.acquire(blocking=force):
            return
        try:
            if force:
                # Re-anchor past any publish that won the lock while we
                # blocked: a stale `now` would read as interval <= 0 and
                # silently skip the FINAL gauges (and any deferred
                # exemplar-file write) stop() exists to flush.
                now = time.perf_counter()
            self._publish_stats_locked(now, force, io_ok)
        finally:
            self._stats_lock.release()

    def _publish_stats_locked(self, now: float, force: bool,
                              io_ok: bool) -> None:
        interval = now - self._stats_t
        if not force and interval < self.cfg.stats_interval_s:
            return
        if interval <= 0:
            return
        with self._pending_lock:
            overload_events = self._overload_events
            self._overload_events = 0
            term = (self._term_total, self._term_bad,
                    self._term_completed, self._term_slow)
            completed = self._stats_completed
            occupancy = self._stats_occupancy
            self._stats_completed = 0
            self._stats_occupancy = []
        depth = self._q.qsize()
        overloaded = (overload_events > 0
                      or depth >= self._knobs.max_queue)
        row: dict[str, float] = {
            "serve_qps": completed / interval,
            "serve_queue_depth": float(depth),
            # Overload gauge: 1 while the engine is shedding/rejecting or
            # the ingress queue is pinned at its bound, else 0.
            "serve_overload": float(overloaded),
        }
        # p50/p99 from the end-to-end histogram's per-window bucket DELTA
        # (cumulative counts subtract exactly — the same bucket math a
        # fleet router uses to merge engines): every completed request in
        # the window counts, where the old bounded sample ring silently
        # forgot overflow under load.
        snap = self._h_e2e.snapshot()
        delta = [a - b for a, b in zip(snap["counts"],
                                       self._p50_prev_counts)]
        self._p50_prev_counts = snap["counts"]
        if sum(delta) > 0:
            row["serve_p50_ms"] = self._h_e2e.quantile(0.50, counts=delta)
            row["serve_p99_ms"] = self._h_e2e.quantile(0.99, counts=delta)
        if occupancy:
            row["serve_batch_occupancy"] = (
                sum(occupancy) / len(occupancy))
        # Session-tier populations + warm accounting. Reading the
        # dispatcher-owned structures from here is a couple of int/len
        # loads (GIL-atomic references; approximate by a tick at worst —
        # gauges, not invariants).
        row["serve_sessions_hot"] = float(len(self._slots))
        if self._warm_enabled:
            warm = self._warm
            row["serve_warm_sessions"] = float(len(warm))
            row["serve_warm_bytes"] = float(warm.bytes)
            row["serve_warm_budget_bytes"] = float(warm.max_bytes)
            # Eviction economics, live: prefill-recompute ms AVOIDED by
            # this window's warm hits, per MB of carry bytes held — the
            # "is the RAM paying for itself" gauge (≫0: keep paging;
            # ~0: the budget is dead weight).
            hits = self._registry.counters().get(
                "serve_warm_hits_total", 0.0)
            d_hits = max(0.0, hits - self._prev_warm_hits)
            self._prev_warm_hits = hits
            held_mb = warm.bytes / 2**20
            # serve_warm_hits_total counts SPILL hits too (an adopted
            # carry re-enters through the warm store), so the econ
            # gauge prices the whole warm+spill tier per RAM MB held.
            row["serve_warm_econ_ms_per_mb"] = (
                d_hits * self._ewma_prefill_ms / held_mb
                if held_mb > 0 else 0.0)
        if self._arena is not None:
            arena = self._arena
            if io_ok:
                # Re-anchor the approximate usage counters with one
                # bounded scandir — consumer/stop threads only (io_ok
                # keeps the failure-path publishes, which run on submit/
                # dispatcher threads, off the filesystem).
                arena.scan_usage()
                self._spill_scan_t = now
            row["serve_spill_bytes"] = float(arena.bytes)
            row["serve_spill_sessions"] = float(arena.sessions)
            row["serve_spill_budget_bytes"] = float(arena.max_bytes)
        row.update(self._slo_burn(now, term))
        self._registry.record_many(row)
        self._fold_exemplars(overloaded, io_ok)
        self._stats_t = now

    def _slo_burn(self, now: float, term: tuple) -> dict[str, float]:
        """Rolling error-budget burn rates over ``obs.slo_window_s``: the
        window is the difference of cumulative terminal-outcome counts
        between now and the oldest in-window publish snapshot. Burn 1.0 =
        spending exactly the SLO's error budget; crossing
        ``obs.slo_burn_threshold`` records a flight event (with the
        current exemplars) and a trace instant, re-arming only after the
        burn halves (hysteresis)."""
        if not self._slo_on:
            return {}
        avail, target_p99, window_s, threshold = self._slo
        win = self._slo_win
        win.append((now, *term))
        # Prune to the NEWEST snapshot at-or-before the window edge: that
        # snapshot is the delta base, so popping it whenever it merely
        # predates the edge would (a) silently exclude every event between
        # the edge and the next snapshot and (b) collapse the delta to
        # zero outright whenever the publish interval reaches window_s
        # (base == the just-appended snapshot). When publishes are sparser
        # than the window, the window degrades to one publish interval —
        # the honest reading, never a frozen gauge.
        while len(win) > 1 and win[1][0] <= now - window_s:
            win.popleft()
        base = win[0]
        d_total = term[0] - base[1]
        d_bad = term[1] - base[2]
        d_completed = term[2] - base[3]
        d_slow = term[3] - base[4]
        out: dict[str, float] = {}
        burns: dict[str, float] = {}
        if avail > 0 and d_total > 0:
            burns["availability"] = (d_bad / d_total) / (1.0 - avail)
            out["serve_slo_availability_burn"] = burns["availability"]
        if target_p99 > 0 and d_completed > 0:
            burns["latency"] = (d_slow / d_completed) / 0.01
            out["serve_slo_latency_burn"] = burns["latency"]
        worst = max(burns.values(), default=0.0)
        if worst >= threshold and not self._burn_alarm:
            self._burn_alarm = True
            self._registry.inc("serve_slo_burn_alerts_total")
            log.warning(
                "SLO burn rate %.2f crossed threshold %.2f "
                "(window %ds: %d/%d bad, %d/%d slow)", worst, threshold,
                int(window_s), d_bad, d_total, d_slow, d_completed)
            if self._obs is not None:
                self._obs.record(
                    "slo_burn", burns=burns, threshold=threshold,
                    window_s=window_s, bad=d_bad, total=d_total,
                    slow=d_slow, completed=d_completed,
                    exemplars=self.exemplars()[:4])
                self._obs.tracer.instant("serve_slo_burn", **burns)
        elif self._burn_alarm and worst < 0.5 * threshold:
            self._burn_alarm = False
        return out

    def _fold_exemplars(self, overloaded: bool, io_ok: bool) -> None:
        """End of a stats window: fold the window's top-K slowest into the
        bounded exemplar ring; on overload ONSET record them into the
        flight ring (the forensic payload for "why was the tail slow when
        shedding started"); write the ring to ``serve_exemplars.json`` in
        the obs run dir when obs is on. ``io_ok=False`` (failure-path
        publishes on submit/dispatcher threads) defers the file write —
        the fold still happens and ``_ex_dirty`` carries the debt to the
        next consumer/stop publish."""
        with self._ex_lock:
            if self._window_slowest:
                self._exemplars.extend(
                    sorted(self._window_slowest,
                           key=lambda e: -e["latency_ms"]))
                self._window_slowest = []
                self._ex_dirty = True
        obs = self._obs
        if obs is None or not getattr(obs, "enabled", False):
            self._overload_flagged = overloaded
            return
        if overloaded and not self._overload_flagged:
            obs.record("serve_overload_exemplars",
                       exemplars=self.exemplars()[:8])
        self._overload_flagged = overloaded
        run_dir = getattr(obs, "run_dir", None)
        # Rewrite the file only when the ring actually changed: a publish
        # with no new window exemplars (idle engine, outage-driven stats
        # ticks) must not pay write+rename on a request-path thread.
        if run_dir and io_ok and self._ex_dirty:
            try:
                path = os.path.join(run_dir, "serve_exemplars.json")
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"exemplars": self.exemplars()}, f)
                os.replace(tmp, path)
                self._ex_dirty = False
            except OSError:
                log.exception("serve exemplar export failed")
