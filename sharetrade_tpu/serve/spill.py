"""Crash-consistent disk SPILL tier for parked session carries.

ISSUE 20: the WarmStore (serve/engine.py) is the RAM half of the warm
tier; this module is its overflow — a directory of per-session carry
RECORDS on local disk, written with the journal's torn-tail discipline
so a spilled carry survives its writer's SIGKILL and can be ADOPTED by
a different engine after a drain, a scale-down, or a crash:

- **one record per session**, named by a content-free digest of the
  session id (``<sha256(sid)[:40]>.spill``) in a directory SHARED by
  every engine of a fleet (fleet/pool.py hands each worker the same
  ``serve.spill_dir``) — the filesystem IS the index, so adoption needs
  no coordination channel and this process keeps no per-record map
  (lint check 19: no unbounded in-memory index of arena records);
- **atomic seal**: a record is built in a ``.tmp-<pid>`` sibling,
  fsync'd, then ``os.replace``d into place (the checkpoint/journal
  discipline, lint check 5) — a reader can NEVER observe a torn record,
  only a missing one; a SIGKILLed writer leaves unsealed debris the
  supervisor sweeps (:func:`sweep_debris`);
- **per-record CRC + step stamp**: the fixed header carries the
  session's dispatched-step count (the adoption clock) and a CRC32 over
  meta + payload; a corrupt, truncated, or foreign-model record fails
  verification and is deleted — the caller demotes that session to the
  cold-restart-through-prefill path, so injected corruption can change
  LATENCY, never bytes (the bitwise fresh-session contract is never
  weakened, only hit less);
- **consume-on-take**: a successful ``take`` deletes the record, so a
  carry is adopted at most once and a later re-entry can never read a
  stamp the episode already advanced past.

Readback maps the sealed record (``mmap``) and copies the leaves out —
the payload is the concatenated raw bytes of the carry's tree leaves in
``jax.tree.leaves`` order, validated against the adopting engine's own
carry template by total byte length (a different model/precision simply
fails the length check and lands cold).

THREADING: the engine confines every arena call to its CONSUMER thread
(spill writes ride the consumer like page-out readback does — dispatch
never blocks on disk), except :meth:`probe` (one ``os.stat``, the
admission-time existence check) and the post-stop drain page-out.

spill-io-ok: this module IS the arena's I/O layer — the one place lint
check 19 allows spill-record file access inside sharetrade_tpu/.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import struct
import zlib
from typing import Any

import numpy as np

from sharetrade_tpu.utils.logging import get_logger

log = get_logger("serve.spill")

#: Sealed-record filename suffix (the confinement token lint check 19
#: scans for outside this module).
SPILL_SUFFIX = ".spill"

#: Record header: magic, version, flags, step stamp, meta length,
#: payload length, CRC32(meta + payload). Little-endian, fixed size —
#: a record shorter than this is torn by definition.
_HEADER = struct.Struct("<4sHHQIII")
_MAGIC = b"STSP"
_VERSION = 1


def record_name(session_id: Any) -> str:
    """Deterministic arena filename for a session id (any engine of the
    fleet computes the same name — the adoption rendezvous)."""
    digest = hashlib.sha256(str(session_id).encode()).hexdigest()
    return digest[:40] + SPILL_SUFFIX


def sweep_debris(root: str, pid: int | None = None) -> int:
    """Remove unsealed ``.tmp-<pid>`` debris left by crashed writers.
    ``pid=None`` sweeps every tmp file (fleet start — no writer is
    live yet); a specific pid sweeps one dead incarnation's leftovers
    (fleet/pool.py calls this when it reaps a crashed engine). Returns
    the number of files removed. Sealed records are never touched."""
    removed = 0
    suffix = f".tmp-{pid}" if pid is not None else None
    try:
        entries = os.scandir(root)
    except OSError:
        return 0
    with entries:
        for entry in entries:
            name = entry.name
            if ".tmp-" not in name:
                continue
            if suffix is not None and not name.endswith(suffix):
                continue
            try:
                os.unlink(entry.path)
                removed += 1
            except OSError:
                pass
    if removed:
        log.info("swept %d unsealed spill tmp file(s) from %s "
                 "(pid=%s)", removed, root, pid)
    return removed


class SpillArena:
    """One engine's handle on the shared parked-carry arena directory.

    ``record_nbytes`` is the engine's carry footprint (the payload
    length every record written OR adopted here must match);
    ``incarnation`` tags records written by this engine life — an
    engine-local take with no fleet clock accepts only its OWN
    incarnation's records, which preserves the supervised-restart
    contract (a rebuild regenerates the incarnation, so every pre-fault
    record reads as stale and the restarted engine serves only cold
    re-entries).

    Byte/record accounting is kept INCREMENTALLY (put/take/delete
    deltas) and re-anchored by :meth:`scan_usage` at the stats cadence —
    approximate between scans (the arena is shared, so a peer's writes
    drift it), exact enough for the ``spill_bytes`` budget, and never
    an in-memory record index (check 19)."""

    def __init__(self, root: str, *, max_bytes: int, record_nbytes: int,
                 incarnation: str):
        self.root = root
        self.max_bytes = int(max_bytes)
        self.record_nbytes = int(record_nbytes)
        self.incarnation = incarnation
        os.makedirs(root, exist_ok=True)
        # Approximate live usage (re-anchored by scan_usage): counters
        # only — the filesystem is the index.  # spill-index-ok
        self.bytes = 0
        self.sessions = 0
        # Event totals (consumer-thread writes; readers see ints).
        self.puts = 0
        self.put_refusals = 0
        self.takes = 0
        self.stale = 0
        self.corrupt = 0
        self._dir_fd_sync = hasattr(os, "O_DIRECTORY")

    # -- paths ---------------------------------------------------------

    def _path(self, session_id: Any) -> str:
        return os.path.join(self.root, record_name(session_id))

    # -- the fast admission-time existence check -----------------------

    def probe(self, session_id: Any) -> bool:
        """True when a sealed record exists for this session (one
        ``os.stat`` — cheap enough for the dispatcher's admission path;
        the actual read rides the consumer thread)."""
        try:
            return os.stat(self._path(session_id)).st_size > 0
        except OSError:
            return False

    # -- write side ----------------------------------------------------

    def put(self, session_id: Any, leaves: list, steps: int) -> bool:
        """Seal one carry record (write tmp → fsync → rename). Returns
        False when the byte budget refuses it (that session simply
        stays cold — the same refusal contract as WarmStore.put)."""
        payload = b"".join(
            np.ascontiguousarray(leaf).tobytes() for leaf in leaves)
        if len(payload) != self.record_nbytes:
            self.put_refusals += 1
            return False
        meta = json.dumps({
            "session": str(session_id),
            "incarnation": self.incarnation,
            "writer": os.getpid(),
        }).encode()
        size = _HEADER.size + len(meta) + len(payload)
        prev = self._stat_size(session_id)
        if self.bytes - prev + size > self.max_bytes:
            self.put_refusals += 1
            return False
        crc = zlib.crc32(meta + payload) & 0xFFFFFFFF
        header = _HEADER.pack(_MAGIC, _VERSION, 0, int(steps),
                              len(meta), len(payload), crc)
        path = self._path(session_id)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(meta)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.exception("spill put failed for session %r", session_id)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        self.puts += 1
        self.bytes += size - prev
        if prev == 0:
            self.sessions += 1
        return True

    def _stat_size(self, session_id: Any) -> int:
        try:
            return os.stat(self._path(session_id)).st_size
        except OSError:
            return 0

    def delete(self, session_id: Any) -> None:
        """Tombstone: remove a session's record if one exists (cold
        re-admission enqueues this so a stale carry can never outlive
        the episode restart that invalidated it)."""
        size = self._stat_size(session_id)
        try:
            os.unlink(self._path(session_id))
        except OSError:
            return
        self.bytes = max(0, self.bytes - size)
        self.sessions = max(0, self.sessions - 1)

    # -- read side (consume-on-take) -----------------------------------

    def take(self, session_id: Any, expected_steps: int | None = None
             ) -> tuple[bytes | None, int, str, bool]:
        """Adopt one record: verify, consume, return
        ``(payload, steps, reason, foreign)``. The payload comes back as
        ONE contiguous bytes copy (the engine slices it against its
        carry template); ``foreign`` is True when the record was written
        by a DIFFERENT engine incarnation — a hit with a fleet clock
        and ``foreign`` is a cross-engine warm ADOPTION. Reasons:

        - ``"hit"`` — verified and consumed; adopt warm.
        - ``"miss"`` — no record; cold.
        - ``"stale"`` — stamp != the session's expected clock (or, with
          no clock, a foreign incarnation): consumed and discarded;
          cold. The safe direction — a stale carry served warm would
          change bytes, a cold restart only changes latency.
        - ``"corrupt"`` — torn/CRC-bad/wrong-model: consumed; cold.
        """
        path = self._path(session_id)
        try:
            f = open(path, "rb")
        except OSError:
            return None, 0, "miss", False
        try:
            with f:
                try:
                    mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                except (ValueError, OSError):
                    # Zero-length or vanished underneath us: torn-tail
                    # equivalent — consume and demote.
                    self._consume(session_id, "corrupt")
                    return None, 0, "corrupt", False
                with mm:
                    parsed = self._parse(mm, session_id)
        except OSError:
            self._consume(session_id, "corrupt")
            return None, 0, "corrupt", False
        if parsed is None:
            self._consume(session_id, "corrupt")
            return None, 0, "corrupt", False
        payload, steps, incarnation = parsed
        foreign = incarnation != self.incarnation
        if expected_steps is not None:
            fresh = steps == int(expected_steps)
        else:
            fresh = not foreign
        if not fresh:
            self._consume(session_id, "stale")
            return None, steps, "stale", foreign
        self._consume(session_id, "hit")
        return payload, steps, "hit", foreign

    def _parse(self, mm, session_id: Any):
        """Verify one mapped record; None on any structural failure."""
        if len(mm) < _HEADER.size:
            return None
        magic, version, _flags, steps, meta_len, payload_len, crc = \
            _HEADER.unpack_from(mm, 0)
        if magic != _MAGIC or version != _VERSION:
            return None
        end = _HEADER.size + meta_len + payload_len
        if payload_len != self.record_nbytes or len(mm) != end:
            return None
        body = mm[_HEADER.size:end]
        if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            return None
        try:
            meta = json.loads(body[:meta_len].decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        if meta.get("session") != str(session_id):
            # Digest collision with a different session: treat as a
            # miss-shaped corruption — never hand one session another's
            # episode state.
            return None
        # bytes(body[meta_len:]) is already a copy detached from the map.
        return bytes(body[meta_len:]), int(steps), meta.get("incarnation")

    def _consume(self, session_id: Any, reason: str) -> None:
        if reason == "hit":
            self.takes += 1
        elif reason == "stale":
            self.stale += 1
        else:
            self.corrupt += 1
        self.delete(session_id)

    # -- accounting ----------------------------------------------------

    def scan_usage(self) -> tuple[int, int]:
        """Exact (bytes, sessions) of SEALED records in the arena right
        now (one bounded ``os.scandir`` pass — the stats-cadence
        re-anchor for the incremental counters; the arena is shared, so
        between scans a peer's writes make them approximate)."""
        total = count = 0
        try:
            entries = os.scandir(self.root)
        except OSError:
            return self.bytes, self.sessions
        with entries:
            for entry in entries:
                if not entry.name.endswith(SPILL_SUFFIX):
                    continue
                try:
                    total += entry.stat().st_size
                    count += 1
                except OSError:
                    pass
        self.bytes, self.sessions = total, count
        return total, count
