"""Serving tier: continuous-batching policy inference (ROADMAP item 2).

- :mod:`engine` — :class:`ServeEngine`: deadline-coalesced padded device
  batches over a device-resident session slot pool (LRU admission /
  eviction, batched re-prefill), dispatcher/consumer split, SLO gauges;
  overload-safe (bounded ingress + shedding, per-request deadlines) and
  self-healing (supervised engine rebuild under backoff, terminal failed
  state) — ISSUE 10's contract, pinned by ``tools/serve_chaos.py``.
- :mod:`swap` — :class:`WeightSwapWatcher`: hot weight swaps from the
  crash-safe tagged checkpoint through the verified restore path, applied
  atomically between batches; repeated verified-restore failures open a
  circuit breaker instead of re-hammering a wedged tag.
- :mod:`driver` — synthetic portfolio sessions + closed/open-loop load
  harnesses (``cli serve``, ``tools/serve_soak.py``, ``bench_serve``).
- :mod:`controller` — :class:`ServeController`: the ONLINE half of the
  self-tuning runtime (ROADMAP item 5): a hysteresis-guarded feedback
  loop on the engine's own windowed latency histogram that adapts the
  ``batch_timeout_ms``/``max_queue`` knobs (bounded steps, configured
  values as ceilings) to hold a target p99 under the measured load.
"""

from sharetrade_tpu.serve.controller import ServeController  # noqa: F401
from sharetrade_tpu.serve.engine import (  # noqa: F401
    ServeDeadlineExceeded,
    ServeEngine,
    ServeEngineFailed,
    ServeRejected,
    ServeResult,
    SlotPool,
)
from sharetrade_tpu.serve.swap import WeightSwapWatcher  # noqa: F401
