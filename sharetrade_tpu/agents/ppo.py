"""PPO with GAE — BASELINE.json config 4 (LSTM policy capable).

Clipped surrogate objective over multiple epochs of minibatch updates, all
inside one jitted chunk (epochs and minibatch sweeps are ``lax.scan``s, not
Python loops — XLA sees a single static program).

Recurrence: minibatches cut across the *agent* axis, never the time axis, so
each minibatch replays full sequences from the unroll's initial carry and
LSTM gradients flow through time correctly (the standard sequence-preserving
PPO+RNN scheme).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import (
    Agent, TrainState, batched_carry, batched_reset, build_optimizer,
    make_update_fn, portfolio_metrics,
)
from sharetrade_tpu.agents.rollout import (
    collect_rollout, gae_advantages, normalize_advantages_masked,
    replay_forward,
)
from sharetrade_tpu.config import LearnerConfig
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model
from sharetrade_tpu.parallel.mesh import has_shard_map_axis
from sharetrade_tpu.precision import FP32
from sharetrade_tpu.utils.logging import get_logger


def _replicated(seam_mesh):
    """The canonical replicated NamedSharding for the seam pins — resolved
    through parallel.sharding's cache (lazily: sharding.py imports
    agents.base, so a module-level import here would cycle)."""
    from sharetrade_tpu.parallel.sharding import canonical_sharding
    return canonical_sharding(seam_mesh)


def make_ppo_agent(model: Model, env: TradingEnv,
                   cfg: LearnerConfig, *, num_agents: int = 10,
                   steps_per_chunk: int | None = None, mesh=None,
                   precision=None) -> Agent:
    optimizer = build_optimizer(cfg)
    precision = precision or FP32
    apply_update = make_update_fn(optimizer, cfg, precision)
    # The rollout→update replicate seam applies ONLY on meshes with a
    # shard_map-partitioned axis (mesh.has_shard_map_axis): there, the
    # epoch scans' permuted minibatch gathers over dp-sharded rollout
    # products collide with the partitioned paths' transposed-mesh specs
    # and GSPMD bridges them with an involuntary full rematerialization
    # PER GATHER (the MULTICHIP_r01..r05 warnings; see
    # tools/shard_audit.py). Pure dp/tp meshes compile those gathers
    # cleanly already and keep their exact pre-seam programs — measured
    # byte-identical in the shard-audit manifest.
    seam_mesh = mesh if has_shard_map_axis(mesh) else None
    unroll = steps_per_chunk or cfg.unroll_len
    # Largest divisor of num_agents not exceeding the configured count keeps
    # minibatch SGD meaningful when the two don't divide evenly (e.g. 10
    # agents / 4 requested -> 2 minibatches of 5, not a silent full batch).
    requested = max(1, min(cfg.ppo_minibatches, num_agents))
    num_minibatches = max(d for d in range(1, requested + 1)
                          if num_agents % d == 0)
    if num_minibatches != requested:
        get_logger("agents.ppo").warning(
            "ppo_minibatches=%d does not divide num_agents=%d; using %d",
            cfg.ppo_minibatches, num_agents, num_minibatches)
    mb_size = num_agents // num_minibatches

    def init(key: jax.Array) -> TrainState:
        k_params, k_rng = jax.random.split(key)
        params = model.init(k_params)
        return TrainState(
            params=params, opt_state=optimizer.init(params),
            carry=precision.cast_carry(
                batched_carry(model, num_agents), model),
            env_state=batched_reset(env, num_agents),
            rng=k_rng, env_steps=jnp.int32(0), updates=jnp.int32(0),
        )

    def minibatch_loss(params, traj_mb, carry_mb, adv_mb, ret_mb):
        logits, values, aux = replay_forward(model, params, traj_mb, carry_mb,
                                             remat=cfg.remat)
        log_probs = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            log_probs, traj_mb.action[..., None], axis=-1)[..., 0]
        weight = traj_mb.active
        denom = jnp.maximum(jnp.sum(weight), 1.0)

        # Advantage normalization over the minibatch's active steps (the
        # shared masked normalizer; its re-masking is idempotent under the
        # loss terms' own * weight factors).
        adv = normalize_advantages_masked(adv_mb, weight, denom)

        ratio = jnp.exp(logp - traj_mb.logp)
        clipped = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps)
        policy_loss = -jnp.sum(
            jnp.minimum(ratio * adv, clipped * adv) * weight) / denom
        value_loss = jnp.sum(jnp.square(values - ret_mb) * weight) / denom
        entropy = -jnp.sum(
            jnp.sum(jnp.exp(log_probs) * log_probs, axis=-1) * weight) / denom
        total = (policy_loss + cfg.value_coef * value_loss
                 - cfg.entropy_coef * entropy + cfg.aux_loss_coef * aux)
        return total, (policy_loss, value_loss, entropy)

    def step(ts: TrainState):
        # Rollout forwards read ONE compute-dtype weight copy
        # (precision.py cast_compute — identity in fp32 mode); each
        # minibatch update below casts its own fresh copy of the
        # just-updated masters.
        ts, traj, bootstrap, init_carry = collect_rollout(
            model, env, ts, unroll, num_agents,
            params=precision.cast_compute(ts.params))
        advantages = gae_advantages(traj.reward, traj.value, traj.active,
                                    bootstrap, cfg.gamma, cfg.gae_lambda)
        returns = advantages + traj.value
        if seam_mesh is not None:
            # The rollout→update seam (sp/ep meshes only — see seam_mesh
            # above): marking the rollout products replicated makes the
            # epoch scans' permuted-gather data movement ONE planned
            # all-gather per chunk instead of an involuntary full
            # rematerialization per gather; the updated params/opt and the
            # carried TrainState keep their canonical specs via the jit
            # in/out shardings and the parallel layer's seam pins
            # (parallel/sharding.py constrain_train_state).
            replicated = _replicated(seam_mesh)
            traj, init_carry, advantages, returns = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(x, replicated),
                (traj, init_carry, advantages, returns))

        def epoch_body(carry, _):
            params, opt_state, rng = carry
            rng, k_perm = jax.random.split(rng)
            perm = jax.random.permutation(k_perm, num_agents)

            def mb_body(carry, mb_idx):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice_in_dim(
                    perm, mb_idx * mb_size, mb_size)
                traj_mb = jax.tree.map(lambda x: x[:, idx], traj)
                carry_mb = jax.tree.map(lambda x: x[idx], init_carry)
                adv_mb, ret_mb = advantages[:, idx], returns[:, idx]
                if seam_mesh is not None:
                    # Pin the GATHERED slices replicated as well: GSPMD
                    # otherwise re-derives a dp layout for the tiny
                    # minibatch tensors (mb_size rows can't even tile the
                    # dp axis) and the episode trunk's sp/ep attention
                    # spec then forces the involuntary remat this module
                    # exists to avoid — on carry_mb['hist'] specifically,
                    # the MULTICHIP logs' signature warning.
                    replicated = _replicated(seam_mesh)
                    traj_mb, carry_mb, adv_mb, ret_mb = jax.tree.map(
                        lambda x: jax.lax.with_sharding_constraint(
                            x, replicated),
                        (traj_mb, carry_mb, adv_mb, ret_mb))
                # Differentiate against the compute copy of the CURRENT
                # masters (re-cast per minibatch — the masters just moved);
                # the update itself applies in f32 to the masters.
                (loss, aux), grads = jax.value_and_grad(
                    minibatch_loss, has_aux=True)(
                    precision.cast_compute(params), traj_mb, carry_mb,
                    adv_mb, ret_mb)
                params, opt_state = apply_update(grads, opt_state, params)
                return (params, opt_state), (loss, *aux)

            (params, opt_state), losses = jax.lax.scan(
                mb_body, (params, opt_state), jnp.arange(num_minibatches))
            return (params, opt_state, rng), losses

        (params, opt_state, rng), losses = jax.lax.scan(
            epoch_body, (ts.params, ts.opt_state, ts.rng), None,
            length=cfg.ppo_epochs)
        total, policy_l, value_l, entropy = (jnp.mean(x) for x in losses)

        ts = ts.replace(
            params=params, opt_state=opt_state, rng=rng,
            updates=ts.updates + cfg.ppo_epochs * num_minibatches)
        metrics = {
            "loss": total,
            "policy_loss": policy_l,
            "value_loss": value_l,
            "entropy": entropy,
            "reward_sum": jnp.sum(traj.reward),
            "env_steps": ts.env_steps,
            "updates": ts.updates,
            **portfolio_metrics(env, ts.env_state),
        }
        return ts, metrics

    return Agent(name="ppo", init=init, step=step,
                 num_agents=num_agents, steps_per_chunk=unroll, model=model)
