"""REINFORCE (vanilla policy gradient).

The reference's Python ancestor (rl.py, cited in its README) is a policy-
gradient trader — BASELINE.json config 1. Monte-Carlo returns-to-go with a
batch-mean baseline; one update per unroll.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import (
    Agent, TrainState, batched_carry, batched_reset, build_optimizer,
    make_update_fn, portfolio_metrics,
)
from sharetrade_tpu.agents.rollout import (
    collect_rollout, discounted_returns, normalize_advantages_masked,
    replay_forward,
)
from sharetrade_tpu.config import LearnerConfig
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model
from sharetrade_tpu.precision import FP32


def make_pg_agent(model: Model, env: TradingEnv,
                  cfg: LearnerConfig, *, num_agents: int = 10,
                  steps_per_chunk: int | None = None,
                  precision=None) -> Agent:
    optimizer = build_optimizer(cfg)
    precision = precision or FP32
    apply_update = make_update_fn(optimizer, cfg, precision)
    unroll = steps_per_chunk or cfg.unroll_len

    def init(key: jax.Array) -> TrainState:
        k_params, k_rng = jax.random.split(key)
        params = model.init(k_params)
        return TrainState(
            params=params, opt_state=optimizer.init(params),
            carry=precision.cast_carry(
                batched_carry(model, num_agents), model),
            env_state=batched_reset(env, num_agents),
            rng=k_rng, env_steps=jnp.int32(0), updates=jnp.int32(0),
        )

    def step(ts: TrainState):
        # ONE compute-dtype weight copy per chunk update (precision.py):
        # rollout forwards, loss replay and backward all read it; the
        # update applies to the fp32 masters. Identity in fp32 mode.
        params_c = precision.cast_compute(ts.params)
        ts, traj, bootstrap, init_carry = collect_rollout(
            model, env, ts, unroll, num_agents, params=params_c)
        returns = discounted_returns(traj.reward, traj.active,
                                     bootstrap, cfg.gamma)
        weight = traj.active
        denom = jnp.maximum(jnp.sum(weight), 1.0)
        baseline = jnp.sum(returns * weight) / denom
        adv = (returns - baseline) * weight
        if cfg.normalize_advantages:
            adv = normalize_advantages_masked(adv, weight, denom)

        def loss_fn(params):
            logits, _, aux = replay_forward(model, params, traj, init_carry,
                                            remat=cfg.remat)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), traj.action[..., None], axis=-1
            )[..., 0]
            pg_loss = -jnp.sum(logp * jax.lax.stop_gradient(adv)) / denom
            return pg_loss + cfg.aux_loss_coef * aux

        loss, grads = jax.value_and_grad(loss_fn)(params_c)
        params, opt_state = apply_update(grads, ts.opt_state, ts.params)
        ts = ts.replace(params=params, opt_state=opt_state,
                        updates=ts.updates + 1)
        metrics = {
            "loss": loss,
            "reward_sum": jnp.sum(traj.reward),
            "return_mean": baseline,
            "env_steps": ts.env_steps,
            "updates": ts.updates,
            **portfolio_metrics(env, ts.env_state),
        }
        return ts, metrics

    return Agent(name="pg", init=init, step=step,
                 num_agents=num_agents, steps_per_chunk=unroll, model=model)
