"""Learner zoo (L2): the generalization of the reference's single Q-policy
actor into an algorithm registry (SURVEY.md §7.1 item 3; BASELINE.json
config ladder: qlearn → pg → dqn → a2c → ppo).
"""

from __future__ import annotations

from sharetrade_tpu.agents.a2c import make_a2c_agent
from sharetrade_tpu.agents.base import (  # noqa: F401
    Agent,
    TrainState,
    build_optimizer,
    epsilon_greedy,
    exploit_probability,
    portfolio_metrics,
)
from sharetrade_tpu.agents.dqn import make_dqn_agent
from sharetrade_tpu.agents.pg import make_pg_agent
from sharetrade_tpu.agents.ppo import make_ppo_agent
from sharetrade_tpu.agents.qlearn import make_qlearn_agent
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.env import trading
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.core import Model
from sharetrade_tpu.precision import policy_from_config

_FACTORIES = {
    "qlearn": make_qlearn_agent,
    "pg": make_pg_agent,
    "dqn": make_dqn_agent,
    "a2c": make_a2c_agent,
    "ppo": make_ppo_agent,
}

# Value-based algorithms drive a Q-head; the rest are actor-critic.
_HEADS = {"qlearn": "q", "dqn": "q", "pg": "ac", "a2c": "ac", "ppo": "ac"}


def build_agent(cfg: FrameworkConfig, env: TradingEnv | trading.EnvParams,
                model: Model | None = None, mesh=None) -> Agent:
    """Wire model + env + learner from a framework config.

    Accepts either the generic :class:`TradingEnv` bundle or a bare
    single-asset ``EnvParams`` (wrapped automatically — the common
    test/bench construction path). ``mesh`` flows to ``build_model`` for the
    partitioned transformer paths (ring attention over sp, pipelined blocks
    over pp).
    """
    if isinstance(env, trading.EnvParams):
        params = env
        env = trading.make_trading_env(
            params.prices, window=params.window,
            initial_budget=float(params.initial_budget),
            initial_shares=int(params.initial_shares))
    algo = cfg.learner.algo
    if algo not in _FACTORIES:
        raise ValueError(f"unknown learner.algo {algo!r}; "
                         f"choose from {sorted(_FACTORIES)}")
    if _HEADS[algo] == "q" and cfg.model.kind != "mlp":
        # Value-based learners drive a stateless Q-head; recurrent/attention
        # policies go through the actor-critic algorithms (a2c/ppo/pg).
        raise ValueError(
            f"learner.algo={algo!r} requires model.kind='mlp' "
            f"(got {cfg.model.kind!r}); use a2c/ppo for {cfg.model.kind} policies")
    # Multi-asset model-family boundaries (TCN, episode transformer —
    # PARITY.md) are enforced by build_model, the single authority every
    # construction path funnels through.
    if model is None:
        model = build_model(cfg.model, env.obs_dim, head=_HEADS[algo],
                            num_actions=env.num_actions, mesh=mesh,
                            num_assets=env.num_assets)
    kwargs = {}
    # Precision policy (precision.py): fp32 = structural identity with the
    # pre-policy code; bf16_mixed = fp32 masters + bf16 compute copies at
    # each update boundary + fused f32 updates. Validated here (ConfigError
    # on unknown modes — construction-time STOP, like every impossible
    # composition).
    kwargs["precision"] = policy_from_config(cfg.precision)
    if algo == "dqn" and cfg.learner.journal_replay:
        kwargs["collect_transitions"] = True
    if algo == "ppo":
        # PPO's minibatch phase gathers PERMUTED agent rows out of the
        # dp-sharded rollout products; with the mesh in hand it marks that
        # layout change explicitly (one planned all-gather at the
        # rollout→update seam) instead of leaving GSPMD an involuntary
        # full rematerialization per gather (agents/ppo.py).
        kwargs["mesh"] = mesh
    return _FACTORIES[algo](
        model, env, cfg.learner,
        num_agents=cfg.parallel.num_workers,
        steps_per_chunk=cfg.runtime.chunk_steps, **kwargs)
