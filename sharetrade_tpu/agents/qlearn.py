"""Online Q-learning — the reference algorithm, fused on-device.

One scan iteration here does what one fold step + four Session.run calls do
in the reference (SURVEY.md §3.3): epsilon-greedy selection
(QDecisionPolicyActor.scala:58-62), env transition
(TrainerChildActor.scala:118-146), TD(0) target
(QDecisionPolicyActor.scala:66-73), and the AdaGrad update — for the whole
agent batch at once, with no host involvement.

TD-target index: the reference writes the target at the **next** state's
argmax index (QDecisionPolicyActor.scala:69-71); its rl.py ancestor — and
textbook Q-learning — uses the *taken* action. ``cfg.update_taken_action``
selects (True = textbook, the default; False = reference-bug parity). The
elementwise square loss ``(y - q)²`` reduces to the single updated
coordinate because y equals q everywhere else — implemented directly as the
single-coordinate TD error.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import (
    Agent,
    TrainState,
    batched_carry,
    batched_reset,
    build_optimizer,
    epsilon_greedy,
    exploit_probability,
    make_update_fn,
    portfolio_metrics,
    quarantine_mask,
)
from sharetrade_tpu.config import LearnerConfig
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model, apply_batched
from sharetrade_tpu.precision import FP32


def make_qlearn_agent(model: Model, env: TradingEnv,
                      cfg: LearnerConfig, *, num_agents: int = 10,
                      steps_per_chunk: int = 200, precision=None) -> Agent:
    optimizer = build_optimizer(cfg)
    precision = precision or FP32
    apply_update = make_update_fn(optimizer, cfg, precision)
    horizon = env.num_steps

    def init(key: jax.Array) -> TrainState:
        k_params, k_rng = jax.random.split(key)
        params = model.init(k_params)   # fp32 masters, always
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            carry=precision.cast_carry(
                batched_carry(model, num_agents), model),
            env_state=batched_reset(env, num_agents),
            rng=k_rng,
            env_steps=jnp.int32(0),
            updates=jnp.int32(0),
        )

    def apply_batch(params, obs_batch, carry_batch):
        outs, carries = apply_batched(model, params, obs_batch, carry_batch)
        # aux = the model's auxiliary regularizer (MoE balance term; 0 for
        # dense models) — the loss adds it so a routed-FFN Q-network can't
        # train with an unregularized, collapse-prone gate.
        return outs.logits, jnp.mean(jnp.asarray(outs.aux)), carries

    def one_step(ts: TrainState, _):
        rng, k_act = jax.random.split(ts.rng)
        act_keys = jax.random.split(k_act, num_agents)
        # ONE compute-dtype weight copy per update boundary (precision.py):
        # selection forward, TD replay and backward all read it; the
        # gradients upcast inside apply_update and the update applies to
        # the fp32 masters in ts.params. Identity in fp32 mode.
        params_c = precision.cast_compute(ts.params)

        # Freeze agents whose episode is over (chunking may overrun the
        # horizon) AND quarantine poisoned rows (base.quarantine_mask): a
        # non-finite agent must not reach the shared parameters; the
        # orchestrator respawns the row.
        obs_raw = jax.vmap(env.observe)(ts.env_state)
        healthy = quarantine_mask(obs_raw, ts.env_state)
        active = (ts.env_state.t < horizon) & healthy  # (B,) bool
        obs = jnp.where(healthy[:, None], obs_raw, 0.0)

        q_sel, _aux_sel, carry_new = apply_batch(params_c, obs, ts.carry)
        actions = jax.vmap(lambda k, q: epsilon_greedy(k, q, ts.env_steps, cfg))(
            act_keys, q_sel)

        stepped, rewards = jax.vmap(env.step)(ts.env_state, actions)
        env_state = jax.tree.map(
            lambda new, old: jnp.where(
                active.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
            stepped, ts.env_state)
        rewards = jnp.where(active, rewards, 0.0)
        next_obs = jnp.where(healthy[:, None],
                             jax.vmap(env.observe)(env_state), 0.0)

        def td_loss(params):
            # One stacked forward for Q(s) and Q(s'): tiny matmuls are
            # launch-overhead-bound on TPU, so halving the op count beats
            # two back-to-back (B, obs) contractions.
            q_both, aux, _ = apply_batch(
                params, jnp.concatenate([obs, next_obs], axis=0),
                jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0),
                             ts.carry, carry_new))
            q_s = q_both[:num_agents]                             # (B, A)
            q_next = jax.lax.stop_gradient(q_both[num_agents:])
            target = rewards + cfg.gamma * jnp.max(q_next, axis=-1)
            idx = jnp.where(
                cfg.update_taken_action,
                actions,
                jnp.argmax(q_next, axis=-1).astype(jnp.int32),  # reference bug
            )
            predicted = jnp.take_along_axis(q_s, idx[:, None], axis=-1)[:, 0]
            per_agent = jnp.square(predicted - target) * active
            td = jnp.sum(per_agent) / jnp.maximum(jnp.sum(active), 1)
            return td + cfg.aux_loss_coef * aux

        loss, grads = jax.value_and_grad(td_loss)(params_c)
        any_active = jnp.any(active)
        new_params, opt_state = apply_update(grads, ts.opt_state, ts.params)
        params = jax.tree.map(
            lambda new, old: jnp.where(any_active, new, old),
            new_params, ts.params)
        opt_state = jax.tree.map(
            lambda new, old: jnp.where(any_active, new, old),
            opt_state, ts.opt_state)

        ts = ts.replace(
            params=params, opt_state=opt_state, carry=carry_new,
            env_state=env_state, rng=rng,
            env_steps=ts.env_steps + jnp.where(any_active, 1, 0),
            updates=ts.updates + jnp.where(any_active, 1, 0),
        )
        return ts, (loss, jnp.sum(rewards))

    def step(ts: TrainState):
        ts, (losses, rewards) = jax.lax.scan(
            one_step, ts, None, length=steps_per_chunk)
        metrics = {
            "loss": jnp.mean(losses),
            "reward_sum": jnp.sum(rewards),
            "exploit_prob": exploit_probability(ts.env_steps, cfg),
            "env_steps": ts.env_steps,
            "updates": ts.updates,
            **portfolio_metrics(env, ts.env_state),
        }
        return ts, metrics

    return Agent(name="qlearn", init=init, step=step,
                 num_agents=num_agents, steps_per_chunk=steps_per_chunk,
                 model=model)
