"""DQN with an on-device replay buffer — BASELINE.json config 2.

Replay lives in HBM as fixed-size circular arrays (no dynamic shapes —
position/size are carried indices), so sampling and the TD update stay inside
the jitted chunk. A target network (synced every ``target_update_every``
updates) stabilizes the bootstrap — the standard upgrade over the reference's
online Q-learning, which bootstraps from the live network
(QDecisionPolicyActor.scala:67-68).

The journal bridge gives the persistence-backed replay capability of the
reference's event-sourced layer (SURVEY.md §7.4 "Replay/persistence
bandwidth"): the runtime appends packed binary records
(data/transitions.py) and ``fill_replay_from_arrays`` /
``fill_replay_from_journal`` rebuild the device buffer on resume (the
latter reads legacy JSON events).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from sharetrade_tpu.agents.base import (
    Agent, TrainState, batched_carry, batched_reset, build_optimizer,
    epsilon_greedy, exploit_probability, make_update_fn, per_beta,
    portfolio_metrics, quarantine_mask,
)
from sharetrade_tpu.config import ConfigError, LearnerConfig
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model, apply_batched
from sharetrade_tpu.ops import sum_tree
from sharetrade_tpu.precision import FP32


@struct.dataclass
class ReplayBuffer:
    obs: jax.Array       # (cap, obs_dim) f32
    action: jax.Array    # (cap,) i32
    reward: jax.Array    # (cap,) f32
    next_obs: jax.Array  # (cap, obs_dim) f32
    pos: jax.Array       # i32 next write index
    size: jax.Array      # i32 valid entries

    @classmethod
    def create(cls, capacity: int, obs_dim: int) -> "ReplayBuffer":
        return cls(
            obs=jnp.zeros((capacity, obs_dim), jnp.float32),
            action=jnp.zeros((capacity,), jnp.int32),
            reward=jnp.zeros((capacity,), jnp.float32),
            next_obs=jnp.zeros((capacity, obs_dim), jnp.float32),
            pos=jnp.int32(0),
            size=jnp.int32(0),
        )

    def push(self, obs, action, reward, next_obs, valid) -> "ReplayBuffer":
        """Insert a batch of B transitions (wrapping). ``valid`` masks agents
        whose episode already ended — their slots are written then un-counted
        by pointing them at already-valid rows (weight-neutral because the
        write happens before the pointer advances past them). XLA
        dead-code-eliminates the unused plan outputs, so this traced
        program is the pre-plan one bit-for-bit (golden-pinned)."""
        return self.push_with_plan(obs, action, reward, next_obs, valid)[0]

    def sample(self, key: jax.Array, batch: int):
        idx = jax.random.randint(key, (batch,), 0,
                                 jnp.maximum(self.size, 1))
        return (self.obs[idx], self.action[idx],
                self.reward[idx], self.next_obs[idx])

    def push_with_plan(self, obs, action, reward, next_obs, valid):
        """:meth:`push` plus its write plan ``(buffer, slot_idx,
        write_mask)`` so a priority structure (the PER sum-tree) can
        mirror exactly the slots the circular buffer touched. ``push``
        delegates here (one copy of the circular-write plan; the golden
        trajectory pins that the delegation kept the compiled uniform
        program bit-identical)."""
        batch = obs.shape[0]
        capacity = self.obs.shape[0]
        # Only advance through valid transitions: compact them to the front.
        order = jnp.argsort(~valid)  # valid rows first, stable
        obs, action = obs[order], action[order]
        reward, next_obs = reward[order], next_obs[order]
        n_valid = jnp.sum(valid).astype(jnp.int32)
        idx = (self.pos + jnp.arange(batch, dtype=jnp.int32)) % capacity
        write = jnp.arange(batch) < n_valid
        safe_idx = jnp.where(write, idx, (self.pos - 1) % capacity)
        buf = self.replace(
            obs=self.obs.at[safe_idx].set(
                jnp.where(write[:, None], obs, self.obs[safe_idx])),
            action=self.action.at[safe_idx].set(
                jnp.where(write, action, self.action[safe_idx])),
            reward=self.reward.at[safe_idx].set(
                jnp.where(write, reward, self.reward[safe_idx])),
            next_obs=self.next_obs.at[safe_idx].set(
                jnp.where(write[:, None], next_obs, self.next_obs[safe_idx])),
            pos=(self.pos + n_valid) % capacity,
            size=jnp.minimum(self.size + n_valid, capacity),
        )
        return buf, safe_idx, write


@struct.dataclass
class DQNExtras:
    target_params: object
    replay: ReplayBuffer


@struct.dataclass
class PerState:
    """Prioritized-replay state riding next to the circular arrays: the
    fixed-shape sum-tree (leaf i = stored priority of replay slot i,
    already ``per_alpha``-exponentiated) and the running max stored
    priority new transitions enter at."""

    tree: sum_tree.SumTree
    max_priority: jax.Array   # f32 scalar, stored-domain


@struct.dataclass
class DQNExtrasPER:
    """``DQNExtras`` + the PER sum-tree (``learner.replay_priority="per"``).
    A separate class — not an optional field — so the uniform default's
    pytree (and therefore its traced program and checkpoint layout) stays
    byte-identical to the pre-data-plane code."""

    target_params: object
    replay: ReplayBuffer
    per: PerState


def make_dqn_agent(model: Model, env: TradingEnv,
                   cfg: LearnerConfig, *, num_agents: int = 10,
                   steps_per_chunk: int = 200,
                   collect_transitions: bool = False,
                   precision=None) -> Agent:
    """``collect_transitions`` makes each chunk additionally return its raw
    transition batch under ``metrics["transitions"]`` so the host can journal
    them (the runtime's ``learner.journal_replay`` switch).

    ``learner.replay_priority`` selects the sampler: ``"uniform"``
    (default) is the pre-data-plane code path bit-for-bit (golden-pinned,
    tests/golden/replay_uniform_golden.json); ``"per"`` adds the
    sum-tree prioritized sampler (ops/sum_tree.py) — priority update,
    stratified sample, and TD-error write-back all inside this one traced
    step, with the importance-sampling weights folded into the TD loss."""
    if cfg.replay_priority not in ("uniform", "per"):
        raise ConfigError(
            f"unknown learner.replay_priority {cfg.replay_priority!r} "
            "(expected 'uniform' or 'per')")
    if cfg.replay_capacity <= num_agents:
        # The circular push aliases masked rows onto (pos-1): with the
        # batch spanning the whole buffer, a masked row can collide with
        # a valid write and the scatter winner is implementation-defined
        # (buffer AND sum-tree). A capacity this small is a config error,
        # not a samplable buffer.
        raise ConfigError(
            f"learner.replay_capacity ({cfg.replay_capacity}) must exceed "
            f"the agent batch ({num_agents}): a push spanning the whole "
            "circular buffer has implementation-defined slot winners")
    use_per = cfg.replay_priority == "per"
    optimizer = build_optimizer(cfg)
    precision = precision or FP32
    apply_update = make_update_fn(optimizer, cfg, precision)
    horizon = env.num_steps
    obs_dim = model.obs_dim

    def init(key: jax.Array) -> TrainState:
        k_params, k_rng = jax.random.split(key)
        params = model.init(k_params)
        replay = ReplayBuffer.create(cfg.replay_capacity, obs_dim)
        target = jax.tree.map(jnp.copy, params)
        extras = (DQNExtrasPER(
            target_params=target, replay=replay,
            per=PerState(tree=sum_tree.create(cfg.replay_capacity),
                         max_priority=jnp.float32(1.0)))
            if use_per else
            DQNExtras(target_params=target, replay=replay))
        return TrainState(
            params=params, opt_state=optimizer.init(params),
            carry=precision.cast_carry(
                batched_carry(model, num_agents), model),
            env_state=batched_reset(env, num_agents),
            rng=k_rng, env_steps=jnp.int32(0), updates=jnp.int32(0),
            extras=extras,
        )

    def q_batch(params, obs_batch):
        outs, _ = apply_batched(model, params, obs_batch, ())
        return outs.logits

    def q_batch_with_aux(params, obs_batch):
        """Forward that also surfaces ModelOut.aux (the MoE balance term;
        0 for dense models) so the TD loss can regularize a routed gate."""
        outs, _ = apply_batched(model, params, obs_batch, ())
        return outs.logits, jnp.mean(jnp.asarray(outs.aux))

    def one_step(ts: TrainState, _):
        rng, k_act, k_sample = jax.random.split(ts.rng, 3)
        act_keys = jax.random.split(k_act, num_agents)
        # ONE compute-dtype copy per update boundary (precision.py): the
        # online net AND the target net forwards read compute copies; the
        # update applies to the fp32 masters. Identity in fp32 mode.
        params_c = precision.cast_compute(ts.params)
        target_c = precision.cast_compute(ts.extras.target_params)

        # Horizon freeze + poisoned-row quarantine (base.quarantine_mask):
        # a non-finite agent contributes no transitions to the replay buffer
        # and no NaNs to the shared network; the orchestrator respawns it.
        obs_raw = jax.vmap(env.observe)(ts.env_state)
        healthy = quarantine_mask(obs_raw, ts.env_state)
        active = (ts.env_state.t < horizon) & healthy
        obs = jnp.where(healthy[:, None], obs_raw, 0.0)

        q_sel = q_batch(params_c, obs)
        actions = jax.vmap(lambda k, q: epsilon_greedy(k, q, ts.env_steps, cfg))(
            act_keys, q_sel)
        stepped, rewards = jax.vmap(env.step)(ts.env_state, actions)
        env_state = jax.tree.map(
            lambda new, old: jnp.where(
                active.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
            stepped, ts.env_state)
        rewards = jnp.where(active, rewards, 0.0)
        next_obs = jnp.where(healthy[:, None],
                             jax.vmap(env.observe)(env_state), 0.0)

        def td_core(params, b_obs, b_act, b_rew, b_next, weights=None):
            """ONE copy of the TD math for both samplers (a target-rule
            fix must never land in one branch only): ``weights=None`` is
            the uniform loss — literally the pre-PER ops, golden-pinned;
            PER passes its IS weights in."""
            q_s, aux = q_batch_with_aux(params, b_obs)
            q_next = jax.lax.stop_gradient(q_batch(target_c, b_next))
            target = b_rew + cfg.gamma * jnp.max(q_next, axis=-1)
            predicted = jnp.take_along_axis(
                q_s, b_act[:, None], axis=-1)[:, 0]
            td_err = predicted - target
            sq = (jnp.square(td_err) if weights is None
                  else weights * jnp.square(td_err))
            return jnp.mean(sq) + cfg.aux_loss_coef * aux, td_err

        if use_per:
            # Prioritized path: the push mirrors its write plan into the
            # sum-tree (new transitions enter at the running max stored
            # priority), the stratified sample + IS weights come from the
            # tree, and the TD errors below re-prioritize the sampled
            # leaves — all inside this traced step.
            per = ts.extras.per
            replay, push_idx, push_write = ts.extras.replay.push_with_plan(
                obs, actions, rewards, next_obs, active)
            tree = sum_tree.set_priorities(
                per.tree, push_idx,
                jnp.broadcast_to(per.max_priority, push_idx.shape),
                push_write)
            sample_idx, sample_probs = sum_tree.sample_stratified(
                tree, k_sample, cfg.replay_batch)
            beta = per_beta(ts.env_steps, cfg)
            weights = jax.lax.stop_gradient(
                sum_tree.is_weights(sample_probs, replay.size, beta))

            def td_loss(params):
                return td_core(params, replay.obs[sample_idx],
                               replay.action[sample_idx],
                               replay.reward[sample_idx],
                               replay.next_obs[sample_idx], weights)

            ready = replay.size >= cfg.replay_batch
            (loss, td_err), grads = jax.value_and_grad(
                td_loss, has_aux=True)(params_c)
        else:
            replay = ts.extras.replay.push(obs, actions, rewards, next_obs, active)

            def td_loss(params):
                b_obs, b_act, b_rew, b_next = replay.sample(k_sample, cfg.replay_batch)
                # The unused td_err aux is dead-code-eliminated: the
                # compiled uniform program is the pre-PER one bit-for-bit.
                return td_core(params, b_obs, b_act, b_rew, b_next)[0]

            # Learn only once the buffer can fill a batch.
            ready = replay.size >= cfg.replay_batch
            loss, grads = jax.value_and_grad(td_loss)(params_c)
        new_params, opt_state = apply_update(grads, ts.opt_state, ts.params)
        params = jax.tree.map(lambda new, old: jnp.where(ready, new, old),
                              new_params, ts.params)
        opt_state = jax.tree.map(lambda new, old: jnp.where(ready, new, old),
                                 opt_state, ts.opt_state)
        n_updates = ts.updates + jnp.where(ready, 1, 0)

        # Hard target sync every target_update_every updates.
        sync = ready & (n_updates % cfg.target_update_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t),
            ts.extras.target_params, params)

        if use_per:
            # TD-error write-back, gated on ready THROUGH THE MASK: an
            # unready sample ran on garbage strata and must not touch
            # real priorities. The mask (not a post-hoc where over old
            # and new trees) keeps the pre-write tree dead after this
            # call, so XLA scatters the levels in place instead of
            # copying them — the difference between PER riding along and
            # PER costing a tree copy per env step.
            new_p = (jnp.abs(td_err) + cfg.per_eps) ** cfg.per_alpha
            tree = sum_tree.set_priorities(
                tree, sample_idx, new_p,
                mask=jnp.broadcast_to(ready, sample_idx.shape))
            max_p = jnp.where(
                ready, jnp.maximum(per.max_priority, jnp.max(new_p)),
                per.max_priority)
            extras = DQNExtrasPER(
                target_params=target_params, replay=replay,
                per=PerState(tree=tree, max_priority=max_p))
        else:
            extras = DQNExtras(target_params=target_params, replay=replay)
        ts = ts.replace(
            params=params, opt_state=opt_state, env_state=env_state, rng=rng,
            env_steps=ts.env_steps + jnp.where(jnp.any(active), 1, 0),
            updates=n_updates,
            extras=extras,
        )
        out = (jnp.where(ready, loss, 0.0), jnp.sum(rewards))
        if collect_transitions:
            out = out + ((obs, actions, rewards, next_obs, active),)
        return ts, out

    def step(ts: TrainState):
        ts, outs = jax.lax.scan(
            one_step, ts, None, length=steps_per_chunk)
        losses, rewards = outs[0], outs[1]
        metrics = {
            "loss": jnp.mean(losses),
            "reward_sum": jnp.sum(rewards),
            "replay_size": ts.extras.replay.size,
            "exploit_prob": exploit_probability(ts.env_steps, cfg),
            "env_steps": ts.env_steps,
            "updates": ts.updates,
            **portfolio_metrics(env, ts.env_state),
        }
        if use_per:
            # PER gauges (obs/metrics.prom via the chunk metric stream);
            # only in per mode — the uniform metrics dict is part of the
            # golden-pinned pre-PR surface.
            metrics["per_max_priority"] = ts.extras.per.max_priority
            metrics["per_beta"] = per_beta(ts.env_steps, cfg)
        if collect_transitions:
            t_obs, t_act, t_rew, t_next, t_valid = outs[2]
            metrics["transitions"] = {
                "obs": t_obs, "action": t_act, "reward": t_rew,
                "next_obs": t_next, "valid": t_valid}
        return ts, metrics

    return Agent(name="dqn", init=init, step=step,
                 num_agents=num_agents, steps_per_chunk=steps_per_chunk,
                 model=model)


def reseed_per_priorities(extras, *, priority: float | None = None):
    """Rebuild the PER sum-tree after an out-of-band buffer fill (the
    resume-time journal warm start): priorities are not journaled, so the
    ``warm.size`` recovered rows re-enter at the running max stored
    priority (exactly how a fresh push would admit them) and every empty
    slot goes massless. No-op for uniform-mode extras."""
    if not isinstance(extras, DQNExtrasPER):
        return extras
    per = extras.per
    n_leaves = per.tree.num_leaves
    p = per.max_priority if priority is None else jnp.float32(priority)
    leaves = jnp.where(
        jnp.arange(n_leaves) < extras.replay.size, p, 0.0
    ).astype(jnp.float32)
    return extras.replace(per=per.replace(
        tree=sum_tree.from_leaves(leaves)))


def fill_replay_from_journal(replay: ReplayBuffer, journal) -> ReplayBuffer:
    """Replay journaled transitions into the device buffer (offline/warm-start
    path — the event-sourcing recovery pattern applied to experience).

    Only the journal tail that can actually survive in the circular buffer is
    pushed: replaying from record zero would cost time linear in the whole
    training history, and pushing batches wider than the buffer would scatter
    with duplicate indices (implementation-defined winner). Events are pushed
    oldest-first in capacity-bounded slices so "newest wins" circular
    semantics hold deterministically."""
    return fill_replay_from_events(
        replay, [e for e in journal.replay() if e.get("type") == "transitions"])


def fill_replay_from_arrays(replay: ReplayBuffer, obs, action, reward,
                            next_obs) -> ReplayBuffer:
    """Push pre-decoded transition arrays (oldest-first) into the device
    buffer in capacity-bounded slices — the fast path fed by the packed
    binary journal reader (data/transitions.py read_tail_transitions)."""
    capacity = replay.obs.shape[0]
    obs = jnp.asarray(obs, jnp.float32)
    action = jnp.asarray(action, jnp.int32)
    reward = jnp.asarray(reward, jnp.float32)
    next_obs = jnp.asarray(next_obs, jnp.float32)
    for lo in range(0, obs.shape[0], capacity):
        sl = slice(lo, lo + capacity)
        valid = jnp.ones((obs[sl].shape[0],), bool)
        replay = replay.push(obs[sl], action[sl], reward[sl],
                             next_obs[sl], valid)
    return replay


def fill_replay_from_events(replay: ReplayBuffer,
                            events: list[dict]) -> ReplayBuffer:
    capacity = replay.obs.shape[0]
    # Walk back from the tail until the kept events cover the capacity.
    kept, rows = [], 0
    for event in reversed(events):
        kept.append(event)
        rows += len(event["action"])
        if rows >= capacity:
            break
    for event in reversed(kept):
        obs = jnp.asarray(event["obs"], jnp.float32)
        action = jnp.asarray(event["action"], jnp.int32)
        reward = jnp.asarray(event["reward"], jnp.float32)
        next_obs = jnp.asarray(event["next_obs"], jnp.float32)
        for lo in range(0, obs.shape[0], capacity):
            sl = slice(lo, lo + capacity)
            valid = jnp.ones((obs[sl].shape[0],), bool)
            replay = replay.push(obs[sl], action[sl], reward[sl],
                                 next_obs[sl], valid)
    return replay
