"""Learner interface and shared RL machinery.

The reference's learner is one actor whose mailbox serializes ~230k
single-row Session.run calls (SURVEY.md §3.3). Here every learner exposes the
same two pure functions, and the whole step loop lives on-device:

- ``init(key) -> TrainState``
- ``step(TrainState) -> (TrainState, metrics)``  — advances ``steps_per_chunk``
  env steps for the WHOLE agent batch inside one jitted program (action
  selection + env transition + learning update fused; §7.2's inversion).

The orchestrator (runtime/) only ever calls these two functions, so the
algorithms (Q-learning, PG, DQN, A2C, PPO) are interchangeable — the
generalization of the reference's single hard-wired Q-policy actor that
SURVEY.md §7.1 item 3 requires.

Batching note (the explicit algorithm change demanded by SURVEY.md §7.4): the
reference's 10 workers funnel updates through one mailbox, so the network
changes between *every* worker's step. Here the B agents' per-step losses are
averaged into ONE update per env step (or per unroll). With one agent the
semantics match the reference exactly — that is the parity-test configuration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from sharetrade_tpu.config import LearnerConfig
from sharetrade_tpu.env.core import TradingEnv


@struct.dataclass
class TrainState:
    """Everything a learner threads between chunks — exactly the state that
    checkpoint/resume must capture (SURVEY.md §7.1 item 7: model + optimizer
    + RNG + episode cursor)."""

    params: Any
    opt_state: Any
    carry: Any               # (B, ...) model recurrent state
    env_state: Any           # batched (B,) episode cursors (env-specific pytree)
    rng: jax.Array
    env_steps: jax.Array     # i32 global env-step counter (epsilon schedule input)
    updates: jax.Array       # i32 update counter (the reference's `iteration`)
    extras: Any = None       # algo-specific (replay buffer, target params, ...)


@dataclass(frozen=True)
class Agent:
    """A learner: pure init/step plus static shape facts for the runtime.

    ``model`` carries the policy network the learner was built around so the
    runtime evaluates exactly what was trained (rebuilding from config would
    silently evaluate a different architecture when a custom model was
    injected)."""

    name: str
    init: Callable[[jax.Array], TrainState]
    step: Callable[[TrainState], tuple[TrainState, dict[str, jax.Array]]]
    num_agents: int
    steps_per_chunk: int
    model: Any = None


def megachunk_step(step_fn: Callable[[TrainState],
                                     tuple[TrainState, dict[str, jax.Array]]],
                   factor: int) -> Callable[[TrainState],
                                            tuple[TrainState, dict]]:
    """Device-resident megachunk: ``factor`` consecutive chunk steps fused
    into ONE compiled program, so the host pays one dispatch per ``factor``
    chunks instead of one each. On tunneled links the ~0.1 s host dispatch
    floor costs about as much as executing an entire flagship chunk
    (BASELINE.md, round-5 verdict), so this is the lever that amortizes it.

    Per-chunk metrics stack along a leading ``(factor,)`` axis: every
    learner's metrics dict — scalars AND DQN's ``transitions`` batch — is a
    scan output, so the whole megachunk's metric stream reads back with a
    single batched ``jax.device_get`` at the boundary instead of ``factor``
    scattered scalar round-trips. The scanned body is the same traced
    function as the single-chunk program, so K fused chunks are bit-identical
    to K host-dispatched chunks (pinned by tests/test_megachunk.py parity).

    On a mesh, ``parallel/sharding.py`` composes the carry-sharding pin
    UNDER this wrapper (``step_fn`` arrives already constrained), so each
    of the K-1 inner-chunk seams — which have no jit in/out shardings of
    their own — keeps the TrainState on its canonical specs instead of
    letting GSPMD re-derive (and involuntarily reshard) the scan carry.
    """
    if factor < 1:
        raise ValueError(f"megachunk factor must be >= 1, got {factor}")

    def megastep(ts: TrainState):
        def body(carry, _):
            return step_fn(carry)

        return jax.lax.scan(body, ts, None, length=factor)

    return megastep


def build_optimizer(cfg: LearnerConfig) -> optax.GradientTransformation:
    """Reference: AdaGrad(0.01) (QDecisionPolicyActor.scala:50). optax's
    default ``initial_accumulator_value=0.1`` matches TF's AdaGrad."""
    if cfg.optimizer == "adagrad":
        return optax.adagrad(cfg.learning_rate)
    if cfg.optimizer == "adam":
        return optax.adam(cfg.learning_rate)
    if cfg.optimizer == "sgd":
        return optax.sgd(cfg.learning_rate)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


def make_update_fn(optimizer: optax.GradientTransformation,
                   cfg: LearnerConfig, precision,
                   *, use_pallas: bool | None = None):
    """THE optimizer-update seam every learner applies its gradients
    through: ``update(grads, opt_state, params) -> (params, opt_state)``.

    ``grads`` arrive in whatever dtype the loss backward produced (bf16
    under the mixed policy — differentiation runs against the compute
    copy); the seam owns the master-space upcast, so learners never touch
    a dtype. Two implementations, selected by the precision policy
    (``precision.use_fused_update``):

    - **optax pair** (fp32 default): literally ``optimizer.update`` +
      ``optax.apply_updates`` — the pre-policy code path, bit-identical,
      with the grads routed through ``precision.grads_to_master`` (an
      object identity in fp32 mode).
    - **fused** (bf16_mixed default, or ``precision.fused_update='on'``):
      ``ops/fused_update.fused_apply`` — grad-upcast + moment update +
      param update in one pass per leaf (Pallas on TPU, one fused XLA
      elementwise chain elsewhere), optax-exact in fp32 and sharing the
      optax state structure either way.

    Unsupported optimizers under 'on'/'auto' fall back to the optax pair
    (fused_supported) rather than failing — the policy is a performance
    lever, not a capability gate."""
    from sharetrade_tpu.ops.fused_update import fused_apply, fused_supported

    if precision is not None and precision.use_fused_update \
            and fused_supported(cfg):
        name, lr = cfg.optimizer, cfg.learning_rate
        compute_dtype = precision.compute_dtype

        def update(grads, opt_state, params):
            return fused_apply(name, lr, grads, opt_state, params,
                               compute_dtype=compute_dtype,
                               use_pallas=use_pallas)

        return update

    def update(grads, opt_state, params):
        if precision is not None:
            grads = precision.grads_to_master(grads)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state

    return update


def exploit_probability(step: jax.Array, cfg: LearnerConfig) -> jax.Array:
    """P(exploit) = min(epsilon, step / ramp): fully random at step 0 ramping
    to epsilon-greedy (QDecisionPolicyActor.scala:58: ``Seq(epsilon,
    step/1000f).min``)."""
    return jnp.minimum(jnp.float32(cfg.epsilon),
                       step.astype(jnp.float32) / cfg.epsilon_ramp_steps)


def per_beta(step: jax.Array, cfg: LearnerConfig) -> jax.Array:
    """Importance-sampling exponent schedule for prioritized replay:
    anneal from ``per_beta0`` to 1 over ``per_beta_steps`` env steps (the
    Schaul et al. schedule — bias correction tightens as the policy
    stabilizes), the PER sibling of :func:`exploit_probability`."""
    frac = step.astype(jnp.float32) / max(1, cfg.per_beta_steps)
    return jnp.minimum(
        jnp.float32(1.0),
        jnp.float32(cfg.per_beta0) + (1.0 - cfg.per_beta0) * frac)


def epsilon_greedy(key: jax.Array, q_values: jax.Array, step: jax.Array,
                   cfg: LearnerConfig) -> jax.Array:
    """One agent's Buy/Sell/Hold choice (QDecisionPolicyActor.scala:58-62)."""
    k_gate, k_rand = jax.random.split(key)
    exploit = jax.random.uniform(k_gate) < exploit_probability(step, cfg)
    greedy = jnp.argmax(q_values).astype(jnp.int32)
    rand = jax.random.randint(k_rand, (), 0, q_values.shape[0], jnp.int32)
    return jnp.where(exploit, greedy, rand)


def batched_reset(env: TradingEnv, num_agents: int):
    single = env.reset()
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (num_agents,) + x.shape),
                        single)


def batched_carry(model, num_agents: int):
    carry = model.init_carry()
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (num_agents,) + x.shape),
                        carry)


def healthy_mask(obs: jax.Array) -> jax.Array:
    """(B, obs_dim) observations -> (B,) bool: rows that are entirely finite.

    The quarantine predicate of the per-agent fault story (the reference's
    one-dead-child-doesn't-stop-the-other-nine supervision,
    TrainerRouterActor.scala:141-146, translated to vectorized agents): a
    poisoned agent (NaN/Inf budget, corrupted price row) is masked out of
    the shared parameter update on-device — learners AND the observation fed
    to the network (sanitized to zeros so no NaN flows through the loss) —
    and the orchestrator respawns just that row between chunks
    (Orchestrator._heal_agents)."""
    return jnp.all(jnp.isfinite(obs), axis=-1)


def agent_health(env_state) -> jax.Array:
    """(B,) bool from the env-state pytree: True where every leaf row is
    finite (the host-visible form of the quarantine predicate)."""
    leaves = jax.tree.leaves(env_state)
    b = leaves[0].shape[0]
    ok = jnp.ones((b,), bool)
    for leaf in leaves:
        ok &= jnp.all(jnp.isfinite(leaf.reshape(b, -1)), axis=-1)
    return ok


def election_health(env_state, carry) -> jax.Array:
    """(B,) bool: THE row-health predicate shared by representative
    election (agents/rollout.py) and the per-row heal
    (runtime/orchestrator.py): every env-state leaf row finite AND every
    batched model-carry leaf row finite. A row with a finite wallet but a
    non-finite carry (NaN K/V cache) must never be elected representative —
    its carry would broadcast into every agent's shared trunk, escalating a
    one-row fault to a whole-batch poisoning."""
    from sharetrade_tpu.models.core import rows_finite
    ok = agent_health(env_state)
    return ok & rows_finite(carry, ok.shape[0])


def quarantine_mask(obs_raw: jax.Array, env_state) -> jax.Array:
    """THE learner-side quarantine predicate: a row is healthy iff its
    observation AND its whole env-state row are finite. One definition so
    every learner fences the same faults — a site that checked only the
    observation would silently re-admit poison living outside it (e.g.
    ``share_value``, which reaches the loss through the reward)."""
    return healthy_mask(obs_raw) & agent_health(env_state)


def portfolio_metrics(env: TradingEnv, env_state) -> dict[str, jax.Array]:
    """The router's aggregation: mean/std over worker portfolios
    (TrainerRouterActor.scala:137-151) plus richer distribution stats.

    Two aggregation views are emitted side by side:

    - ``portfolio_mean``/``portfolio_std``: continuous stats over all
      HEALTHY agents, including in-flight ones (progressive — richer than
      the reference). Quarantined (non-finite) rows are excluded, the way a
      dead child drops out of the reference's aggregation, and counted in
      ``unhealthy_workers`` so the orchestrator can heal them.
    - ``portfolio_mean_trained``/``portfolio_std_trained``: stats over only
      the agents whose episode cursor reached the horizon — the reference's
      exact ``GetAvg`` observable, which asks the *trained* children only
      (TrainerRouterActor.scala:84-95,137-139). ``trained_workers`` carries
      the mask count so the host can answer NotComputed when it is zero
      (masked stats are 0-filled then, never NaN, to stay jit-safe).
    """
    values = jax.vmap(env.portfolio_value)(env_state)
    fine = agent_health(env_state).astype(jnp.float32)
    values = jnp.where(fine > 0, values, 0.0)
    n_fine = jnp.maximum(jnp.sum(fine), 1.0)
    mean = jnp.sum(values * fine) / n_fine
    var = jnp.sum(fine * (values - mean) ** 2) / n_fine
    done = fine * (env_state.t >= env.num_steps).astype(jnp.float32)
    n_done = jnp.sum(done)
    safe_n = jnp.maximum(n_done, 1.0)
    mean_t = jnp.sum(values * done) / safe_n
    var_t = jnp.sum(done * (values - mean_t) ** 2) / safe_n
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    return {
        "portfolio_mean": mean,
        "portfolio_std": jnp.sqrt(var),
        "portfolio_min": jnp.min(jnp.where(fine > 0, values, big)),
        "portfolio_max": jnp.max(jnp.where(fine > 0, values, -big)),
        "portfolio_mean_trained": mean_t,
        "portfolio_std_trained": jnp.sqrt(var_t),
        "trained_workers": n_done,
        "unhealthy_workers": values.shape[0] - jnp.sum(fine),
    }
