"""Advantage Actor-Critic (n-step, synchronous) — BASELINE.json config 3.

The "rollout workers → shared learner" shape of the reference (10 broadcast
workers, one parameter server; SURVEY.md §2.2) is exactly A2C's synchronous
geometry: B parallel env agents advance ``unroll_len`` steps, then one joint
update from bootstrapped n-step returns. Policy + value + entropy losses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import (
    Agent, TrainState, batched_carry, batched_reset, build_optimizer,
    make_update_fn, portfolio_metrics,
)
from sharetrade_tpu.agents.rollout import (
    collect_rollout, discounted_returns, normalize_advantages_masked,
    replay_forward,
)
from sharetrade_tpu.config import LearnerConfig
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model
from sharetrade_tpu.precision import FP32


def make_a2c_agent(model: Model, env: TradingEnv,
                   cfg: LearnerConfig, *, num_agents: int = 10,
                   steps_per_chunk: int | None = None,
                   precision=None) -> Agent:
    optimizer = build_optimizer(cfg)
    precision = precision or FP32
    apply_update = make_update_fn(optimizer, cfg, precision)
    unroll = steps_per_chunk or cfg.unroll_len

    def init(key: jax.Array) -> TrainState:
        k_params, k_rng = jax.random.split(key)
        params = model.init(k_params)
        return TrainState(
            params=params, opt_state=optimizer.init(params),
            carry=precision.cast_carry(
                batched_carry(model, num_agents), model),
            env_state=batched_reset(env, num_agents),
            rng=k_rng, env_steps=jnp.int32(0), updates=jnp.int32(0),
        )

    def step(ts: TrainState):
        # ONE compute-dtype weight copy per chunk update (precision.py);
        # the update applies to the fp32 masters. Identity in fp32 mode.
        params_c = precision.cast_compute(ts.params)
        ts, traj, bootstrap, init_carry = collect_rollout(
            model, env, ts, unroll, num_agents, params=params_c)
        returns = discounted_returns(traj.reward, traj.active,
                                     bootstrap, cfg.gamma)
        weight = traj.active
        denom = jnp.maximum(jnp.sum(weight), 1.0)

        def loss_fn(params):
            logits, values, aux = replay_forward(
                model, params, traj, init_carry, remat=cfg.remat)
            log_probs = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                log_probs, traj.action[..., None], axis=-1)[..., 0]
            adv = jax.lax.stop_gradient(returns - values) * weight
            if cfg.normalize_advantages:
                adv = normalize_advantages_masked(adv, weight, denom)
            policy_loss = -jnp.sum(logp * adv) / denom
            value_loss = jnp.sum(jnp.square(values - returns) * weight) / denom
            entropy = -jnp.sum(
                jnp.sum(jnp.exp(log_probs) * log_probs, axis=-1) * weight
            ) / denom
            total = (policy_loss + cfg.value_coef * value_loss
                     - cfg.entropy_coef * entropy + cfg.aux_loss_coef * aux)
            return total, (policy_loss, value_loss, entropy)

        (loss, (policy_loss, value_loss, entropy)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_c)
        params, opt_state = apply_update(grads, ts.opt_state, ts.params)
        ts = ts.replace(params=params, opt_state=opt_state,
                        updates=ts.updates + 1)
        metrics = {
            "loss": loss,
            "policy_loss": policy_loss,
            "value_loss": value_loss,
            "entropy": entropy,
            "reward_sum": jnp.sum(traj.reward),
            "env_steps": ts.env_steps,
            "updates": ts.updates,
            **portfolio_metrics(env, ts.env_state),
        }
        return ts, metrics

    return Agent(name="a2c", init=init, step=step,
                 num_agents=num_agents, steps_per_chunk=unroll, model=model)
