"""On-policy rollout collection shared by PG / A2C / PPO.

One ``lax.scan`` gathers a ``(T, B, ...)`` trajectory block for the whole
agent batch — the TPU inversion of the reference's per-step worker↔learner
mailbox round-trips (SURVEY.md §7.2). Losses recompute the forward pass from
the stored observations (and the unroll's *initial* recurrent carry, so
recurrent policies differentiate through time correctly).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import (
    TrainState, election_health, quarantine_mask)
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model, apply_batched


class StepData(NamedTuple):
    """One time-slice of a trajectory, batched over agents."""

    obs: jax.Array      # (B, obs_dim)
    action: jax.Array   # (B,) i32
    logp: jax.Array     # (B,) log-prob of the sampled action (behavior policy)
    value: jax.Array    # (B,) critic estimate at obs
    reward: jax.Array   # (B,)
    active: jax.Array   # (B,) f32 1.0 while the episode is running


def supports_precomputed_trunk(model: Model, env: TradingEnv) -> bool:
    """THE dispatch predicate for the precomputed-rollout fast path, shared
    by training (collect_rollout) and greedy eval (Orchestrator.evaluate).
    The path hard-codes the single-asset trading layout — obs =
    [window | budget, shares] with a SCALAR wallet and a priced step — so a
    trunk-capable model alone is not enough: a one-asset portfolio env has
    num_assets == 1 but a (1,)-vector shares leaf (env/portfolio.py), which
    only the ``step_priced is not None`` check (set solely by
    make_trading_env) excludes."""
    return (model.apply_rollout_trunk is not None
            and env.num_assets == 1 and env.step_priced is not None)


def collect_rollout(model: Model, env: TradingEnv,
                    ts: TrainState, unroll_len: int, num_agents: int,
                    params=None):
    """Roll the policy forward ``unroll_len`` steps.

    Returns ``(new_ts, traj, bootstrap_value, init_carry)`` where ``traj``
    stacks :class:`StepData` along a leading time axis, ``bootstrap_value`` is
    V(s_T) for return bootstrapping, and ``init_carry`` is the recurrent state
    the unroll started from (needed to replay the forward pass in losses).

    ``params`` overrides the weights the rollout forwards read — the
    precision policy's compute copy (precision.py cast_compute); the fp32
    masters in ``ts.params`` are never mutated here and the returned
    ``new_ts`` keeps them. None (the fp32 path) reads ``ts.params``.

    Models exposing the precomputed-rollout pair (``apply_rollout_trunk`` /
    ``apply_rollout_head``, models/core.py) take the parallel-trunk path:
    the unroll's entire trunk runs as ONE pass up front and the sequential
    env loop applies only the tiny state-dependent head per step.
    """
    # Envs outside the single-asset trading layout would be fed malformed
    # observations by the fast path; they use the generic per-step loop.
    if supports_precomputed_trunk(model, env):
        return _collect_rollout_precomputed(
            model, env, ts, unroll_len, num_agents, params=params)
    params = ts.params if params is None else params
    horizon = env.num_steps
    init_carry = ts.carry

    def one_step(carry, _):
        env_state, model_carry, rng = carry
        rng, k_act = jax.random.split(rng)
        act_keys = jax.random.split(k_act, num_agents)

        # Horizon freeze + poisoned-row quarantine (base.quarantine_mask):
        # a non-finite agent's observation is sanitized to zeros (so no NaN
        # reaches the shared forward/loss) and its row is masked inactive —
        # frozen until the orchestrator respawns it.
        obs_raw = jax.vmap(env.observe)(env_state)
        healthy = quarantine_mask(obs_raw, env_state)
        active = ((env_state.t < horizon) & healthy).astype(jnp.float32)
        obs = jnp.where(healthy[:, None], obs_raw, 0.0)
        outs, new_model_carry = apply_batched(model, params, obs, model_carry)
        actions = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(act_keys, outs.logits)
        actions = actions.astype(jnp.int32)
        logp = jax.vmap(
            lambda lg, a: jax.nn.log_softmax(lg)[a])(outs.logits, actions)

        stepped, rewards = jax.vmap(env.step)(env_state, actions)
        mask = active.astype(bool)
        new_env = jax.tree.map(
            lambda new, old: jnp.where(
                mask.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
            stepped, env_state)
        # where() not *: a quarantined row's reward is NaN, and NaN*0 = NaN.
        rewards = jnp.where(mask, rewards, 0.0)

        data = StepData(obs=obs, action=actions, logp=logp,
                        value=outs.value, reward=rewards, active=active)
        return (new_env, new_model_carry, rng), data

    (env_state, model_carry, rng), traj = jax.lax.scan(
        one_step, (ts.env_state, ts.carry, ts.rng), None, length=unroll_len)

    # Bootstrap value for the state the unroll stopped at.
    final_raw = jax.vmap(env.observe)(env_state)
    final_fine = quarantine_mask(final_raw, env_state)
    final_obs = jnp.where(final_fine[:, None], final_raw, 0.0)
    final_outs, _ = apply_batched(model, params, final_obs, model_carry)
    bootstrap = final_outs.value * (
        (env_state.t < horizon) & final_fine).astype(jnp.float32)

    # Count steps where ANY agent advanced (not just agent 0): with
    # per-agent healing, cursors can diverge — a respawned agent keeps
    # running after the rest finish, and its chunks must count.
    steps_taken = jnp.sum(jnp.any(traj.active > 0, axis=1)).astype(jnp.int32)
    new_ts = ts.replace(env_state=env_state, carry=model_carry, rng=rng,
                        env_steps=ts.env_steps + steps_taken)
    return new_ts, traj, bootstrap, init_carry


def _trunk_precompute(model: Model, env: TradingEnv, params, state1, carry1,
                      t_len: int, horizon: int):
    """Shared single-representative-agent precompute for trunk rollouts
    (training and greedy eval): the load-bearing alignment invariant —
    trade price at step i = the newest tick of window i+1, fed to BOTH the
    trunk's future_ticks and the priced env step — lives here once.

    ``state1``/``carry1`` are batch-of-1 pytrees. Returns
    ``(windows (T+1, W), trade_prices (T,), hn_base (T+1, d), carry_out)``.
    """
    window = model.obs_dim - 2

    def window_at(i):
        shifted = state1.replace(t=jnp.minimum(state1.t + i, horizon))
        return jax.vmap(env.observe)(shifted)[0, :window]

    windows = jax.vmap(window_at)(jnp.arange(t_len + 1))       # (T+1, W)
    obs1_raw = jax.vmap(env.observe)(state1)
    # Sanitize ONLY the wallet features: the price window comes from the
    # static series (always finite) and is all the trunk reads — zeroing
    # the whole row when agent 0's wallet is poisoned would corrupt the
    # SHARED trunk for every healthy agent.
    obs1 = jnp.concatenate(
        [obs1_raw[:, :window],
         jnp.where(jnp.isfinite(obs1_raw[:, window:]),
                   obs1_raw[:, window:], 0.0)], axis=-1)
    hn1, carry_out = model.apply_rollout_trunk(
        params, obs1, windows[None, 1:, -1], carry1)
    return windows, windows[1:, -1], hn1[0], carry_out


def _collect_rollout_precomputed(model: Model, env: TradingEnv,
                                 ts: TrainState, unroll_len: int,
                                 num_agents: int, params=None):
    """Rollout with the heavy trunk hoisted OUT of the sequential loop.

    The trading env's prices are action-independent (actions move only
    budget/shares; the cursor advances one tick per step regardless), so
    the tick that enters the observation window at each future step is
    known before any action is taken. The model's trunk — everything up to
    the portfolio-feature injection — therefore computes for the WHOLE
    unroll in one parallel banded pass (``apply_rollout_trunk``); the
    sequential ``lax.scan`` keeps only action sampling, the env transition,
    and the (B, d)-sized head (``apply_rollout_head``). This removes the
    measured 70%-of-chunk sequential cache-attention rollout
    (benchmarks/profile_flagship.py).

    Agents frozen mid-unroll (horizon reached, or quarantined by
    ``quarantine_mask``) read trunk rows computed for cursors they never
    reached; their outputs are masked inactive exactly as the incremental
    path masked its lockstep-advanced carry.
    """
    params = ts.params if params is None else params
    horizon = env.num_steps
    init_carry = ts.carry
    window = model.obs_dim - 2

    # ---- bulk precompute (everything scalar-unit-hostile hoisted out of
    # the scan: a vmapped dynamic gather costs ~75-230 us PER ITERATION on
    # TPU and a threefry split ~120 us, vs ~0.1 us for elementwise math;
    # as single ops out here they cost milliseconds total) ---------------
    #
    # Agent-invariance: every HEALTHY agent replays the SAME price series
    # in LOCKSTEP (batched_reset broadcasts one reset state, and any
    # per-agent respawn must keep healthy rows lockstep —
    # orchestrator._heal_agents), so the price windows AND the whole trunk
    # are computed for ONE representative agent and broadcast — the trunk's
    # cost and the window gather drop by a factor of B. The representative
    # must be a healthy row BY THE SAME PREDICATE the heal uses
    # (election_health: env state AND model carry finite): a quarantined
    # row's cursor freezes while the broadcast carry['t'] keeps advancing,
    # so electing it would feed every healthy agent windows from a stale
    # cursor with desynced RoPE positions — and a finite-wallet row with a
    # NaN carry would broadcast the NaN K/V cache into the shared trunk.
    # argmax picks the first healthy row. Fallback when NONE exists: row 0.
    # If every row failed on env state, all rows are also quarantine-masked
    # and the chunk is a masked no-op; if every row failed only on its
    # carry, the broadcast NaN trunk makes the chunk's loss non-finite and
    # the orchestrator's detector escalates to restore — correct when the
    # whole batch is beyond a row-level heal.
    rep = jnp.argmax(election_health(ts.env_state, ts.carry)).astype(jnp.int32)
    take_rep = lambda x: jax.lax.dynamic_index_in_dim(x, rep, 0,
                                                      keepdims=True)
    state1 = jax.tree.map(take_rep, ts.env_state)
    carry1 = jax.tree.map(take_rep, ts.carry)
    windows, trade_prices, hn_base, carry1_out = _trunk_precompute(
        model, env, params, state1, carry1, unroll_len, horizon)
    new_model_carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (num_agents,) + x.shape[1:]),
        carry1_out)

    rng, k_noise = jax.random.split(ts.rng)
    # Gumbel-max sampling noise for the whole unroll: argmax(logits + g)
    # IS a categorical draw, with zero in-loop RNG traffic.
    gumbel = jax.random.gumbel(
        k_noise, (unroll_len, num_agents, model.num_actions), jnp.float32)

    step_priced = env.step_priced

    # Linearity-factored head (models/core.py rollout_head_factored): the
    # whole unroll's trunk→logits/value terms become ONE batched matmul
    # out here, leaving only a (3 -> A) portfolio contraction inside the
    # scan — the per-iteration d-sized head GEMMs were the measured d=256
    # bound once everything else was hoisted (BASELINE.md round 5).
    factored = model.rollout_head_factored
    if factored is not None:
        base_l, base_v, pf_fn = factored(params, hn_base)
        head_xs = (base_l[:unroll_len], base_v[:unroll_len])

        def head_outs(head_i, obs):
            base_l_i, base_v_i = head_i
            d_l, d_v = pf_fn(obs)
            return base_l_i[None] + d_l, base_v_i + d_v

        final_head = (base_l[unroll_len], base_v[unroll_len])
    else:
        head_xs = (hn_base[:unroll_len],)

        def head_outs(head_i, obs):
            (hn_i,) = head_i
            outs = model.apply_rollout_head(
                params,
                jnp.broadcast_to(hn_i, (num_agents,) + hn_i.shape), obs)
            return outs.logits, outs.value

        final_head = (hn_base[unroll_len],)

    def one_step(env_state, inputs):
        win_i, price_i, g_i, head_i = inputs
        # Assemble the observation from the precomputed (shared) window +
        # the live wallet (the only state-dependent features).
        obs_raw = jnp.concatenate(
            [jnp.broadcast_to(win_i, (num_agents, window)),
             env_state.budget[:, None], env_state.shares[:, None]],
            axis=-1)
        healthy = quarantine_mask(obs_raw, env_state)
        active = ((env_state.t < horizon) & healthy).astype(jnp.float32)
        obs = jnp.where(healthy[:, None], obs_raw, 0.0)

        logits, value = head_outs(head_i, obs)
        actions = jnp.argmax(logits + g_i, axis=-1).astype(jnp.int32)
        log_probs = jax.nn.log_softmax(logits)
        # one_hot contraction, not take_along_axis: gathers are scalar-unit
        # dispatches inside a scan.
        logp = jnp.sum(
            log_probs * jax.nn.one_hot(actions, log_probs.shape[-1]), axis=-1)

        # step_priced is guaranteed by supports_precomputed_trunk.
        stepped, rewards = jax.vmap(
            step_priced, in_axes=(0, 0, None))(env_state, actions, price_i)
        mask = active.astype(bool)
        new_env = jax.tree.map(
            lambda new, old: jnp.where(
                mask.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
            stepped, env_state)
        rewards = jnp.where(mask, rewards, 0.0)

        data = StepData(obs=obs, action=actions, logp=logp,
                        value=value, reward=rewards, active=active)
        return new_env, data

    env_state, traj = jax.lax.scan(
        one_step, ts.env_state,
        (windows[:-1], trade_prices, gumbel, head_xs))

    final_raw = jax.vmap(env.observe)(env_state)
    final_fine = quarantine_mask(final_raw, env_state)
    final_obs = jnp.where(final_fine[:, None], final_raw, 0.0)
    _, final_value = head_outs(final_head, final_obs)
    bootstrap = final_value * (
        (env_state.t < horizon) & final_fine).astype(jnp.float32)

    steps_taken = jnp.sum(jnp.any(traj.active > 0, axis=1)).astype(jnp.int32)
    new_ts = ts.replace(env_state=env_state, carry=new_model_carry, rng=rng,
                        env_steps=ts.env_steps + steps_taken)
    return new_ts, traj, bootstrap, init_carry


def greedy_rollout_precomputed(model: Model, env: TradingEnv, params,
                               *, horizon: int | None = None):
    """Greedy (argmax) single-agent episode replay through the precomputed
    trunk — the fast ``evaluate()`` path for trunk models. Same structure
    as :func:`_collect_rollout_precomputed` (prices are action-independent,
    so the whole episode's trunk is one banded pass) minus sampling,
    batching, and quarantine. Returns ``(final_env_state, rewards (T,))``.
    """
    horizon = env.num_steps if horizon is None else horizon
    state1 = jax.tree.map(lambda x: x[None], env.reset())   # batch of 1
    carry1 = jax.tree.map(lambda x: x[None], model.init_carry())
    windows, trade_prices, hn_base, _ = _trunk_precompute(
        model, env, params, state1, carry1, horizon, horizon)
    step_priced = env.step_priced

    factored = model.rollout_head_factored
    if factored is not None:   # same hoist as _collect_rollout_precomputed
        base_l, _, pf_fn = factored(params, hn_base)
        head_xs = (base_l[:horizon],)

        def head_logits(head_i, obs):
            return head_i[0][None] + pf_fn(obs)[0]
    else:
        head_xs = (hn_base[:horizon],)

        def head_logits(head_i, obs):
            return model.apply_rollout_head(params, head_i[0][None],
                                            obs).logits

    def one(env_state, inputs):
        win_i, price_i, head_i = inputs
        obs = jnp.concatenate(
            [win_i[None], env_state.budget[:, None],
             env_state.shares[:, None]], axis=-1)
        logits = head_logits(head_i, obs)
        action = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_state, reward = jax.vmap(
            step_priced, in_axes=(0, 0, None))(env_state, action, price_i)
        return new_state, reward[0]

    final, rewards = jax.lax.scan(
        one, state1, (windows[:-1], trade_prices, head_xs))
    return jax.tree.map(lambda x: x[0], final), rewards


#: Max observation rows per folded forward call — bounds replay activation
#: memory (4096 seq-202 transformer rows ≈ 0.8 GB per bf16 activation
#: tensor; larger folds trade HBM headroom for no extra MXU win).
_MAX_FOLD_ROWS = 2048


def replay_forward(model: Model, params: Any, traj: StepData, init_carry,
                   *, remat: bool = False):
    """Recompute ``(logits, values, aux)`` along a stored trajectory under
    ``params``, threading the recurrent carry — the differentiable forward
    for losses. ``aux`` is the mean of the model's auxiliary loss over the
    replay (ModelOut.aux — the MoE balance term; 0 for dense models), which
    losses weight by ``LearnerConfig.aux_loss_coef``.

    Stateless models (MLP, transformer — empty carry) have no step-to-step
    data dependence, so the (T, B) trajectory folds into one big batch
    instead of a T-step scan of B-row launches: a 10-agent/32-step PPO
    replay becomes a single 320-sequence forward that actually loads the
    MXU (the scan form was the round-2 transformer-throughput bottleneck).
    The fold is BATCH-major — (T, B) transposes to (B, T) before merging —
    so a dp-sharded agent axis stays the leading factor of the merged dim
    and GSPMD keeps the shard layout (a time-major merge would force an
    all-gather of the folded observations on every minibatch).

    Folding is sliced to ``_MAX_FOLD_ROWS`` rows per call, which bounds the
    per-call transient working set (qkv/attention intermediates). Note the
    forward RESIDUALS of every slice still accumulate for the backward
    unless ``remat=True``, which checkpoints each slice so the backward
    recomputes from stored observations — the FLOPs-for-HBM trade that
    makes large agent batches fit.
    """
    if model.apply_unroll_shared is not None:
        # Shared-trunk replay: the banded pass runs ONCE for a
        # representative row and only the portfolio head runs per agent —
        # valid because every learner in this framework keeps the agent
        # batch lockstep over one shared price series (models/core.py
        # apply_unroll_shared; the factor-B update-phase redundancy).
        fwd = model.apply_unroll_shared
        if remat:
            fwd = jax.checkpoint(fwd)
        return fwd(params, traj.obs, init_carry)
    if model.apply_unroll is not None:
        # The model replays a whole trajectory natively (episode-mode
        # transformer: one banded pass over the unroll's tick sequence
        # instead of T window forwards).
        fwd = model.apply_unroll
        if remat:
            fwd = jax.checkpoint(fwd)
        return fwd(params, traj.obs, init_carry)

    stateless = not jax.tree.leaves(init_carry)
    if stateless:
        t, b = traj.obs.shape[:2]
        # Largest divisor of T whose folded rows stay under the cap.
        fold = max(f for f in range(1, t + 1)
                   if t % f == 0 and (f * b <= _MAX_FOLD_ROWS or f == 1))
        groups = t // fold

        def fwd(params, obs_g):
            # (fold, b, D) -> (b, fold, D) -> (b*fold, D): batch-major merge.
            flat = obs_g.swapaxes(0, 1).reshape(
                (b * fold,) + obs_g.shape[2:])
            outs, _ = apply_batched(model, params, flat, init_carry)
            return (outs.logits.reshape(b, fold, -1).swapaxes(0, 1),
                    outs.value.reshape(b, fold).swapaxes(0, 1),
                    jnp.mean(jnp.asarray(outs.aux)))

        if remat:
            fwd = jax.checkpoint(fwd)
        if groups == 1:
            return fwd(params, traj.obs)
        grouped = traj.obs.reshape((groups, fold) + traj.obs.shape[1:])
        _, (logits, values, aux) = jax.lax.scan(
            lambda _, obs_g: (None, fwd(params, obs_g)), None, grouped)
        return (logits.reshape((t,) + logits.shape[2:]),
                values.reshape((t,) + values.shape[2:]),
                jnp.mean(aux))

    def fwd(params, obs_t, model_carry):
        return apply_batched(model, params, obs_t, model_carry)

    if remat:
        fwd = jax.checkpoint(fwd)

    def one_step(model_carry, obs_t):
        outs, new_carry = fwd(params, obs_t, model_carry)
        return new_carry, (outs.logits, outs.value,
                           jnp.mean(jnp.asarray(outs.aux)))

    _, (logits, values, aux) = jax.lax.scan(one_step, init_carry, traj.obs)
    return logits, values, jnp.mean(aux)  # (T, B, A), (T, B), scalar


def normalize_advantages_masked(adv: jax.Array, weight: jax.Array,
                                denom: jax.Array) -> jax.Array:
    """Zero-mean unit-variance advantages over the ACTIVE steps, re-masked —
    THE normalization every policy-gradient learner shares (PPO always, PG/
    A2C via ``learner.normalize_advantages``), so the epsilon and masking
    convention cannot drift between estimators. ``weight`` is the binary
    active mask; ``denom`` its (clamped) sum. Idempotent under the losses'
    own later ``* weight`` factors."""
    mean = jnp.sum(adv * weight) / denom
    var = jnp.sum(jnp.square(adv - mean) * weight) / denom
    return (adv - mean) * jax.lax.rsqrt(var + 1e-8) * weight


def discounted_returns(rewards: jax.Array, active: jax.Array,
                       bootstrap: jax.Array, gamma: float) -> jax.Array:
    """Returns-to-go R_t = r_t + γ R_{t+1}, seeded with the bootstrap value;
    computed as a reverse scan over the time axis. Shapes (T, B)."""

    def backward(r_next, inputs):
        reward, live = inputs
        r = reward + gamma * r_next * live
        return r, r

    _, returns = jax.lax.scan(backward, bootstrap,
                              (rewards, active), reverse=True)
    return returns


def gae_advantages(rewards, values, active, bootstrap, gamma, lam):
    """Generalized Advantage Estimation over (T, B) arrays.

    Bootstrapping is gated on the NEXT step's liveness: at an episode's last
    real step the terminal state's value must not leak into delta or flow back
    through the gamma*lam recursion — the same masking collect_rollout applies
    to its bootstrap value. (Gating on the step-start flag let the frozen
    terminal value into both terms, a net +gamma*(1-lam)*V_terminal bias on
    the final real step's advantage.)
    """
    next_values = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
    # Liveness of the successor state. The final slice uses 1: its successor
    # value is `bootstrap`, which collect_rollout already zero-masks when the
    # episode has ended.
    next_active = jnp.concatenate(
        [active[1:], jnp.ones_like(bootstrap)[None]], axis=0)

    def backward(adv_next, inputs):
        reward, value, next_value, live_next = inputs
        delta = reward + gamma * next_value * live_next - value
        adv = delta + gamma * lam * adv_next * live_next
        return adv, adv

    _, advantages = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap),
        (rewards, values, next_values, next_active), reverse=True)
    return advantages
