"""On-policy rollout collection shared by PG / A2C / PPO.

One ``lax.scan`` gathers a ``(T, B, ...)`` trajectory block for the whole
agent batch — the TPU inversion of the reference's per-step worker↔learner
mailbox round-trips (SURVEY.md §7.2). Losses recompute the forward pass from
the stored observations (and the unroll's *initial* recurrent carry, so
recurrent policies differentiate through time correctly).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from sharetrade_tpu.agents.base import TrainState
from sharetrade_tpu.env.core import TradingEnv
from sharetrade_tpu.models.core import Model, apply_batched


class StepData(NamedTuple):
    """One time-slice of a trajectory, batched over agents."""

    obs: jax.Array      # (B, obs_dim)
    action: jax.Array   # (B,) i32
    logp: jax.Array     # (B,) log-prob of the sampled action (behavior policy)
    value: jax.Array    # (B,) critic estimate at obs
    reward: jax.Array   # (B,)
    active: jax.Array   # (B,) f32 1.0 while the episode is running


def collect_rollout(model: Model, env: TradingEnv,
                    ts: TrainState, unroll_len: int, num_agents: int):
    """Roll the policy forward ``unroll_len`` steps.

    Returns ``(new_ts, traj, bootstrap_value, init_carry)`` where ``traj``
    stacks :class:`StepData` along a leading time axis, ``bootstrap_value`` is
    V(s_T) for return bootstrapping, and ``init_carry`` is the recurrent state
    the unroll started from (needed to replay the forward pass in losses).
    """
    horizon = env.num_steps
    init_carry = ts.carry

    def one_step(carry, _):
        env_state, model_carry, rng = carry
        rng, k_act = jax.random.split(rng)
        act_keys = jax.random.split(k_act, num_agents)

        active = (env_state.t < horizon).astype(jnp.float32)
        obs = jax.vmap(env.observe)(env_state)
        outs, new_model_carry = apply_batched(model, ts.params, obs, model_carry)
        actions = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(act_keys, outs.logits)
        actions = actions.astype(jnp.int32)
        logp = jax.vmap(
            lambda lg, a: jax.nn.log_softmax(lg)[a])(outs.logits, actions)

        stepped, rewards = jax.vmap(env.step)(env_state, actions)
        mask = active.astype(bool)
        new_env = jax.tree.map(
            lambda new, old: jnp.where(
                mask.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
            stepped, env_state)
        rewards = rewards * active

        data = StepData(obs=obs, action=actions, logp=logp,
                        value=outs.value, reward=rewards, active=active)
        return (new_env, new_model_carry, rng), data

    (env_state, model_carry, rng), traj = jax.lax.scan(
        one_step, (ts.env_state, ts.carry, ts.rng), None, length=unroll_len)

    # Bootstrap value for the state the unroll stopped at.
    final_obs = jax.vmap(env.observe)(env_state)
    final_outs, _ = apply_batched(model, ts.params, final_obs, model_carry)
    bootstrap = final_outs.value * (env_state.t < horizon).astype(jnp.float32)

    steps_taken = jnp.sum(traj.active[:, 0] > 0).astype(jnp.int32)
    new_ts = ts.replace(env_state=env_state, carry=model_carry, rng=rng,
                        env_steps=ts.env_steps + steps_taken)
    return new_ts, traj, bootstrap, init_carry


#: Max observation rows per folded forward call — bounds replay activation
#: memory (4096 seq-202 transformer rows ≈ 0.8 GB per bf16 activation
#: tensor; larger folds trade HBM headroom for no extra MXU win).
_MAX_FOLD_ROWS = 2048


def replay_forward(model: Model, params: Any, traj: StepData, init_carry,
                   *, remat: bool = False):
    """Recompute ``(logits, values, aux)`` along a stored trajectory under
    ``params``, threading the recurrent carry — the differentiable forward
    for losses. ``aux`` is the mean of the model's auxiliary loss over the
    replay (ModelOut.aux — the MoE balance term; 0 for dense models), which
    losses weight by ``LearnerConfig.aux_loss_coef``.

    Stateless models (MLP, transformer — empty carry) have no step-to-step
    data dependence, so the (T, B) trajectory folds into one big batch
    instead of a T-step scan of B-row launches: a 10-agent/32-step PPO
    replay becomes a single 320-sequence forward that actually loads the
    MXU (the scan form was the round-2 transformer-throughput bottleneck).
    The fold is BATCH-major — (T, B) transposes to (B, T) before merging —
    so a dp-sharded agent axis stays the leading factor of the merged dim
    and GSPMD keeps the shard layout (a time-major merge would force an
    all-gather of the folded observations on every minibatch).

    Folding is sliced to ``_MAX_FOLD_ROWS`` rows per call, which bounds the
    per-call transient working set (qkv/attention intermediates). Note the
    forward RESIDUALS of every slice still accumulate for the backward
    unless ``remat=True``, which checkpoints each slice so the backward
    recomputes from stored observations — the FLOPs-for-HBM trade that
    makes large agent batches fit.
    """
    if model.apply_unroll is not None:
        # The model replays a whole trajectory natively (episode-mode
        # transformer: one banded pass over the unroll's tick sequence
        # instead of T window forwards).
        fwd = model.apply_unroll
        if remat:
            fwd = jax.checkpoint(fwd)
        return fwd(params, traj.obs, init_carry)

    stateless = not jax.tree.leaves(init_carry)
    if stateless:
        t, b = traj.obs.shape[:2]
        # Largest divisor of T whose folded rows stay under the cap.
        fold = max(f for f in range(1, t + 1)
                   if t % f == 0 and (f * b <= _MAX_FOLD_ROWS or f == 1))
        groups = t // fold

        def fwd(params, obs_g):
            # (fold, b, D) -> (b, fold, D) -> (b*fold, D): batch-major merge.
            flat = obs_g.swapaxes(0, 1).reshape(
                (b * fold,) + obs_g.shape[2:])
            outs, _ = apply_batched(model, params, flat, init_carry)
            return (outs.logits.reshape(b, fold, -1).swapaxes(0, 1),
                    outs.value.reshape(b, fold).swapaxes(0, 1),
                    jnp.mean(jnp.asarray(outs.aux)))

        if remat:
            fwd = jax.checkpoint(fwd)
        if groups == 1:
            return fwd(params, traj.obs)
        grouped = traj.obs.reshape((groups, fold) + traj.obs.shape[1:])
        _, (logits, values, aux) = jax.lax.scan(
            lambda _, obs_g: (None, fwd(params, obs_g)), None, grouped)
        return (logits.reshape((t,) + logits.shape[2:]),
                values.reshape((t,) + values.shape[2:]),
                jnp.mean(aux))

    def fwd(params, obs_t, model_carry):
        return apply_batched(model, params, obs_t, model_carry)

    if remat:
        fwd = jax.checkpoint(fwd)

    def one_step(model_carry, obs_t):
        outs, new_carry = fwd(params, obs_t, model_carry)
        return new_carry, (outs.logits, outs.value,
                           jnp.mean(jnp.asarray(outs.aux)))

    _, (logits, values, aux) = jax.lax.scan(one_step, init_carry, traj.obs)
    return logits, values, jnp.mean(aux)  # (T, B, A), (T, B), scalar


def discounted_returns(rewards: jax.Array, active: jax.Array,
                       bootstrap: jax.Array, gamma: float) -> jax.Array:
    """Returns-to-go R_t = r_t + γ R_{t+1}, seeded with the bootstrap value;
    computed as a reverse scan over the time axis. Shapes (T, B)."""

    def backward(r_next, inputs):
        reward, live = inputs
        r = reward + gamma * r_next * live
        return r, r

    _, returns = jax.lax.scan(backward, bootstrap,
                              (rewards, active), reverse=True)
    return returns


def gae_advantages(rewards, values, active, bootstrap, gamma, lam):
    """Generalized Advantage Estimation over (T, B) arrays.

    Bootstrapping is gated on the NEXT step's liveness: at an episode's last
    real step the terminal state's value must not leak into delta or flow back
    through the gamma*lam recursion — the same masking collect_rollout applies
    to its bootstrap value. (Gating on the step-start flag let the frozen
    terminal value into both terms, a net +gamma*(1-lam)*V_terminal bias on
    the final real step's advantage.)
    """
    next_values = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
    # Liveness of the successor state. The final slice uses 1: its successor
    # value is `bootstrap`, which collect_rollout already zero-masks when the
    # episode has ended.
    next_active = jnp.concatenate(
        [active[1:], jnp.ones_like(bootstrap)[None]], axis=0)

    def backward(adv_next, inputs):
        reward, value, next_value, live_next = inputs
        delta = reward + gamma * next_value * live_next - value
        adv = delta + gamma * lam * adv_next * live_next
        return adv, adv

    _, advantages = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap),
        (rewards, values, next_values, next_active), reverse=True)
    return advantages
