"""L3: the trading environment, as pure JAX functions.

Reference: the episode fold in ``TrainerChildActor.scala:82-146``. Here the
fold body becomes a pure ``step`` usable under ``vmap`` (agent batches) and
``lax.scan`` (the time axis) inside one compiled program.
"""

from sharetrade_tpu.env.core import TradingEnv  # noqa: F401
from sharetrade_tpu.env.portfolio import PortfolioState, make_portfolio_env  # noqa: F401
from sharetrade_tpu.env.trading import (  # noqa: F401
    BUY,
    HOLD,
    NUM_ACTIONS,
    SELL,
    EnvParams,
    EnvState,
    env_from_prices,
    make_trading_env,
    num_steps,
    observe,
    portfolio_value,
    reset,
    step,
)
