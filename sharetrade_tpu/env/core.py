"""Environment interface: what every trading environment exposes to agents.

The reference hard-wires one environment shape (single stock, fold loop in
TrainerChildActor.scala). Generalizing to an explicit bundle of pure
functions lets the same learners drive single-asset and multi-asset
portfolio environments unchanged — the functions close over the (static)
price data, so under jit they compile to constants exactly like the original
module-level functions did.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax


@dataclass(frozen=True)
class TradingEnv:
    """A trading environment as pure functions + static shape facts."""

    reset: Callable[[], Any]                 # () -> EnvState
    observe: Callable[[Any], jax.Array]      # state -> (obs_dim,)
    step: Callable[[Any, jax.Array], tuple[Any, jax.Array]]  # (state, action)
    portfolio_value: Callable[[Any], jax.Array]
    num_steps: int                           # episode horizon
    obs_dim: int
    num_actions: int
    num_assets: int = 1
    # Optional price-injected step: same transition arithmetic as ``step``
    # but with the trade price passed in instead of gathered from the series
    # by cursor. Rollout fast paths that PRECOMPUTE all price windows for an
    # unroll use this to keep per-agent gathers out of the sequential scan
    # (a vmapped dynamic gather costs ~75 us per scan iteration on TPU —
    # scalar-unit dispatch — vs ~0.1 us for the same arithmetic).
    step_priced: Callable[[Any, jax.Array, jax.Array],
                          tuple[Any, jax.Array]] | None = None
