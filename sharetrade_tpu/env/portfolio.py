"""Multi-asset portfolio trading environment.

The forward-looking generalization of the single-stock env (BASELINE.json
config 4: "PPO multi-asset portfolio"): A assets trade simultaneously
against one shared budget. Degenerates exactly to the single-asset
semantics (env/trading.py, itself modeled on TrainerChildActor.scala:82-146)
at A=1 — tested in tests/test_portfolio.py.

- Observation: the A price windows concatenated (A × window floats), then
  budget, then the A share counts — obs_dim = A·window + 1 + A. At A=1 this
  is the reference's 203-float layout (window ++ budget ++ shares).
- Actions: ``2A+1`` discrete choices — ``a``∈[0,A): Buy one share of asset
  a; ``a``∈[A,2A): Sell one share of asset a−A; ``2A``: Hold. At A=1 the
  order is (Buy, Sell, Hold), the reference's action indexing
  (QDecisionPolicyActor.scala:17).
- Feasibility and reward follow the single-asset rules per traded asset:
  Buy iff budget covers that asset's price, Sell iff shares held;
  reward = portfolio delta with last-trade-price marking (seeded 0).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from sharetrade_tpu.env.core import TradingEnv


@struct.dataclass
class PortfolioState:
    t: jax.Array            # i32 step cursor
    budget: jax.Array       # f32 shared cash
    shares: jax.Array       # (A,) f32 holdings
    share_value: jax.Array  # (A,) f32 last trade prices (0 before first mark)


def make_portfolio_env(prices, window: int = 201,
                       initial_budget: float = 2400.0,
                       initial_shares=None) -> TradingEnv:
    """Build a multi-asset env from ``prices`` of shape (A, T) (or (T,) for
    a single asset)."""
    prices = jnp.asarray(prices, jnp.float32)
    if prices.ndim == 1:
        prices = prices[None, :]
    if prices.ndim != 2:
        raise ValueError(f"prices must be (A, T), got {prices.shape}")
    num_assets, total = int(prices.shape[0]), int(prices.shape[1])
    if total <= window:
        # Matches trading.env_from_prices: window + 1 prices = 1-step episode.
        raise ValueError(
            f"price count ({total}) must exceed the window ({window})")
    if initial_shares is None:
        initial_shares = jnp.zeros((num_assets,), jnp.float32)
    else:
        initial_shares = jnp.broadcast_to(
            jnp.asarray(initial_shares, jnp.float32), (num_assets,))
    budget0 = jnp.float32(initial_budget)

    num_actions = 2 * num_assets + 1
    obs_dim = num_assets * window + 1 + num_assets

    def reset() -> PortfolioState:
        return PortfolioState(
            t=jnp.int32(0), budget=budget0,
            shares=initial_shares,
            share_value=jnp.zeros((num_assets,), jnp.float32))

    def observe(state: PortfolioState) -> jax.Array:
        windows = jax.lax.dynamic_slice(
            prices, (0, state.t), (num_assets, window))     # (A, window)
        return jnp.concatenate(
            [windows.reshape(-1), state.budget[None], state.shares])

    def portfolio_value(state: PortfolioState) -> jax.Array:
        return state.budget + jnp.sum(state.shares * state.share_value)

    def step(state: PortfolioState, action: jax.Array):
        trade_prices = prices[:, state.t + window]           # (A,)

        is_buy = action < num_assets
        is_sell = (action >= num_assets) & (action < 2 * num_assets)
        asset = jnp.where(is_buy, action,
                          jnp.where(is_sell, action - num_assets, 0))
        onehot = jax.nn.one_hot(asset, num_assets, dtype=jnp.float32)
        price_a = trade_prices[asset]

        can_buy = is_buy & (state.budget >= price_a)
        can_sell = is_sell & (state.shares[asset] > 0)
        delta = jnp.where(can_buy, 1.0, jnp.where(can_sell, -1.0, 0.0))

        new_budget = state.budget - delta * price_a
        new_shares = state.shares + delta * onehot

        current = portfolio_value(state)
        new_portfolio = new_budget + jnp.sum(new_shares * trade_prices)
        reward = new_portfolio - current

        new_state = PortfolioState(
            t=state.t + 1, budget=new_budget, shares=new_shares,
            share_value=trade_prices)
        return new_state, reward

    return TradingEnv(
        reset=reset, observe=observe, step=step,
        portfolio_value=portfolio_value,
        num_steps=total - window, obs_dim=obs_dim,
        num_actions=num_actions, num_assets=num_assets)
