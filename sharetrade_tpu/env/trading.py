"""Windowed share-trading environment as pure JAX functions.

Reference semantics (TrainerChildActor.scala:82-146):

- Observation at step ``i``: the 201-price sliding window ``prices[i .. i+200]``
  concatenated with ``(budget, shares)`` — 203 floats (``:90``).
- The trade executes at ``prices[i + 201]``, the price just *after* the window
  (``newShareValue``, ``:94``).
- Buy: feasible iff ``budget >= price`` → budget −= price, shares += 1.
  Sell: feasible iff ``shares > 0`` → budget += price, shares −= 1.
  Infeasible actions degrade to Hold (``makeDecisionAccordingToAction``,
  ``:118-123``).
- Reward = new portfolio − current portfolio, where portfolio = budget +
  shares × share_value and share_value is the *previous* step's trade price
  (seeded 0.0, so the first portfolio equals the initial budget;
  ``:84-92,136-146``).
- Episode length = len(prices) − 201 steps (``:67``); final portfolio =
  budget + shares × last trade price (``:68``).

Fidelity note: the reference's fold reads the **constructor** budget/shares in
``makeDecisionAccordingToAction`` instead of the folded running values
(SURVEY.md §2.1 "quirks") — every step trades against the initial state. This
implementation threads the running values, the behavior the fold was written
to produce.

Everything here is shape-static and branch-free (``jnp.where`` over
``lax.cond``) so a whole episode compiles into one fused ``lax.scan`` and a
batch of divergent agents into one ``vmap`` — no per-step host round-trips
(the reference pays 2 actor hops + ≤4 JNI crossings per step, SURVEY.md §3.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from sharetrade_tpu.env.core import TradingEnv

BUY, SELL, HOLD = 0, 1, 2  # reference action order: actions = Seq(Buy, Sell, Hold)
NUM_ACTIONS = 3


@struct.dataclass
class EnvParams:
    """Static episode data: the full price series plus initial conditions.

    ``window`` is static metadata (``pytree_node=False``) because it fixes
    observation shape; ``prices`` is a device array shared by every agent in a
    batch (the Akka broadcast of ``Train(stockData)`` becomes replication,
    TrainerRouterActor.scala:66,88).
    """

    prices: jax.Array                                     # (T,) float32
    initial_budget: jax.Array                             # scalar f32
    initial_shares: jax.Array                             # scalar f32
    window: int = struct.field(pytree_node=False, default=201)


@struct.dataclass
class EnvState:
    """Per-agent mutable state threaded through the scan (the fold carry)."""

    t: jax.Array            # i32 step cursor (the fold index i)
    budget: jax.Array       # f32
    shares: jax.Array       # f32 (integer-valued; float for uniform arithmetic)
    share_value: jax.Array  # f32 last trade price (0.0 before the first trade)


def env_from_prices(
    prices, window: int = 201, initial_budget: float = 2400.0, initial_shares: int = 0
) -> EnvParams:
    prices = jnp.asarray(prices, dtype=jnp.float32)
    if prices.ndim != 1:
        raise ValueError(f"prices must be 1-D, got shape {prices.shape}")
    if prices.shape[0] <= window:
        # Reference guard: "Stock price count should be more than Tensorflow
        # input nodes" (TrainerChildActor.scala:69-70). Exactly window + 1
        # prices is a valid one-step episode (the trade price prices[window]
        # is in bounds), matching the reference bound size > h1Dim + 1.
        raise ValueError(
            f"price count ({prices.shape[0]}) must exceed the window ({window})"
        )
    return EnvParams(
        prices=prices,
        initial_budget=jnp.float32(initial_budget),
        initial_shares=jnp.float32(initial_shares),
        window=window,
    )


def num_steps(params: EnvParams) -> int:
    """Steps per episode: len(prices) − window (TrainerChildActor.scala:67)."""
    return int(params.prices.shape[0]) - params.window


def reset(params: EnvParams) -> EnvState:
    zero = jnp.float32(0.0)
    return EnvState(
        t=jnp.int32(0),
        budget=jnp.asarray(params.initial_budget, jnp.float32),
        shares=jnp.asarray(params.initial_shares, jnp.float32),
        share_value=zero,
    )


def observe(params: EnvParams, state: EnvState) -> jax.Array:
    """Observation: ``prices[t : t+window] ++ (budget, shares)`` — shape (window+2,)."""
    window_slice = jax.lax.dynamic_slice(params.prices, (state.t,), (params.window,))
    return jnp.concatenate(
        [window_slice, jnp.stack([state.budget, state.shares])]
    )


def portfolio_value(state: EnvState) -> jax.Array:
    """budget + shares × last trade price (TrainerChildActor.scala:68,92)."""
    return state.budget + state.shares * state.share_value


def make_trading_env(prices, window: int = 201, initial_budget: float = 2400.0,
                     initial_shares: int = 0) -> TradingEnv:
    """Bundle the single-asset functions into the generic TradingEnv
    interface (env/core.py); the params close over as jit constants."""
    params = env_from_prices(prices, window=window,
                             initial_budget=initial_budget,
                             initial_shares=initial_shares)
    return TradingEnv(
        reset=lambda: reset(params),
        observe=lambda s: observe(params, s),
        step=lambda s, a: step(params, s, a),
        portfolio_value=portfolio_value,
        num_steps=num_steps(params),
        obs_dim=params.window + 2,
        num_actions=NUM_ACTIONS,
        num_assets=1,
        step_priced=lambda s, a, p: step(params, s, a, trade_price=p),
    )


def step(params: EnvParams, state: EnvState, action: jax.Array,
         trade_price: jax.Array | None = None):
    """Apply one action; returns ``(new_state, reward)``.

    Branch-free Buy/Sell/Hold with feasibility masking, so it vectorizes
    cleanly under ``vmap`` and stays a single fused XLA computation under
    ``lax.scan``. ``trade_price`` overrides the by-cursor gather (the
    ``TradingEnv.step_priced`` fast path — precomputed-rollout loops pass
    the price to keep gathers out of the sequential scan).
    """
    if trade_price is None:
        trade_price = params.prices[state.t + params.window]

    can_buy = (action == BUY) & (state.budget >= trade_price)
    can_sell = (action == SELL) & (state.shares > 0)

    delta = jnp.where(can_buy, 1.0, jnp.where(can_sell, -1.0, 0.0)).astype(jnp.float32)
    new_budget = state.budget - delta * trade_price
    new_shares = state.shares + delta

    current_portfolio = portfolio_value(state)
    new_portfolio = new_budget + new_shares * trade_price
    reward = new_portfolio - current_portfolio

    new_state = EnvState(
        t=state.t + 1,
        budget=new_budget,
        shares=new_shares,
        share_value=trade_price,
    )
    return new_state, reward
