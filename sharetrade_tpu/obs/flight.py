"""Crash flight recorder: a bounded ring of recent run events.

Black-box style: the orchestrator continuously records cheap host-side facts
— sampled chunk metric rows, lifecycle transitions, structured run events
(the EventLog mirror) and WARNING+ log lines — into a fixed-size deque.
Nothing touches disk until something goes wrong; when supervision trips, the
NaN-loss guard fires, or the run escalates, :meth:`dump` writes the whole
ring plus failure context as one forensic JSON bundle
(``flight_recorder.json``), so the last-K chunks before a crash are
reconstructable without per-chunk logging overhead during healthy runs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any


class FlightRecorder:
    def __init__(self, capacity: int = 256):
        self._ring: deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        #: Chunk index of the most recent ``chunk_metrics`` record — at dump
        #: time this IS the failing chunk (rows are recorded before the
        #: fault hook / health checks that can raise on them).
        self.last_chunk: int | None = None
        self.dumps = 0

    def record(self, kind: str, **payload: Any) -> None:
        if kind == "chunk_metrics" and "chunk" in payload:
            self.last_chunk = int(payload["chunk"])
        with self._lock:
            self._ring.append({"ts": time.time(), "kind": kind, **payload})

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, path: str, *, reason: str, **context: Any) -> str:
        """Write the forensic bundle atomically (tmp + rename, the
        checkpoint/journal crash-safety contract); returns the path."""
        bundle = {
            "reason": reason,
            "dumped_at": time.time(),
            "failing_chunk": context.pop("failing_chunk", self.last_chunk),
            "context": context,
            "events": self.snapshot(),
        }
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=2, default=str)
        os.replace(tmp, path)
        self.dumps += 1
        return path


class RingLogHandler(logging.Handler):
    """Feeds WARNING+ log records into the flight ring, so the bundle shows
    what the logs said in the window before the crash."""

    def __init__(self, flight: FlightRecorder,
                 level: int = logging.WARNING):
        super().__init__(level=level)
        self._flight = flight

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._flight.record("log", level=record.levelname,
                                logger=record.name,
                                message=record.getMessage())
        except Exception:   # a broken log record must never kill the run
            pass
