"""Stitch per-process span journals into one cross-process trace.

Every fleet process (client, frontend/router, each engine worker) appends
its finished wire spans to its OWN bounded CRC-framed journal
(obs/trace.py ``SpanJournal`` — ``spans-<proc>-<pid>.journal`` plus sealed
``.segNNNNNNNN`` segments under one shared spans directory). This module
is the read side: walk every journal, convert each process's raw
``perf_counter`` timestamps to a shared epoch-microsecond timeline using
the monotonic→epoch anchor its clock lines carry, group by trace id, and
emit one Perfetto-renderable trace per request.

What a collector may assume (the cross-process contract, pinned by
tests/test_obs_collect.py and the fleet soak):

- **parentage** — every span names its trace id, its own span id, and its
  parent span id ("" = root); within one stitched trace every non-empty
  parent id resolves to a span some process journaled, EXCEPT spans whose
  emitting process was SIGKILLed mid-request (their children survive as
  orphans and are reported, not dropped);
- **clock alignment** — span timestamps become comparable across
  processes only after applying each RECORD's own clock line (epoch −
  mono); same-host wall clocks make the residual error capture jitter,
  so interval nesting is verified with a small slack
  (:data:`NEST_SLACK_US`), never exact equality;
- **journal bounds** — journals rotate and prune oldest-first, and each
  record is self-describing (clock line first), so a stitched trace is
  complete only for requests younger than the retention window; pruning
  can never misalign surviving spans, only remove whole batches.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

from sharetrade_tpu.data.journal import iter_framed_records, segment_paths
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("obs.collect")

#: Cross-process nesting slack (µs): same-host epoch clocks agree to well
#: under this; child intervals are asserted inside their parents only to
#: this tolerance.
NEST_SLACK_US = 2000.0


def span_journal_paths(spans_dir: str) -> list[str]:
    """Every span journal file under ``spans_dir`` — sealed segments
    first (oldest data), then each active file."""
    try:
        names = sorted(os.listdir(spans_dir))
    except FileNotFoundError:
        return []
    active = [os.path.join(spans_dir, n) for n in names
              if n.startswith("spans-") and n.endswith(".journal")]
    paths: list[str] = []
    for path in active:
        paths.extend(segment_paths(path))
        paths.append(path)
    return paths


def _iter_file_spans(path: str) -> Iterator[dict]:
    for _off, payload in iter_framed_records(path, warn=False):
        lines = payload.split(b"\n")
        if not lines:
            continue
        try:
            clock = json.loads(lines[0])
            offset = float(clock["epoch"]) - float(clock["mono"])
            proc, pid = clock["proc"], clock["pid"]
        except (ValueError, KeyError, TypeError):
            continue            # not a span batch; skip the record
        for raw in lines[1:]:
            try:
                ev = json.loads(raw)
            except ValueError:
                continue
            span = {"trace": ev["trace"], "span": ev["span"],
                    "parent": ev.get("parent", ""), "name": ev["name"],
                    "proc": proc, "pid": pid,
                    "ts_us": (float(ev["t0"]) + offset) * 1e6}
            if "t1" in ev:
                span["dur_us"] = (float(ev["t1"]) - float(ev["t0"])) * 1e6
            if ev.get("note"):
                span["note"] = ev["note"]
            yield span


def read_span_dir(spans_dir: str) -> list[dict]:
    """All spans from every journal under ``spans_dir``, clock-aligned to
    epoch microseconds (``ts_us``; complete spans carry ``dur_us``)."""
    spans: list[dict] = []
    for path in span_journal_paths(spans_dir):
        spans.extend(_iter_file_spans(path))
    return spans


def trace_ids(spans: list[dict]) -> dict[str, int]:
    """trace id -> span count, ordered by each trace's first timestamp."""
    first: dict[str, float] = {}
    counts: dict[str, int] = {}
    for s in spans:
        t = s["trace"]
        counts[t] = counts.get(t, 0) + 1
        if t not in first or s["ts_us"] < first[t]:
            first[t] = s["ts_us"]
    return {t: counts[t] for t in sorted(counts, key=first.get)}


def stitch(spans: list[dict], trace_id: str) -> dict:
    """One trace's spans, time-sorted, with the contract verified.

    Returns ``{"trace_id", "spans", "procs", "errors"}`` where ``errors``
    lists every violated invariant: an unresolved parent id, or a span
    interval escaping its parent's by more than :data:`NEST_SLACK_US`.
    An empty ``errors`` is the stitched-trace acceptance the soak and the
    e2e tests assert."""
    mine = sorted((s for s in spans if s["trace"] == trace_id),
                  key=lambda s: s["ts_us"])
    by_id = {s["span"]: s for s in mine}
    errors: list[str] = []
    for s in mine:
        parent = by_id.get(s["parent"]) if s["parent"] else None
        if s["parent"] and parent is None:
            errors.append(f"span {s['span']} ({s['name']}, {s['proc']}): "
                          f"parent {s['parent']} unresolved")
            continue
        if parent is None or "dur_us" not in parent:
            continue            # root, or parented under an instant
        p0 = parent["ts_us"] - NEST_SLACK_US
        p1 = parent["ts_us"] + parent["dur_us"] + NEST_SLACK_US
        s0 = s["ts_us"]
        s1 = s0 + s.get("dur_us", 0.0)
        if s0 < p0 or s1 > p1:
            errors.append(
                f"span {s['span']} ({s['name']}, {s['proc']}) "
                f"[{s0:.0f},{s1:.0f}]us escapes parent "
                f"{parent['span']} ({parent['name']}) "
                f"[{p0:.0f},{p1:.0f}]us")
    return {"trace_id": trace_id, "spans": mine,
            "procs": sorted({s["proc"] for s in mine}),
            "errors": errors}


def write_perfetto(stitched: dict, path: str) -> str:
    """Render a stitched trace as Chrome trace-event JSON (the same
    array format obs/trace.py writes — ui.perfetto.dev loads it
    directly). Each journaling process becomes one named Perfetto
    process row."""
    procs = {proc: i + 1 for i, proc in enumerate(stitched["procs"])}
    events: list[dict] = []
    for proc, pid in procs.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": proc}})
    for s in stitched["spans"]:
        args: dict[str, Any] = {"trace": s["trace"], "span": s["span"],
                                "parent": s["parent"]}
        if "note" in s:
            args["note"] = s["note"]
        ev = {"name": s["name"], "cat": "wire", "pid": procs[s["proc"]],
              "tid": 0, "ts": round(s["ts_us"], 3), "args": args}
        if "dur_us" in s:
            ev.update(ph="X", dur=round(s["dur_us"], 3))
        else:
            ev.update(ph="i", s="p")
        events.append(ev)
    with open(path, "w", encoding="utf-8") as f:
        f.write("[\n")
        f.write("".join(json.dumps(e) + ",\n" for e in events))
    return path


def collect_trace(spans_dir: str, trace_id: str,
                  out: str | None = None) -> dict:
    """Read + stitch + (optionally) render one trace; the shared body of
    ``cli obs --trace`` and tools/trace_collect.py."""
    stitched = stitch(read_span_dir(spans_dir), trace_id)
    if out and stitched["spans"]:
        stitched["perfetto"] = write_perfetto(stitched, out)
    return stitched


def migrated_traces(spans: list[dict]) -> list[dict]:
    """Stitched traces whose router relay MIGRATED mid-flight (an attempt
    span annotated ``migrate``) — the kill-correlation surface the fleet
    soak asserts on: each returned trace carries the set of engine procs
    whose spans made it into the record."""
    out: list[dict] = []
    for tid in trace_ids(spans):
        stitched = stitch(spans, tid)
        attempts = [s for s in stitched["spans"]
                    if s["name"] == "relay_attempt"]
        if not any(s.get("note", "").startswith("migrate") for s in attempts):
            continue
        stitched["engines"] = sorted(
            {s["proc"] for s in stitched["spans"]
             if s["proc"].startswith("engine-")})
        out.append(stitched)
    return out
