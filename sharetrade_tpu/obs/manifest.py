"""Run manifest: the identity card every telemetry consumer needs first.

One ``manifest.json`` per run dir, written at orchestrator construction:
the full config plus a stable hash of it (so two run dirs are comparable at
a glance), the device backend and mesh shape the run actually got, and the
git revision of the code that produced the numbers. Everything is
best-effort — a missing git binary or a detached workdir must not block
training — and written atomically like every other obs artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import subprocess
import sys
import time
from typing import Any


def _git_rev() -> str | None:
    try:
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_dir, timeout=5,
            capture_output=True, text=True)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def config_hash(cfg: Any) -> str:
    """THE stable 16-char config identity — manifest.json's
    ``config_hash`` and the bench envelope's (``bench._result_envelope``)
    are the same recipe by construction, so run dirs and BENCH rows join
    on it."""
    blob = json.dumps(cfg.to_dict(), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def build_manifest(cfg: Any, *, mesh: Any = None) -> dict:
    cfg_dict = cfg.to_dict()
    try:
        import jax
        backend = jax.default_backend()
        device_count = jax.device_count()
        jax_version = jax.__version__
    except Exception:       # manifest must not force device discovery to work
        backend, device_count, jax_version = None, None, None
    manifest = {
        "created_at": time.time(),
        "config_hash": config_hash(cfg),
        "config": cfg_dict,
        "backend": backend,
        "device_count": device_count,
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
        "git_rev": _git_rev(),
        "jax_version": jax_version,
        "python_version": sys.version.split()[0],
        "hostname": platform.node(),
        "pid": os.getpid(),
    }
    try:
        # Tuned-knob provenance (tuning.py): which registered knobs ran
        # at default / profile / explicit values, and under which
        # profile + host fingerprint — the ``cli obs`` tuning section's
        # source. Best-effort like the git probe: a vanished profile
        # must not block a run from writing its manifest.
        if hasattr(cfg, "tuning"):
            from sharetrade_tpu.tuning import describe
            manifest["tuning"] = describe(cfg)
    except Exception:
        pass
    return manifest


def write_manifest(path: str, cfg: Any, *, mesh: Any = None) -> dict:
    manifest = build_manifest(cfg, mesh=mesh)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, default=str)
    os.replace(tmp, path)
    return manifest
