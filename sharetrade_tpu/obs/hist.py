"""Mergeable streaming histograms with fixed log-spaced buckets.

The serving tier's only latency signal used to be p50/p99 over a bounded
sample ring — an aggregate that cannot be combined across engines (the
percentile of a union is not a function of per-shard percentiles) and
cannot say which STAGE owns a tail. This module is the Prometheus
``_bucket``-style answer: a fixed, name-determined bucket layout shared by
every process, so

- **merge is exact**: two shards' histograms combine by bucket-wise count
  addition (plus sum/count) — the fleet-router aggregation ROADMAP item 2
  balances on, with zero approximation introduced by the merge itself;
- **windows are subtraction**: cumulative counts snapshotted at t0 and t1
  diff into the exact histogram of the interval (how the serve engine
  derives its rolling p50/p99 gauges without a sample ring);
- **quantiles are bounded-error**: any quantile estimate is within ONE
  bucket width of the exact nearest-rank sample quantile (pinned by
  tests/test_obs_hist.py against ``serve/engine.py latency_percentiles``,
  the repo's single quantile convention).

Buckets are log-spaced (``per_decade`` bounds per power of 10) because
latencies live on a ratio scale: constant RELATIVE resolution from 10 µs
to minutes in ~35 buckets. The layout is part of a metric's contract —
``DEFAULT_MS_BOUNDS`` for every ``*_ms`` histogram, ``SECONDS_BOUNDS``
for ``*_seconds`` — so independently-started engines always merge.

Thread-safety: each histogram carries its own lock; ``observe`` is a
bisect + two adds under it (no allocation), cheap enough for per-request
hot paths. Export rides :class:`~sharetrade_tpu.obs.exporter.
MetricsExporter` via ``MetricsRegistry.attach_histogram``.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left

__all__ = [
    "DEFAULT_MS_BOUNDS",
    "SECONDS_BOUNDS",
    "Histogram",
    "from_prom_buckets",
    "log_bounds",
    "merge",
    "quantile_from_counts",
    "quantile_from_snapshot",
]


def log_bounds(lo: float, hi: float, *, per_decade: int = 5
               ) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds from ``lo`` up to (at least) ``hi``,
    ``per_decade`` per power of ten. Generated from integer exponents so
    two processes computing the same spec get BIT-IDENTICAL bounds — the
    precondition for exact merges."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(
            f"log_bounds needs 0 < lo < hi and per_decade >= 1, got "
            f"lo={lo} hi={hi} per_decade={per_decade}")
    e0 = round(math.log10(lo) * per_decade)
    bounds = []
    e = e0
    while True:
        b = 10.0 ** (e / per_decade)
        bounds.append(b)
        if b >= hi:
            return tuple(bounds)
        e += 1


#: The framework-wide layout for millisecond metrics (`*_ms`): 10 µs to
#: ~100 s at 5 buckets/decade (36 bounds). Changing this changes the merge
#: contract — bump only with a fleet-wide flag day.
DEFAULT_MS_BOUNDS = log_bounds(0.01, 1e5, per_decade=5)

#: Layout for second-scale training metrics (chunk wall times): 100 µs to
#: ~1000 s.
SECONDS_BOUNDS = log_bounds(1e-4, 1e3, per_decade=5)


def quantile_from_counts(bounds, counts, q: float) -> float:
    """Nearest-rank quantile estimate over NON-cumulative per-bucket
    ``counts`` (len(bounds) + 1, last = overflow). Matches the exact
    convention of ``serve/engine.py latency_percentiles`` (1-indexed rank
    ``ceil(q * n)``), then linearly interpolates inside the selected
    bucket — the estimate is within one bucket width of the exact sample
    quantile. Empty counts return 0.0; an overflow-bucket hit returns the
    top finite bound (the histogram cannot see past it)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = min(max(math.ceil(q * total), 1), total)
    cum = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            if i >= len(bounds):
                return float(bounds[-1])
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - cum) / c
            return float(lo + frac * (hi - lo))
        cum += c
    return float(bounds[-1])


def quantile_from_snapshot(snapshot: dict, q: float) -> float:
    """Quantile over a :meth:`Histogram.snapshot` dict (what the exporter
    writes into ``metrics.jsonl`` — the ``cli obs`` reader's entry point)."""
    return quantile_from_counts(snapshot["bounds"], snapshot["counts"], q)


class Histogram:
    """Fixed-bucket streaming histogram; see the module docstring.

    ``counts`` is NON-cumulative per bucket with one overflow slot at the
    end; the Prometheus cumulative form (including ``+Inf``) is derived at
    export time. ``sum``/``count`` ride along for ``_sum``/``_count``."""

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds=None):
        bounds = tuple(bounds) if bounds is not None else DEFAULT_MS_BOUNDS
        if not bounds or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("histogram bounds must be strictly ascending "
                             "and non-empty")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        """Count one sample (bucket semantics: ``value <= bound``, the
        Prometheus ``le`` convention)."""
        value = float(value)
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def merge(self, other: "Histogram") -> "Histogram":
        """Bucket-wise add ``other`` into self (EXACT — integer counts).
        Refuses mismatched layouts loudly: merging across different bucket
        specs would silently corrupt every downstream quantile."""
        if self.bounds != other.bounds:
            raise ValueError(
                "cannot merge histograms with different bucket bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} buckets)")
        o = other.snapshot()
        with self._lock:
            for i, c in enumerate(o["counts"]):
                self.counts[i] += c
            self.sum += o["sum"]
            self.count += o["count"]
        return self

    def snapshot(self) -> dict:
        """Consistent copy: ``{"bounds", "counts", "sum", "count"}`` —
        the exporter/merge/window unit."""
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self.counts),
                    "sum": self.sum,
                    "count": self.count}

    def quantile(self, q: float, *, counts=None) -> float:
        """Quantile estimate (within one bucket width of exact). Pass
        ``counts`` (e.g. a window delta from two snapshots) to evaluate a
        sub-interval instead of the cumulative distribution."""
        if counts is None:
            counts = self.snapshot()["counts"]
        return quantile_from_counts(self.bounds, counts, q)


def merge(histograms) -> Histogram:
    """Fresh histogram holding the exact bucket-wise sum of ``histograms``
    (all must share one layout) — the fleet-aggregation helper."""
    hs = list(histograms)
    if not hs:
        raise ValueError("merge() of no histograms")
    out = Histogram(bounds=hs[0].bounds)
    for h in hs:
        out.merge(h)
    return out


def from_prom_buckets(buckets, total_sum: float, count: int) -> Histogram:
    """Rebuild a :class:`Histogram` from a scraped Prometheus exposition —
    ``buckets`` is the ``[(le, cumulative)]`` list :func:`~sharetrade_tpu.
    obs.exporter.parse_prom_text` returns (``le`` = ``+inf`` for the
    overflow terminal). The reconstruction is EXACT: cumulative counts
    diff back to the per-bucket integers the engine observed, so the
    fleet router's bucket-wise merge of scraped engines equals the merge
    of the engines' in-process histograms bit for bit (the precondition
    for exact fleet-level p50/p99 — the aggregation contract README
    "Request tracing" documents and the fleet extends over the wire).

    Raises ``ValueError`` on a non-monotone cumulative series, a missing
    ``+Inf`` terminal, or a ``+Inf``/count mismatch — a corrupt scrape
    must never silently fold garbage into fleet quantiles."""
    # parse_prom_text hands le through as label TEXT ("+Inf" included);
    # float() accepts both spellings, so scraped and in-process sources
    # meet here.
    buckets = [(float(le), cum) for le, cum in buckets]
    if not buckets or not math.isinf(buckets[-1][0]):
        raise ValueError("prom histogram must end in a +Inf bucket")
    bounds = tuple(le for le, _ in buckets[:-1])
    # The exporter's %.12g labels drop the last ~4 bits of a double, so
    # a parsed bound can differ from its source by ~1e-13 relative —
    # enough for Histogram.merge's layout check to refuse a scraped
    # shard against an in-process histogram. Snap to the canonical
    # framework layouts when the LABEL TEXT matches (the actual merge
    # key two processes share); a foreign layout passes through as
    # parsed and still merges exactly with other scrapes of itself.
    for canon in (DEFAULT_MS_BOUNDS, SECONDS_BOUNDS):
        if len(canon) == len(bounds) and all(
                f"{c:.12g}" == f"{b:.12g}"
                for c, b in zip(canon, bounds)):
            bounds = canon
            break
    hist = Histogram(bounds=bounds)
    counts = []
    prev = 0.0
    for le, cum in buckets:
        if cum < prev:
            raise ValueError(
                f"non-monotone cumulative bucket counts at le={le}")
        counts.append(int(cum - prev))
        prev = cum
    if int(buckets[-1][1]) != int(count):
        raise ValueError(
            f"+Inf bucket {buckets[-1][1]} != _count {count}")
    hist.counts = counts
    hist.sum = float(total_sum)
    hist.count = int(count)
    return hist
