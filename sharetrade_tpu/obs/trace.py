"""Host-side span tracer emitting Chrome trace-event JSON.

``jax.profiler`` device traces (utils/profiling.py Tracer) need TensorBoard/
XProf to read their XPlane protos; this tracer is the complementary HOST
timeline: orchestrator phases (dispatch, readback, host processing,
checkpoint IO, supervision recovery) written as Chrome trace events that
Perfetto (https://ui.perfetto.dev) or chrome://tracing load directly, no
profiler runtime required.

File format: the JSON Array Format of the Trace Event spec — an opening
``[`` then one ``{event},`` per line. The spec makes the closing ``]``
optional precisely so crashed writers still leave a loadable trace, which is
also what makes the file greppable/tail-able like JSONL: every event is one
self-contained line. Events are buffered and flushed every
``flush_every`` records (and on close), so the hot loop pays a dict+append,
not a syscall, per span.

``SpanTracer(None)`` is the disabled instance: ``span()`` returns a shared
null context and nothing is ever opened or written (the obs.enabled=false
contract — zero files, near-zero cost).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any

_NULL_CTX = contextlib.nullcontext()


class _Span:
    """One in-flight span; emits a complete ("ph": "X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, *exc) -> None:
        t1 = self._tracer._now_us()
        self._tracer._emit({
            "name": self._name, "ph": "X", "ts": self._t0,
            "dur": t1 - self._t0, "pid": self._tracer._pid,
            "tid": threading.get_ident(),
            **({"args": self._args} if self._args else {}),
        })


class SpanTracer:
    def __init__(self, path: str | None, *, flush_every: int = 64):
        self._path = path
        self._flush_every = max(1, flush_every)
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._pid = os.getpid()
        # Trace timestamps are microseconds on the perf_counter clock from
        # tracer construction (Perfetto only needs them monotone/relative);
        # wall-clock anchoring lives in the run manifest.
        self._t0 = time.perf_counter()
        self._fh = None
        if path:
            self._fh = open(path, "w", encoding="utf-8")
            self._fh.write("[\n")

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def span(self, name: str, **args: Any):
        """Context manager timing one named phase; no-op when disabled."""
        if self._fh is None:
            return _NULL_CTX
        return _Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker (lifecycle transitions, dumps, restarts)."""
        if self._fh is None:
            return
        self._emit({
            "name": name, "ph": "i", "ts": self._now_us(), "s": "p",
            "pid": self._pid, "tid": threading.get_ident(),
            **({"args": args} if args else {}),
        })

    def to_us(self, t_perf: float) -> float:
        """A raw ``time.perf_counter()`` stamp on this tracer's timeline —
        for RETROSPECTIVE emission (the serve engine stamps request edges
        as floats and emits the whole lifecycle at completion)."""
        return (t_perf - self._t0) * 1e6

    @property
    def pid(self) -> int:
        return self._pid

    def emit_lines(self, lines: list[str]) -> None:
        """Bulk-append PRE-SERIALIZED event lines (no trailing comma/
        newline) under one lock acquisition — the per-request hot path.
        The serve engine formats its request-lifecycle events with
        f-strings instead of per-event ``json.dumps`` (measured ~10x
        cheaper at 5 events/request on the completion thread); callers
        own the validity of what they hand in (tests round-trip it
        through :func:`read_trace`)."""
        with self._lock:
            if self._fh is None:
                return
            self._buf.extend(lines)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _emit(self, event: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            self._buf.append(json.dumps(event))
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf and self._fh is not None:
            self._fh.write("".join(line + ",\n" for line in self._buf))
            self._fh.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# ---------------------------------------------------------------------------
# Cross-process wire spans (ISSUE 17): every fleet process journals its
# finished spans to a bounded per-process CRC-framed file; obs/collect.py
# stitches them by trace id into one Perfetto trace. Timestamps are raw
# ``time.perf_counter()`` floats — processes do NOT share that clock, so
# each journal records a monotonic→epoch anchor the collector aligns with.


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id (random, collision-free in practice);
    minted once per inbound request at whichever hop first finds no
    ``X-Trace-Id`` header (the client when traced, else the frontend)."""
    return os.urandom(8).hex()


class SpanJournal:
    """Bounded per-process span journal: CRC-framed batches, segment
    rotation, oldest-first pruning — the data/journal.py frame (ONE
    framing definition; every file replays through
    ``iter_framed_records``) without its writer-lock/fsync weight: span
    files are keyed by (process label, pid) so two writers can never
    share one, and spans are telemetry — a torn tail loses at most the
    last unflushed batch, never correctness.

    Clock contract (the correctness core the collector leans on): at
    open, ONE ``(epoch=time.time(), mono=time.perf_counter())`` pair is
    captured — the tightest of several samples, so the pairing error is
    bounded by the narrowest observed sampling window — and a clock line
    carrying it leads EVERY flushed batch payload. Each record is
    therefore self-describing: segment pruning or a torn tail can never
    orphan spans from their alignment offset."""

    def __init__(self, directory: str, proc: str, *,
                 max_records: int = 4096, max_segments: int = 8):
        self.dir = directory
        self.proc = proc
        self.pid = os.getpid()
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory,
                                 f"spans-{proc}-{self.pid}.journal")
        best = None
        for _ in range(5):
            a = time.perf_counter()
            epoch = time.time()
            b = time.perf_counter()
            if best is None or (b - a) < best[2]:
                best = (epoch, (a + b) / 2.0, b - a)
        self.epoch, self.mono = best[0], best[1]
        self._clock_line = json.dumps(
            {"clock": 1, "proc": proc, "pid": self.pid,
             "epoch": self.epoch, "mono": self.mono},
            separators=(",", ":")).encode()
        self._max_records = max(1, int(max_records))
        self._max_segments = max(1, int(max_segments))
        self._records = 0
        self._lock = threading.Lock()
        self._fh = open(self.path, "ab")

    def append_batch(self, lines: list[bytes]) -> None:
        """Append ONE framed record: the clock line plus ``lines``
        (newline-joined pre-serialized span events). Flushed to the OS
        immediately — the page cache survives a SIGKILLed writer, which
        is what lets a dead engine's ingress spans reach the stitched
        trace of a migrated request."""
        from sharetrade_tpu.data.journal import frame_record
        payload = b"\n".join([self._clock_line, *lines])
        record = frame_record(payload)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(record)
            self._fh.flush()
            self._records += 1
            if self._records >= self._max_records:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        from sharetrade_tpu.data.journal import segment_paths
        self._fh.close()
        existing = segment_paths(self.path)
        last = int(existing[-1].rsplit(".seg", 1)[1]) if existing else 0
        os.rename(self.path, f"{self.path}.seg{last + 1:08d}")
        for stale in segment_paths(self.path)[:-self._max_segments]:
            try:
                os.unlink(stale)
            except OSError:
                pass
        self._fh = open(self.path, "ab")
        self._records = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class SpanSink:
    """Hot-path wire-span buffer: one tuple append per finished span into
    a BOUNDED ring, serialization deferred to the batched flush (one
    ``json.dumps`` per span at flush cadence, one framed journal append
    per batch) — the emission discipline tools/lint_hot_loop.py check 16
    pins for the evloop runner and router relay closures. Overflow drops
    the oldest spans (counted in ``dropped``) instead of growing."""

    def __init__(self, journal: SpanJournal, *, capacity: int = 8192,
                 flush_every: int = 128):
        self._journal = journal
        self._flush_every = max(1, int(flush_every))
        # trace-buffer-ok: bounded ring (maxlen); overflow counted, not grown
        self._buf: deque = deque(maxlen=max(self._flush_every,
                                            int(capacity)))
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._id_prefix = f"{journal.pid:x}"
        self.proc = journal.proc
        self.dropped = 0

    def new_span_id(self) -> str:
        """Pid-prefixed counter hex — unique across processes without
        per-span entropy syscalls."""
        return f"{self._id_prefix}.{next(self._ids):x}"

    def span(self, trace_id: str, span_id: str, parent: str, name: str,
             t0: float, t1: float | None, note: str = "") -> None:
        """Record one finished span (``t0``/``t1`` on this process's
        ``perf_counter`` clock; ``t1=None`` = instant event)."""
        with self._lock:
            buf = self._buf
            if len(buf) == buf.maxlen:
                self.dropped += 1
            buf.append((trace_id, span_id, parent, name, t0, t1, note))
            if len(buf) >= self._flush_every:
                self._flush_locked()

    def instant(self, trace_id: str, span_id: str, parent: str, name: str,
                note: str = "", *, flush: bool = False) -> None:
        """Zero-duration marker at now; ``flush=True`` makes it DURABLE
        before returning (the engine-ingress eager flush: a SIGKILLed
        engine must still leave trace evidence for in-flight requests)."""
        self.span(trace_id, span_id, parent, name,
                  time.perf_counter(), None, note)
        if flush:
            self.flush()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        lines = []
        for trace_id, span_id, parent, name, t0, t1, note in self._buf:
            ev: dict = {"trace": trace_id, "span": span_id,
                        "parent": parent, "name": name, "t0": t0}
            if t1 is not None:
                ev["t1"] = t1
            if note:
                ev["note"] = note
            lines.append(json.dumps(ev, separators=(",", ":")).encode())
        self._buf.clear()
        self._journal.append_batch(lines)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        self.flush()
        self._journal.close()


def read_trace(path: str) -> list[dict]:
    """Parse a (possibly unterminated) JSON-Array-Format trace back into
    event dicts — the reader the `cli obs` summary and tests share."""
    with open(path, encoding="utf-8") as f:
        content = f.read()
    content = content.strip()
    if not content or content == "[":
        return []
    if not content.endswith("]"):
        content = content.rstrip(",") + "]"
    return json.loads(content)
