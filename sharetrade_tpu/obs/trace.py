"""Host-side span tracer emitting Chrome trace-event JSON.

``jax.profiler`` device traces (utils/profiling.py Tracer) need TensorBoard/
XProf to read their XPlane protos; this tracer is the complementary HOST
timeline: orchestrator phases (dispatch, readback, host processing,
checkpoint IO, supervision recovery) written as Chrome trace events that
Perfetto (https://ui.perfetto.dev) or chrome://tracing load directly, no
profiler runtime required.

File format: the JSON Array Format of the Trace Event spec — an opening
``[`` then one ``{event},`` per line. The spec makes the closing ``]``
optional precisely so crashed writers still leave a loadable trace, which is
also what makes the file greppable/tail-able like JSONL: every event is one
self-contained line. Events are buffered and flushed every
``flush_every`` records (and on close), so the hot loop pays a dict+append,
not a syscall, per span.

``SpanTracer(None)`` is the disabled instance: ``span()`` returns a shared
null context and nothing is ever opened or written (the obs.enabled=false
contract — zero files, near-zero cost).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any

_NULL_CTX = contextlib.nullcontext()


class _Span:
    """One in-flight span; emits a complete ("ph": "X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, *exc) -> None:
        t1 = self._tracer._now_us()
        self._tracer._emit({
            "name": self._name, "ph": "X", "ts": self._t0,
            "dur": t1 - self._t0, "pid": self._tracer._pid,
            "tid": threading.get_ident(),
            **({"args": self._args} if self._args else {}),
        })


class SpanTracer:
    def __init__(self, path: str | None, *, flush_every: int = 64):
        self._path = path
        self._flush_every = max(1, flush_every)
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._pid = os.getpid()
        # Trace timestamps are microseconds on the perf_counter clock from
        # tracer construction (Perfetto only needs them monotone/relative);
        # wall-clock anchoring lives in the run manifest.
        self._t0 = time.perf_counter()
        self._fh = None
        if path:
            self._fh = open(path, "w", encoding="utf-8")
            self._fh.write("[\n")

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def span(self, name: str, **args: Any):
        """Context manager timing one named phase; no-op when disabled."""
        if self._fh is None:
            return _NULL_CTX
        return _Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker (lifecycle transitions, dumps, restarts)."""
        if self._fh is None:
            return
        self._emit({
            "name": name, "ph": "i", "ts": self._now_us(), "s": "p",
            "pid": self._pid, "tid": threading.get_ident(),
            **({"args": args} if args else {}),
        })

    def to_us(self, t_perf: float) -> float:
        """A raw ``time.perf_counter()`` stamp on this tracer's timeline —
        for RETROSPECTIVE emission (the serve engine stamps request edges
        as floats and emits the whole lifecycle at completion)."""
        return (t_perf - self._t0) * 1e6

    @property
    def pid(self) -> int:
        return self._pid

    def emit_lines(self, lines: list[str]) -> None:
        """Bulk-append PRE-SERIALIZED event lines (no trailing comma/
        newline) under one lock acquisition — the per-request hot path.
        The serve engine formats its request-lifecycle events with
        f-strings instead of per-event ``json.dumps`` (measured ~10x
        cheaper at 5 events/request on the completion thread); callers
        own the validity of what they hand in (tests round-trip it
        through :func:`read_trace`)."""
        with self._lock:
            if self._fh is None:
                return
            self._buf.extend(lines)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _emit(self, event: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            self._buf.append(json.dumps(event))
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf and self._fh is not None:
            self._fh.write("".join(line + ",\n" for line in self._buf))
            self._fh.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_trace(path: str) -> list[dict]:
    """Parse a (possibly unterminated) JSON-Array-Format trace back into
    event dicts — the reader the `cli obs` summary and tests share."""
    with open(path, encoding="utf-8") as f:
        content = f.read()
    content = content.strip()
    if not content or content == "[":
        return []
    if not content.endswith("]"):
        content = content.rstrip(",") + "]"
    return json.loads(content)
