"""A tiny on-disk time-series ring for fleet telemetry history.

The fleet router already scrapes every engine's ``/metrics`` each poll
and publishes LAST-VALUE gauges (``fleet_status.json``). This module
keeps the recent *history* of those polls — one JSONL row per poll,
retention bounded by ROWS, not time — so soaks, benches and ``cli obs
--history`` can answer "fleet p99 over the last N windows" instead of
only "fleet p99 right now". This is the gauge-not-a-guess substrate the
ROADMAP item-3 autoscaler will read its load signal from.

Write discipline: plain buffered appends on the poller thread (one row
per ``telemetry_poll_s``, no fsync — history is telemetry, a torn tail
loses one row). When the file grows past twice the retention bound it is
compacted by atomic rewrite (tmp + ``os.replace``) keeping the newest
``max_rows`` rows, so readers always see either the old file or the
compacted one, never a partial rewrite.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

from sharetrade_tpu.utils.logging import get_logger

log = get_logger("obs.tsdb")

#: The fleet router's per-poll gauge history, written next to
#: fleet_status.json in the fleet workdir (fleet/router.py) and read by
#: ``cli obs --history`` — named HERE so the CLI read path never imports
#: the fleet (and its engine/jax weight) just to find the file.
FLEET_HISTORY_FILE = "fleet_history.jsonl"


class TsdbRing:
    """Bounded JSONL history at ``path`` (see module docstring)."""

    def __init__(self, path: str, *, max_rows: int = 2048):
        self.path = path
        self.max_rows = max(1, int(max_rows))
        self._lock = threading.Lock()
        self._rows_in_file = sum(1 for _ in self._iter_lines())
        self._fh = open(path, "a", encoding="utf-8")

    def _iter_lines(self):
        try:
            with open(self.path, encoding="utf-8") as f:
                yield from f
        except OSError:
            return

    def append(self, row: dict[str, Any]) -> None:
        """Append one poll row; compacts past 2x the retention bound."""
        line = json.dumps(row, separators=(",", ":"))
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            self._rows_in_file += 1
            if self._rows_in_file > 2 * self.max_rows:
                self._compact_locked()

    def _compact_locked(self) -> None:
        self._fh.close()
        keep = [ln for ln in self._iter_lines()
                if ln.strip()][-self.max_rows:]
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.writelines(keep)
        os.replace(tmp, self.path)  # fsync-not-needed: bounded telemetry
        self._fh = open(self.path, "a", encoding="utf-8")
        self._rows_in_file = len(keep)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_history(path: str, last_n: int = 0) -> list[dict]:
    """The newest ``last_n`` rows (0 = all retained), tolerating a torn
    final line."""
    rows: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue        # torn tail row
    except OSError:
        return []
    return rows[-last_n:] if last_n > 0 else rows


def summarize_history(rows: list[dict],
                      keys: tuple = ("fleet_p50_ms", "fleet_p99_ms",
                                     "fleet_engines_live",
                                     "fleet_window_requests")) -> dict:
    """min/max/last per tracked gauge over ``rows`` — the "over the last
    N windows" answer ``cli obs --history`` prints."""
    summary: dict[str, Any] = {"rows": len(rows)}
    if not rows:
        return summary
    if rows[0].get("ts") is not None and rows[-1].get("ts") is not None:
        summary["window_s"] = round(rows[-1]["ts"] - rows[0]["ts"], 3)
    for key in keys:
        vals = [r[key] for r in rows
                if isinstance(r.get(key), (int, float))]
        if vals:
            summary[key] = {"min": min(vals), "max": max(vals),
                            "last": vals[-1]}
    return summary
