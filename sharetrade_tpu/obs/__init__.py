"""Unified telemetry (SURVEY.md §5: the subsystem the reference lacks).

Three persistent surfaces over the existing in-memory primitives, all
gated by ``ObsConfig`` (everything off by default — zero files, near-zero
hot-loop cost when disabled):

- :mod:`trace` — host span tracer → ``trace.jsonl`` (Chrome trace events;
  open in Perfetto / chrome://tracing). Under ``runtime.async_pipeline``
  the timeline splits across threads: ``dispatch`` spans stay on the
  dispatcher tid while ``readback``/``host_process`` move to the consumer
  tid, joined by ``queue_wait`` (consumer starved — healthy) and
  ``pipeline_stall`` (dispatcher blocked on the bounded queue — host-bound)
  spans, with ``pipeline_stalls_total``/``pipeline_queue_depth`` in the
  metrics export;
- :mod:`exporter` — background drain of :class:`MetricsRegistry` →
  ``metrics.jsonl`` + Prometheus textfile ``metrics.prom``;
- :mod:`flight` — bounded ring of recent chunk metrics / lifecycle /
  log events → ``flight_recorder.json`` forensic bundle on failure;
- :mod:`manifest` — run identity (``manifest.json``: config hash, mesh,
  backend, git rev) written at construction;
- :mod:`roofline` — compiled-cost capture (XLA cost/memory analysis per
  (mega)chunk program) → live ``mfu``/``achieved_tflops``/``hbm_gbps``
  gauges + schema-versioned ``roofline.json`` (``obs.roofline`` knob).

The :class:`Obs` facade is what the orchestrator holds; a disabled instance
is inert (``span()`` hands back a shared null context, ``record()`` returns
immediately) so the hot loop never branches on more than ``obs.enabled``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

from sharetrade_tpu.obs.exporter import (  # noqa: F401
    MetricsExporter,
    PromParseError,
    parse_prom_text,
)
from sharetrade_tpu.obs.hist import (  # noqa: F401
    Histogram,
    quantile_from_snapshot,
)
from sharetrade_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    RingLogHandler,
)
from sharetrade_tpu.obs.manifest import build_manifest, write_manifest  # noqa: F401
from sharetrade_tpu.obs.roofline import (  # noqa: F401
    RooflineCapture,
    read_roofline,
    summarize_roofline,
)
from sharetrade_tpu.obs.trace import (  # noqa: F401
    SpanJournal,
    SpanSink,
    SpanTracer,
    new_trace_id,
    read_trace,
)

FLIGHT_BUNDLE = "flight_recorder.json"

#: Stage names of the serve request-latency decomposition, in lifecycle
#: order — the single source for the ``serve_<stage>_ms`` histogram
#: families shared by the engine, the CLI/run-dir summaries, the soak's
#: perf-gate rows, and the obs demo.
SERVE_STAGES = ("queue_wait", "batch_wait", "device", "readback")


def serve_stage_p99s(registry: Any) -> dict[str, float]:
    """Histogram-derived per-stage p99s off a live ``MetricsRegistry`` —
    the "which stage owns the tail" row every serve summary prints.
    Stages with no observations are omitted."""
    out: dict[str, float] = {}
    for stage in SERVE_STAGES:
        hist = registry.histogram(f"serve_{stage}_ms")
        if hist is not None and hist.count:
            out[stage] = round(hist.quantile(0.99), 3)
    return out


class Obs:
    """Facade over tracer / exporter / flight recorder for one run dir."""

    def __init__(self, *, run_dir: str | None = None,
                 tracer: SpanTracer | None = None,
                 exporter: MetricsExporter | None = None,
                 flight: FlightRecorder | None = None,
                 log_handler: RingLogHandler | None = None,
                 roofline: RooflineCapture | None = None,
                 spans: SpanSink | None = None):
        self.run_dir = run_dir
        self.enabled = run_dir is not None
        self.tracer = tracer if tracer is not None else SpanTracer(None)
        #: Cross-process wire-span sink (obs.span_dir) — None when wire
        #: tracing is off; may be live even when ``enabled`` is False
        #: (fleet engine workers journal spans with the rest of obs off).
        self.spans = spans
        self.exporter = exporter
        # obs.flight_recorder=false means NO ring feeding and NO bundle —
        # the attribute stays a (never-dumped) recorder so attribute access
        # is uniform, but record()/dump_flight() gate on _flight_on.
        self._flight_on = self.enabled and flight is not None
        self.flight = flight if flight is not None else FlightRecorder(1)
        #: Roofline capture (obs.roofline) — None when disabled, so callers
        #: gate on ONE attribute read and a disabled run pays nothing.
        self.roofline = roofline
        self._log_handler = log_handler
        self._closed = False

    # -- hot-loop surface ------------------------------------------------

    def span(self, name: str, **args: Any):
        return self.tracer.span(name, **args)

    def record(self, kind: str, **payload: Any) -> None:
        if self._flight_on:
            self.flight.record(kind, **payload)

    # -- failure path ----------------------------------------------------

    def dump_flight(self, *, reason: str, **context: Any) -> str | None:
        """Write the forensic bundle into the run dir; None when the flight
        recorder (or obs entirely) is disabled."""
        if not self._flight_on:
            return None
        path = os.path.join(self.run_dir, FLIGHT_BUNDLE)
        out = self.flight.dump(path, reason=reason, **context)
        self.tracer.instant("flight_recorder_dump", reason=reason)
        return out

    # -- lifecycle -------------------------------------------------------

    def flush(self) -> None:
        """Make everything durable without ending the run (terminal loop
        states flush; only Orchestrator.stop()/close() tear down)."""
        if self.spans is not None:
            self.spans.flush()
        if not self.enabled:
            return
        self.tracer.flush()
        if self.exporter is not None:
            try:
                self.exporter.drain()
            except Exception:
                pass            # export IO never outranks the run itself

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.spans is not None:
            self.spans.close()
        if self.exporter is not None:
            self.exporter.stop()
        self.tracer.close()
        if self._log_handler is not None:
            logging.getLogger("sharetrade").removeHandler(self._log_handler)
            self._log_handler = None


def build_obs(cfg: Any, registry: Any, *, mesh: Any = None) -> Obs:
    """Construct the run's telemetry from ``cfg.obs``; inert when disabled
    (no directory is created, nothing is opened)."""
    oc = cfg.obs

    def _span_sink() -> SpanSink | None:
        # Wire-span journal (ISSUE 17): created iff obs.span_dir names a
        # directory — INDEPENDENT of oc.enabled, because fleet engine
        # workers run with obs off (telemetry stays with the fleet
        # process) yet must journal their half of every stitched trace.
        span_dir = getattr(oc, "span_dir", "")
        if not span_dir:
            return None
        proc = getattr(oc, "span_proc", "") or f"p{os.getpid()}"
        journal = SpanJournal(
            span_dir, proc,
            max_records=getattr(oc, "span_journal_records", 4096),
            max_segments=getattr(oc, "span_journal_segments", 8))
        return SpanSink(journal)

    if not oc.enabled:
        spans = _span_sink()
        return Obs(spans=spans) if spans is not None else Obs()
    run_dir = oc.dir
    os.makedirs(run_dir, exist_ok=True)
    write_manifest(os.path.join(run_dir, "manifest.json"), cfg, mesh=mesh)
    tracer = SpanTracer(os.path.join(run_dir, "trace.jsonl")
                        if oc.trace else None)
    exporter = None
    if oc.metrics_export:
        exporter = MetricsExporter(registry, run_dir,
                                   interval_s=oc.export_interval_s)
        exporter.start()
    flight = log_handler = None
    if oc.flight_recorder:
        flight = FlightRecorder(oc.flight_capacity)
        log_handler = RingLogHandler(flight)
        logging.getLogger("sharetrade").addHandler(log_handler)
    roofline = None
    if oc.roofline:
        # Discrepancy warnings land in the flight ring (when one exists) so
        # a later forensic dump names the miscounted program.
        roofline = RooflineCapture(
            registry, run_dir,
            flight_record=flight.record if flight is not None else None)
    return Obs(run_dir=run_dir, tracer=tracer, exporter=exporter,
               flight=flight, log_handler=log_handler, roofline=roofline,
               spans=_span_sink())


def summarize_run_dir(run_dir: str) -> dict:
    """The ``cli obs`` summary: what a run dir contains, condensed to one
    JSON object (manifest identity, span aggregates, metrics tail, flight
    bundle verdict)."""
    out: dict[str, Any] = {"run_dir": run_dir}
    manifest_tuning = None
    manifest_path = os.path.join(run_dir, "manifest.json")
    if os.path.isfile(manifest_path):
        with open(manifest_path, encoding="utf-8") as f:
            m = json.load(f)
        out["manifest"] = {k: m.get(k) for k in (
            "config_hash", "backend", "device_count", "mesh_shape",
            "git_rev", "created_at")}
        manifest_tuning = m.get("tuning")
    if manifest_tuning:
        # Self-tuning provenance (tuning.py, stamped into the manifest):
        # the active profile + fingerprint and, per registered knob, the
        # resolved value vs its default and which tier won (explicit /
        # profile / default) — enriched below with the live controller
        # gauges when the run exported metrics.
        out["tuning"] = {
            "profile": manifest_tuning.get("profile"),
            "profile_error": manifest_tuning.get("profile_error"),
            "fingerprint": manifest_tuning.get("fingerprint"),
            "knobs": {
                path: {"value": info.get("value"),
                       "default": info.get("default"),
                       "source": info.get("source")}
                for path, info in sorted(
                    (manifest_tuning.get("knobs") or {}).items())},
        }
    trace_path = os.path.join(run_dir, "trace.jsonl")
    if os.path.isfile(trace_path):
        spans: dict[str, dict[str, float]] = {}
        for ev in read_trace(trace_path):
            if ev.get("ph") != "X":
                continue
            agg = spans.setdefault(ev["name"].split(":")[0],
                                   {"count": 0, "total_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] += ev.get("dur", 0.0) / 1e3
        out["trace"] = {
            name: {"count": int(a["count"]),
                   "total_ms": round(a["total_ms"], 3),
                   "mean_ms": round(a["total_ms"] / a["count"], 3)}
            for name, a in sorted(spans.items())}
    metrics_path = os.path.join(run_dir, "metrics.jsonl")
    if os.path.isfile(metrics_path):
        last = None
        drains = 0
        with open(metrics_path, encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    drains += 1
                    last = line
        last_rec = json.loads(last) if last else None
        counters = (last_rec or {}).get("counters") or {}
        out["metrics"] = {
            "drains": drains,
            "last": last_rec,
            # Counter TOTALS surfaced at the top level of the summary (the
            # exporter's last drain is cumulative — counters are monotone),
            # with the pipeline-health number called out explicitly so an
            # operator doesn't have to know the registry key.
            "counters": counters,
            "pipeline_stalls_total": counters.get(
                "pipeline_stalls_total", 0.0),
            "prom_file": os.path.isfile(
                os.path.join(run_dir, "metrics.prom")),
        }
        gauges = (last_rec or {}).get("gauges") or {}
        hists = (last_rec or {}).get("histograms") or {}
        if hists:
            # Histogram tails in one glanceable block: per-metric count +
            # p50/p99 derived from the exported buckets (the same bucket
            # math a fleet aggregator runs after merging engines).
            out["histograms"] = {
                name: {"count": snap.get("count", 0),
                       "p50": round(quantile_from_snapshot(snap, 0.50), 3),
                       "p99": round(quantile_from_snapshot(snap, 0.99), 3)}
                for name, snap in sorted(hists.items())}
        if ("replay_size" in gauges
                or any(k.startswith(("per_", "journal_"))
                       for k in list(gauges) + list(counters))):
            # Replay data plane (journaled DQN runs): buffer fill, PER
            # priority/anneal state, and the bounded-journal segment
            # telemetry in one glanceable block.
            out["replay"] = {
                "replay_size": gauges.get("replay_size"),
                "per_max_priority": gauges.get("per_max_priority"),
                "per_beta": gauges.get("per_beta"),
                "journal_segments": gauges.get("journal_segments"),
                "journal_segments_retired_total": counters.get(
                    "journal_segments_retired_total", 0.0),
                "journal_compacted_bytes_total": counters.get(
                    "journal_compacted_bytes_total", 0.0),
            }
        if any(k.startswith(("actors_", "actor_", "distrib_"))
               for k in list(gauges) + list(counters)):
            # Actor/learner disaggregation (distrib/): pool membership,
            # supervision counters, per-actor ingest volume and heartbeat
            # ages in one glanceable block — the operator's "is the fleet
            # healthy and is the learner actually eating its output"
            # answer without knowing the registry keys.
            per_actor_rows = {
                k[len("actor_rows_ingested_total_"):]: v
                for k, v in counters.items()
                if k.startswith("actor_rows_ingested_total_")}
            heartbeat_ages = {
                k[len("actor_heartbeat_age_s_"):]: round(v, 3)
                for k, v in gauges.items()
                if k.startswith("actor_heartbeat_age_s_")}
            out["actors"] = {
                "alive": gauges.get("actors_alive"),
                "failed": gauges.get("actors_failed"),
                "backoff": gauges.get("actors_backoff"),
                "restarts_total": counters.get(
                    "actor_restarts_total", 0.0),
                "rows_ingested_total": counters.get(
                    "distrib_rows_ingested_total", 0.0),
                "feeds": gauges.get("distrib_actor_feeds"),
                "rows_ingested_by_actor": per_actor_rows,
                "heartbeat_age_s": heartbeat_ages,
            }
        if any(k.startswith("serve_") for k in list(gauges)
               + list(counters)):
            # Serving tier (``cli serve`` run dirs): the SLO surface in
            # one glanceable block — QPS, latency percentiles, batching
            # health — without the operator knowing the registry keys.
            out["serve"] = {
                "qps": gauges.get("serve_qps"),
                "p50_ms": gauges.get("serve_p50_ms"),
                "p99_ms": gauges.get("serve_p99_ms"),
                "batch_occupancy": gauges.get("serve_batch_occupancy"),
                "queue_depth": gauges.get("serve_queue_depth"),
                "requests_total": counters.get("serve_requests_total", 0.0),
                "batches_total": counters.get("serve_batches_total", 0.0),
                "prefills_total": counters.get("serve_prefills_total", 0.0),
                "evictions_total": counters.get(
                    "serve_evictions_total", 0.0),
                "swaps_total": counters.get("serve_swaps_total", 0.0),
                "swaps_rejected_total": counters.get(
                    "serve_swap_rejected_total", 0.0),
                # Overload & failure surface (ISSUE 10): shedding,
                # deadline expiry, supervised restarts, and the hot-swap
                # breaker in the same glanceable block.
                "overload": gauges.get("serve_overload"),
                "shed_total": counters.get("serve_shed_total", 0.0),
                "queue_rejected_total": counters.get(
                    "serve_queue_rejected_total", 0.0),
                "deadline_expired_total": counters.get(
                    "serve_deadline_expired_total", 0.0),
                "restarts_total": counters.get("serve_restarts_total", 0.0),
                "engine_failed": gauges.get("serve_failed"),
                "swap_breaker_open": gauges.get("serve_swap_breaker_open"),
                "swap_breaker_opens_total": counters.get(
                    "serve_swap_breaker_opens_total", 0.0),
                # Request-level observability (ISSUE 11): per-stage tail
                # decomposition, SLO burn rates, trace-health counters.
                "slo_availability_burn": gauges.get(
                    "serve_slo_availability_burn"),
                "slo_latency_burn": gauges.get("serve_slo_latency_burn"),
                "slo_burn_alerts_total": counters.get(
                    "serve_slo_burn_alerts_total", 0.0),
                "trace_decomposition_errors_total": counters.get(
                    "serve_trace_decomposition_error_total", 0.0),
            }
            stages = {}
            for stage in SERVE_STAGES:
                snap = hists.get(f"serve_{stage}_ms")
                if snap and snap.get("count"):
                    stages[stage] = {
                        "count": snap["count"],
                        "p50_ms": round(
                            quantile_from_snapshot(snap, 0.50), 3),
                        "p99_ms": round(
                            quantile_from_snapshot(snap, 0.99), 3)}
            if stages:
                out["serve"]["stages"] = stages
        if any(k.startswith(("serve_sessions_", "serve_warm_"))
               for k in list(gauges) + list(counters)):
            # Session tiers (ISSUE 18): the hot/warm/cold population and
            # the paging economics in one glanceable block — how many
            # sessions ride device slots vs the host-RAM warm tier, the
            # warm hit rate (a warm hit skips a cold re-prefill), bytes
            # held vs budget, and the live ms-saved-per-MB gauge that
            # answers "is the warm tier paying for its RAM".
            hits = counters.get("serve_warm_hits_total", 0.0)
            misses = counters.get("serve_warm_misses_total", 0.0)
            lookups = hits + misses
            out["sessions"] = {
                "hot": gauges.get("serve_sessions_hot"),
                "warm": gauges.get("serve_warm_sessions"),
                "warm_bytes": gauges.get("serve_warm_bytes"),
                "warm_budget_bytes": gauges.get(
                    "serve_warm_budget_bytes"),
                "warm_parks_total": counters.get(
                    "serve_warm_parks_total", 0.0),
                "warm_hits_total": hits,
                "warm_misses_total": misses,
                "warm_hit_rate": (round(hits / lookups, 4)
                                  if lookups else None),
                "warm_demotions_total": counters.get(
                    "serve_warm_demotions_total", 0.0),
                "warm_stale_drops_total": counters.get(
                    "serve_warm_stale_drops_total", 0.0),
                # Cold tier = sessions resumable only through the
                # journal re-prefill path (serve_prefills_total counts
                # every cold entry, first-time or paged back in).
                "cold_prefills_total": counters.get(
                    "serve_prefills_total", 0.0),
                "econ_ms_per_mb": gauges.get(
                    "serve_warm_econ_ms_per_mb"),
            }
            if (gauges.get("serve_spill_budget_bytes")
                    or counters.get("serve_spill_puts_total")):
                # The 4th rung (ISSUE 20): the crash-consistent disk
                # arena under the warm tier — how many carries sit
                # spilled, the adoption split after a migration (warm =
                # step stamp matched, cold = stale/torn/CRC-bad record
                # demoted to prefill), and how often records were
                # refused/corrupt. econ_ms_per_mb above already prices
                # spill hits — an adoption re-enters through the warm
                # store, so its saved prefill lands in warm_hits_total.
                out["sessions"]["spill"] = {
                    "sessions": gauges.get("serve_spill_sessions"),
                    "bytes": gauges.get("serve_spill_bytes"),
                    "budget_bytes": gauges.get(
                        "serve_spill_budget_bytes"),
                    "puts_total": counters.get(
                        "serve_spill_puts_total", 0.0),
                    "put_refusals_total": counters.get(
                        "serve_spill_put_refusals_total", 0.0),
                    "hits_total": counters.get(
                        "serve_spill_hits_total", 0.0),
                    "misses_total": counters.get(
                        "serve_spill_misses_total", 0.0),
                    "stale_total": counters.get(
                        "serve_spill_stale_total", 0.0),
                    "corrupt_total": counters.get(
                        "serve_spill_corrupt_total", 0.0),
                    "adopt_warm_total": counters.get(
                        "serve_adopt_warm_total", 0.0),
                    "adopt_cold_total": counters.get(
                        "serve_adopt_cold_total", 0.0),
                }
        if (manifest_tuning
                or any(k.startswith(("serve_knob_", "serve_controller_",
                                     "ingest_"))
                       for k in list(gauges) + list(counters))):
            # Live self-tuning state (ISSUE 14): current knob values as
            # the controllers last set them, adjustment counters, and
            # the last objective reading — next to the provenance block
            # above so "what is it tuned to" and "who set it" read as
            # one section.
            tuning_out = out.setdefault("tuning", {})
            tuning_out["live"] = {
                "serve_batch_timeout_ms": gauges.get(
                    "serve_knob_batch_timeout_ms"),
                "serve_max_queue": gauges.get("serve_knob_max_queue"),
                "controller_adjustments_total": counters.get(
                    "serve_controller_adjustments_total", 0.0),
                "controller_target_p99_ms": gauges.get(
                    "serve_controller_target_p99_ms"),
                "controller_last_p99_ms": gauges.get(
                    "serve_controller_p99_ms"),
                "ingest_every_updates_current": gauges.get(
                    "ingest_every_updates_current"),
                "ingest_adjustments_total": counters.get(
                    "ingest_adjustments_total", 0.0),
            }
    fleet_path = os.path.join(run_dir, "fleet_status.json")
    if os.path.isfile(fleet_path):
        # Fleet serving tier (``cli fleet`` / fleet/router.py): the
        # router's atomically-rewritten status — per-engine membership +
        # routing telemetry, merged-histogram fleet quantiles, affinity
        # table size, swap-propagation lag — condensed the same way the
        # other sections are (no registry-key spelunking required).
        try:
            with open(fleet_path, encoding="utf-8") as f:
                fs = json.load(f)
        except (OSError, ValueError):
            fs = None
        if fs:
            pool = fs.get("pool") or {}
            telemetry = fs.get("telemetry") or {}
            fgauges = fs.get("gauges") or {}
            engines = {}
            for eid, e in (pool.get("engines") or {}).items():
                t = telemetry.get(eid) or {}
                engines[eid] = {
                    "state": e.get("state"), "pid": e.get("pid"),
                    "port": e.get("port"),
                    "restarts": e.get("restarts"),
                    "params_step": e.get("params_step"),
                    "queue_depth": e.get("queue_depth"),
                    "window_p99_ms": t.get("window_p99_ms"),
                }
            out["fleet"] = {
                "engines": engines,
                "alive": pool.get("alive"),
                "failed": pool.get("failed"),
                "restarts_total": pool.get("restarts_total"),
                "engines_live": (fs.get("router") or {}).get(
                    "engines_live"),
                "merged_p50_ms": fgauges.get("fleet_p50_ms"),
                "merged_p99_ms": fgauges.get("fleet_p99_ms"),
                "merged_request_ms": fs.get("fleet_request_ms"),
                "affinity_sessions": (fs.get("router") or {}).get(
                    "affinity_sessions"),
                "swap_lag_steps": fgauges.get("fleet_swap_lag_steps"),
                "slo_availability_burn": fgauges.get(
                    "fleet_slo_availability_burn"),
                # Spill-tier migration outcomes (ISSUE 20): fleet-wide
                # parked-on-disk footprint plus the warm-vs-cold
                # adoption split after engine deaths/drains.
                "spill_sessions": fgauges.get("fleet_spill_sessions"),
                "spill_bytes": fgauges.get("fleet_spill_bytes"),
                "adopt_warm_total": (fs.get("counters") or {}).get(
                    "fleet_adopt_warm_total", 0.0),
                "adopt_cold_total": (fs.get("counters") or {}).get(
                    "fleet_adopt_cold_total", 0.0),
                "counters": fs.get("counters"),
                # Selector-thread internals (ISSUE 19): which HTTP
                # parse path is live (native C vs Python), open
                # keep-alive connections, and the loop's backpressure
                # and deadline-wheel counters.
                "evloop": {
                    "proto_backend": (
                        "native"
                        if fgauges.get("fleet_proto_backend_native")
                        else "py"
                        if "fleet_proto_backend_native" in fgauges
                        else None),
                    "open_conns": fgauges.get("fleet_evloop_open_conns"),
                    "backpressure_pauses_total": (fs.get("counters")
                                                  or {}).get(
                        "fleet_evloop_backpressure_pauses_total", 0.0),
                    "deadline_expiries_total": (fs.get("counters")
                                                or {}).get(
                        "fleet_evloop_deadline_expiries_total", 0.0),
                },
            }
    autoscale_path = os.path.join(run_dir, "fleet_autoscale.json")
    if os.path.isfile(autoscale_path):
        # Fleet autoscaler (ISSUE 18, fleet/autoscale.py): the membership
        # control loop's atomically-rewritten state — current target vs
        # actual engines, the operator bounds, and the last applied
        # decision with its reason. Folded into the "sessions" section
        # so paging capacity and fleet capacity read as one story.
        try:
            with open(autoscale_path, encoding="utf-8") as f:
                a = json.load(f)
        except (OSError, ValueError):
            a = None
        if a:
            out.setdefault("sessions", {})["autoscaler"] = {
                "target": a.get("target"), "actual": a.get("actual"),
                "floor": a.get("floor"), "ceiling": a.get("ceiling"),
                "decisions": a.get("decisions"),
                "last_decision": a.get("last_decision"),
            }
    exemplars_path = os.path.join(run_dir, "serve_exemplars.json")
    if os.path.isfile(exemplars_path):
        with open(exemplars_path, encoding="utf-8") as f:
            ex = (json.load(f).get("exemplars") or [])[:5]
        if ex:
            # The K slowest requests with their stage breakdown — the
            # "why was the tail slow" answer without opening the trace.
            out.setdefault("serve", {})["slowest_exemplars"] = ex
    roofline = read_roofline(run_dir)
    if roofline is not None:
        out["roofline"] = summarize_roofline(roofline)
    flight_path = os.path.join(run_dir, FLIGHT_BUNDLE)
    if os.path.isfile(flight_path):
        with open(flight_path, encoding="utf-8") as f:
            bundle = json.load(f)
        out["flight_recorder"] = {
            "reason": bundle.get("reason"),
            "failing_chunk": bundle.get("failing_chunk"),
            "context": bundle.get("context"),
            "events": len(bundle.get("events", [])),
        }
    return out
