"""Roofline telemetry: compiled-cost capture + live MFU/HBM gauges.

The ROADMAP's MFU push starts with measurement: MFU existed only as an
after-the-fact analytic number in ``bench.py`` (utils/flops.py), invisible
during training and ungated in CI. This module makes device utilization a
first-class run-time health signal (the Podracer stance, arxiv 2104.06272):

- **Compile time** — :meth:`RooflineCapture.capture` records XLA
  ``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
  (argument/temp/output bytes) for every jitted (mega)chunk program, via
  the ``cost_hook`` seam in ``parallel/sharding.py jit_parallel_step`` (the
  mesh path) and the orchestrator's CPU-fallback build. Capture costs ONE
  extra AOT lowering+compile per program at build time — never a per-step
  cost — and a capture failure degrades observability, never the run.
  The XLA FLOP count is cross-checked against the analytic
  ``utils/flops.py`` model: a >25% discrepancy is a counting bug in one of
  the two and warns through the flight recorder.
- **Run time** — :meth:`RooflineCapture.on_boundary`, called from the
  pipeline CONSUMER thread (never the dispatcher), divides the captured
  static costs by the measured per-chunk wall time (StepTimer's sampled
  ``chunk_seconds``) and publishes ``mfu``, ``achieved_tflops``,
  ``hbm_gbps``, ``arithmetic_intensity`` and ``roofline_compute_bound``
  gauges through the existing MetricsRegistry → Prometheus path.
- **Artifact** — a schema-versioned ``roofline.json`` in the run dir (one
  entry per captured program: static costs, arithmetic intensity, the
  compute-bound vs memory-bound classification against the chip's ridge
  point), summarized by ``cli obs`` and regression-gated by
  ``tools/shard_audit.py`` (manifest FLOPs/HBM rows) and
  ``tools/perf_gate.py`` (bench-row MFU bands).

Everything is gated by ``ObsConfig.roofline`` (off by default): disabled
means no capture compile, no gauges, no file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable

from sharetrade_tpu.utils.logging import get_logger

log = get_logger("obs.roofline")

SCHEMA_VERSION = 1
ARTIFACT = "roofline.json"

#: Analytic-vs-XLA FLOP band: outside ±25% one of the two counts is wrong
#: (the analytic model drifted from the model code, or the workload's
#: non-matmul FLOPs stopped being negligible) — worth a flight-recorder
#: warning either way.
DISCREPANCY_BAND = 0.25


@dataclass
class ProgramCost:
    """Static compiled-cost record for ONE (mega)chunk program.

    ``flops``/``bytes_accessed`` are PER DISPATCH, trip-count corrected:
    XLA's ``HloCostAnalysis`` counts a while/scan body ONCE (the trip
    count is not statically known to it), so a chunk program — a
    ``lax.scan`` over ``chunk_steps`` env steps, possibly nested in the
    K-megachunk scan — reports ~1/(chunk_steps*K) of the dispatch's real
    arithmetic. :class:`RooflineCapture` probes the attached backend once
    (two tiny scans of different lengths — equal counts mean blind) and
    multiplies by the known loop iterations when, and only when, the
    probe shows blindness; the uncorrected numbers stay in
    ``flops_hlo_once``/``bytes_hlo_once`` so the artifact is auditable.

    The uniform correction is exact for the value-based chunk programs (a
    scan of ``chunk_steps`` identical env-step bodies) but OVERCOUNTS
    programs whose dominant FLOPs live outside that scan — the episode-
    mode PPO chunk runs its banded trunk as ONE pass and its replay as
    epoch×minibatch passes, none of them ``chunk_steps``-deep (measured:
    ~150x over on the flagship). The analytic cross-check catches exactly
    this: when the corrected XLA count leaves the ±25% band and the
    analytic model is available, the LIVE GAUGES switch to the analytic
    count (``gauge_flops_source="analytic"`` — the PaLM-convention
    model-FLOPs MFU, and the same counting behind BENCH_r03's 0.16
    flagship anchor), with bytes scaled by the same factor (intensity is
    scale-invariant under the uniform correction, so the classification
    holds either way). Agreement keeps the XLA count
    (``gauge_flops_source="xla"``). Both numbers, the ratio, and the
    chosen source are in the artifact — nothing is silently blended."""

    label: str
    megachunk_factor: int
    devices: int                  # mesh size the program was partitioned for
    flops: float | None           # per DEVICE per dispatch (SPMD programs
                                  # report the per-device partition; the
                                  # chip-relative gauges want exactly that)
    bytes_accessed: float | None
    flops_hlo_once: float | None  # raw cost_analysis (loop body once)
    bytes_hlo_once: float | None
    loop_iterations: int          # chunk_steps x megachunk_factor
    trip_count_corrected: bool
    argument_bytes: int | None
    temp_bytes: int | None
    output_bytes: int | None
    peak_bytes: int | None        # args + temps + output: the HBM footprint
    arithmetic_intensity: float | None   # FLOPs per byte accessed
    classification: str | None    # "compute-bound" | "memory-bound"
    analytic_flops: float | None  # utils/flops.py model, same dispatch span
    xla_vs_analytic: float | None
    discrepancy: bool = False
    gauge_flops: float | None = None       # what the live gauges divide
    gauge_bytes: float | None = None
    gauge_flops_source: str | None = None  # "xla" | "analytic"

    def flops_per_chunk(self) -> float | None:
        if self.gauge_flops is None:
            return None
        return self.gauge_flops / max(1, self.megachunk_factor)

    def bytes_per_chunk(self) -> float | None:
        if self.gauge_bytes is None:
            return None
        return self.gauge_bytes / max(1, self.megachunk_factor)


def compiled_costs(compiled: Any) -> dict[str, float | int | None]:
    """FLOPs / bytes-accessed / memory split of one ``jax.stages.Compiled``.

    Tolerates every backend quirk seen so far: ``cost_analysis()`` returns
    a dict on some jax versions and a one-per-device list on others; either
    analysis may be missing or raise; absent keys report None (the
    consumers treat None as "unavailable", never zero)."""
    out: dict[str, float | int | None] = {
        "flops": None, "bytes_accessed": None, "argument_bytes": None,
        "temp_bytes": None, "output_bytes": None,
    }
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", -1.0))
        ba = float(ca.get("bytes accessed", -1.0))
        # XLA reports -1 where a backend doesn't implement the counter.
        out["flops"] = flops if flops >= 0 else None
        out["bytes_accessed"] = ba if ba >= 0 else None
    except Exception:
        log.debug("cost_analysis unavailable", exc_info=True)
    try:
        mem = compiled.memory_analysis()
        out["argument_bytes"] = int(mem.argument_size_in_bytes)
        out["temp_bytes"] = int(mem.temp_size_in_bytes)
        out["output_bytes"] = int(mem.output_size_in_bytes)
    except Exception:
        log.debug("memory_analysis unavailable", exc_info=True)
    return out


def _probe_trip_count_blind() -> bool:
    """Does this backend's cost analysis count loop bodies once?

    Compiles two tiny scans differing only in length; equal FLOP counts
    mean the analysis is trip-count blind (XLA's documented
    ``HandleWhile`` behavior) and per-dispatch costs need the known-
    iteration correction. Probed empirically rather than assumed so a
    backend that starts multiplying trip counts is never double-counted.
    Defaults to True (the documented behavior) when the probe can't run.
    """
    try:
        import jax
        import jax.numpy as jnp

        def make(n):
            def f(x):
                def body(c, _):
                    return c @ c, None
                c, _ = jax.lax.scan(body, x, None, length=n)
                return c
            return jax.jit(f)

        x = jnp.ones((8, 8))
        counts = []
        for n in (2, 8):
            costs = compiled_costs(make(n).lower(x).compile())
            if costs["flops"] is None:
                return True
            counts.append(costs["flops"])
        return counts[0] == counts[1]
    except Exception:
        return True


class RooflineCapture:
    """Per-run roofline state: captured program costs + live gauge math.

    Thread contract: :meth:`capture` runs at build time (host, before
    training); :meth:`on_boundary` runs on the pipeline consumer thread;
    the artifact write is lock-guarded so a late capture (megachunk
    program built after the chunk program) can't tear the JSON.
    """

    def __init__(self, registry: Any, run_dir: str | None, *,
                 peak_flops: float | None = None,
                 peak_hbm_bw: float | None = None,
                 flight_record: Callable[..., None] | None = None):
        if peak_flops is None or peak_hbm_bw is None:
            from sharetrade_tpu.utils.flops import (chip_peak_flops,
                                                    chip_peak_hbm_bw)
            peak_flops = peak_flops or chip_peak_flops()
            peak_hbm_bw = peak_hbm_bw or chip_peak_hbm_bw()
        self.registry = registry
        self.run_dir = run_dir
        self.peak_flops = float(peak_flops)
        self.peak_hbm_bw = float(peak_hbm_bw)
        #: FLOPs/byte above which a program is compute-bound on this chip.
        self.ridge = self.peak_flops / self.peak_hbm_bw
        #: Analytic model FLOPs for ONE chunk's dispatch span
        #: (train_flops_per_agent_step x workers x chunk_steps); the
        #: orchestrator sets it once the env's obs_dim is known. None
        #: disables the cross-check, never the capture.
        self.analytic_flops_per_chunk: float | None = None
        #: Env steps one chunk scans over (runtime.chunk_steps) — the
        #: inner loop trip count of every captured program; the
        #: orchestrator sets it before the programs build.
        self.steps_per_chunk: int = 1
        #: Precision mode the captured programs compiled under
        #: (config.PrecisionConfig.mode) — recorded in the artifact so a
        #: bytes/AI movement is attributable to the compute tier, and so
        #: perf tooling never compares rooflines across precisions.
        self.precision_mode: str | None = None
        self.programs: dict[str, ProgramCost] = {}
        self._by_factor: dict[int, ProgramCost] = {}
        self._flight_record = flight_record
        self._lock = threading.Lock()
        self._trip_blind: bool | None = None   # probed lazily, once

    # -- compile-time capture -------------------------------------------

    def capture(self, fn: Any, args: tuple, *, megachunk_factor: int = 1,
                devices: int = 1,
                label: str | None = None) -> ProgramCost | None:
        """AOT-lower ``fn(*args)``, record its compiled costs, cross-check
        the analytic model, refresh the artifact. Never raises.

        ``devices``: the mesh size the program is partitioned over. XLA's
        ``cost_analysis()`` describes the PER-DEVICE partition of an SPMD
        program, so the analytic (global-work) model is divided by the
        device count before the cross-check — and the gauges stay
        per-chip, which is what MFU against a per-chip peak means."""
        label = label or (f"megachunk_k{megachunk_factor}"
                          if megachunk_factor > 1 else "chunk")
        try:
            compiled = fn.lower(*args).compile()
            costs = compiled_costs(compiled)
        except Exception:
            log.warning("roofline capture failed for %r; program stays "
                        "uninstrumented", label, exc_info=True)
            return None
        cost = self._build_cost(label, megachunk_factor, costs,
                                devices=max(1, int(devices)))
        with self._lock:
            self.programs[label] = cost
            self._by_factor[megachunk_factor] = cost
            self._write_artifact_locked()
        self._cross_check(cost)
        return cost

    def _build_cost(self, label: str, k: int, costs: dict[str, Any],
                    *, devices: int = 1) -> ProgramCost:
        raw_flops, raw_ba = costs["flops"], costs["bytes_accessed"]
        if self._trip_blind is None:
            self._trip_blind = _probe_trip_count_blind()
        iters = max(1, self.steps_per_chunk) * max(1, k)
        corrected = self._trip_blind and iters > 1
        scale = iters if corrected else 1
        flops = raw_flops * scale if raw_flops is not None else None
        ba = raw_ba * scale if raw_ba is not None else None
        ai = (flops / ba) if flops and ba else None
        classification = None
        if ai is not None:
            classification = ("compute-bound" if ai >= self.ridge
                              else "memory-bound")
        peak_bytes = None
        if costs["argument_bytes"] is not None:
            peak_bytes = (costs["argument_bytes"]
                          + (costs["temp_bytes"] or 0)
                          + (costs["output_bytes"] or 0))
        # The analytic model counts GLOBAL work (all workers); the SPMD
        # program's cost_analysis describes one device's partition, so the
        # comparison (and the analytic gauge fallback) is per device.
        analytic = (self.analytic_flops_per_chunk * k / devices
                    if self.analytic_flops_per_chunk else None)
        ratio = (flops / analytic) if flops and analytic else None
        discrepancy = (ratio is not None
                       and abs(ratio - 1.0) > DISCREPANCY_BAND)
        # Gauge source selection (see the ProgramCost docstring): XLA when
        # it agrees with (or there is no) analytic model; analytic when the
        # trip-count correction structurally misfits the program. Bytes
        # ride the same factor — arithmetic intensity is preserved.
        if discrepancy and analytic:
            gauge_flops, source = analytic, "analytic"
            gauge_bytes = ba * (analytic / flops) if ba and flops else ba
        else:
            gauge_flops = flops if flops is not None else analytic
            source = ("xla" if flops is not None
                      else ("analytic" if analytic else None))
            gauge_bytes = ba
        return ProgramCost(
            label=label, megachunk_factor=k, devices=devices, flops=flops,
            bytes_accessed=ba,
            flops_hlo_once=raw_flops, bytes_hlo_once=raw_ba,
            loop_iterations=iters, trip_count_corrected=corrected,
            argument_bytes=costs["argument_bytes"],
            temp_bytes=costs["temp_bytes"],
            output_bytes=costs["output_bytes"],
            peak_bytes=peak_bytes,
            arithmetic_intensity=ai, classification=classification,
            analytic_flops=analytic, xla_vs_analytic=ratio,
            discrepancy=discrepancy,
            gauge_flops=gauge_flops, gauge_bytes=gauge_bytes,
            gauge_flops_source=source)

    def _cross_check(self, cost: ProgramCost) -> None:
        if not cost.discrepancy:
            return
        msg = (f"roofline FLOP cross-check: XLA counts "
               f"{cost.flops:.3e} FLOPs for {cost.label} but the analytic "
               f"model (utils/flops.py) expects {cost.analytic_flops:.3e} "
               f"(ratio {cost.xla_vs_analytic:.2f}) — one of the two "
               "countings is wrong (or the program's FLOPs live outside "
               "its chunk-steps scan); live gauges use the analytic count")
        log.warning(msg)
        if self._flight_record is not None:
            self._flight_record("roofline_discrepancy", program=cost.label,
                                xla_flops=cost.flops,
                                analytic_flops=cost.analytic_flops,
                                ratio=cost.xla_vs_analytic)

    # -- run-time gauges (consumer thread) ------------------------------

    def on_boundary(self, *, k: int, chunk_seconds: float | None) -> None:
        """Combine static costs with the sampled per-chunk wall time into
        live gauges. Rides the metrics sampling cadence on the pipeline
        consumer thread — gauge math never touches the dispatcher."""
        if not chunk_seconds or chunk_seconds <= 0:
            return
        cost = self._by_factor.get(k) or self._by_factor.get(1)
        if cost is None:
            return
        flops = cost.flops_per_chunk()
        ba = cost.bytes_per_chunk()
        gauges: dict[str, float] = {}
        if flops:
            achieved = flops / chunk_seconds
            gauges["achieved_tflops"] = achieved / 1e12
            gauges["mfu"] = achieved / self.peak_flops
        if ba:
            gauges["hbm_gbps"] = ba / chunk_seconds / 1e9
        if cost.arithmetic_intensity is not None:
            gauges["arithmetic_intensity"] = cost.arithmetic_intensity
            gauges["roofline_compute_bound"] = float(
                cost.classification == "compute-bound")
        if gauges:
            self.registry.record_many(gauges)

    # -- artifact -------------------------------------------------------

    def _bundle_locked(self) -> dict:
        """The artifact/summary object — caller holds ``self._lock``."""
        return {
            "schema_version": SCHEMA_VERSION,
            "precision_mode": self.precision_mode,
            "peak_flops_per_s": self.peak_flops,
            "peak_hbm_bytes_per_s": self.peak_hbm_bw,
            "ridge_flops_per_byte": self.ridge,
            "analytic_flops_per_chunk": self.analytic_flops_per_chunk,
            "programs": {name: dataclasses.asdict(cost)
                         for name, cost in self.programs.items()},
        }

    def summary(self) -> dict:
        with self._lock:
            return self._bundle_locked()

    def _write_artifact_locked(self) -> None:
        if self.run_dir is None:
            return
        path = os.path.join(self.run_dir, ARTIFACT)
        try:
            bundle = self._bundle_locked()
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=2, default=str)
            os.replace(tmp, path)
        except Exception:       # artifact IO never outranks the run
            log.exception("roofline artifact write failed")


def read_roofline(run_dir: str) -> dict | None:
    """Load a run dir's roofline artifact; None when absent/unreadable."""
    path = os.path.join(run_dir, ARTIFACT)
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


def summarize_roofline(bundle: dict, *, top: int = 3) -> dict:
    """The ``cli obs`` condensation: per-program headline numbers plus the
    top compute-bound / memory-bound programs by FLOPs."""
    programs = bundle.get("programs", {})

    def _brief(name: str) -> dict:
        p = programs[name]
        return {
            "program": name,
            "flops": p.get("flops"),
            "bytes_accessed": p.get("bytes_accessed"),
            "arithmetic_intensity": p.get("arithmetic_intensity"),
            "discrepancy": p.get("discrepancy", False),
        }

    by_flops = sorted(
        (n for n in programs if programs[n].get("flops")),
        key=lambda n: programs[n]["flops"], reverse=True)
    return {
        "schema_version": bundle.get("schema_version"),
        "ridge_flops_per_byte": bundle.get("ridge_flops_per_byte"),
        "programs": len(programs),
        "compute_bound": [
            _brief(n) for n in by_flops
            if programs[n].get("classification") == "compute-bound"][:top],
        "memory_bound": [
            _brief(n) for n in by_flops
            if programs[n].get("classification") == "memory-bound"][:top],
    }
