"""Metrics exporter: MetricsRegistry → JSONL history + Prometheus textfile.

The registry holds everything in memory (it exists so mid-run queries never
stop the device loop); this exporter makes that state durable and scrapeable
without adding anything to the hot loop: a background thread drains
``registry.snapshot()``/``registry.counters()`` every ``interval_s`` seconds
into

- ``metrics.jsonl`` — one append-only line per drain (the full time series
  a notebook replays after the run), skipped when nothing changed;
- ``metrics.prom`` — a Prometheus textfile-collector snapshot (gauges +
  counters, atomically rewritten) for node_exporter-style scraping.

The training thread never blocks on exporter IO; a crashed exporter write
degrades observability, never the run.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from sharetrade_tpu.utils.logging import get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry

log = get_logger("obs.exporter")

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    return f"{prefix}_{_PROM_BAD.sub('_', name)}"


class MetricsExporter:
    def __init__(self, registry: MetricsRegistry, run_dir: str, *,
                 interval_s: float = 2.0, prefix: str = "sharetrade"):
        self._registry = registry
        self._jsonl_path = os.path.join(run_dir, "metrics.jsonl")
        self._prom_path = os.path.join(run_dir, "metrics.prom")
        self._interval_s = max(0.05, float(interval_s))
        self._prefix = prefix
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: tuple[dict, dict] | None = None
        self._io_lock = threading.Lock()   # drain() callable off-thread too

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-exporter", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.drain()
            except Exception:   # exporter IO must never kill anything
                log.exception("metrics export failed; will retry")

    def drain(self) -> bool:
        """One export pass; returns True when something was written."""
        gauges = self._registry.snapshot()
        counters = self._registry.counters()
        with self._io_lock:
            if (gauges, counters) == self._last:
                return False
            self._last = (gauges, counters)
            record = {"ts": time.time(), "gauges": gauges,
                      "counters": counters}
            with open(self._jsonl_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
            self._write_prom(gauges, counters)
        return True

    def _write_prom(self, gauges: dict, counters: dict) -> None:
        lines = []
        for name, value in sorted(gauges.items()):
            pname = _prom_name(name, self._prefix)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {value}")
        for name, value in sorted(counters.items()):
            pname = _prom_name(name, self._prefix)
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {value}")
        tmp = f"{self._prom_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        os.replace(tmp, self._prom_path)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        try:
            self.drain()        # final snapshot always lands on disk
        except Exception:
            log.exception("final metrics export failed")
