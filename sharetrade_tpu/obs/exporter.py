"""Metrics exporter: MetricsRegistry → JSONL history + Prometheus textfile.

The registry holds everything in memory (it exists so mid-run queries never
stop the device loop); this exporter makes that state durable and scrapeable
without adding anything to the hot loop: a background thread drains
``registry.snapshot()``/``registry.counters()`` every ``interval_s`` seconds
into

- ``metrics.jsonl`` — one append-only line per drain (the full time series
  a notebook replays after the run), skipped when nothing changed;
- ``metrics.prom`` — a Prometheus textfile-collector snapshot (gauges +
  counters + histograms in exposition format, atomically rewritten) for
  node_exporter-style scraping.

Histograms attached to the registry (obs/hist.py) export as the standard
``_bucket{le=...}`` / ``_sum`` / ``_count`` triple with CUMULATIVE bucket
counts ending in ``le="+Inf"`` — the mergeable form a fleet router can
scrape and bucket-wise add across engines. :func:`parse_prom_text` is the
strict round-trip reader (tests and ``tools/obs_demo.py`` validate every
export through it).

The training thread never blocks on exporter IO; a crashed exporter write
degrades observability, never the run.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from sharetrade_tpu.utils.logging import get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry

log = get_logger("obs.exporter")

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    return f"{prefix}_{_PROM_BAD.sub('_', name)}"


def _le(bound: float) -> str:
    """Prometheus ``le`` label text for a bucket bound: shortest exact-ish
    decimal (%.12g keeps the full double precision of the log-spaced
    bounds, so two engines' exports carry identical label sets — the
    merge-key contract)."""
    return f"{bound:.12g}"


def render_prom_text(gauges: dict, counters: dict,
                     hists: dict | None = None, *,
                     prefix: str = "sharetrade") -> str:
    """ONE definition of the Prometheus exposition this repo emits —
    the textfile the background exporter atomically rewrites AND the
    live ``/metrics`` body the fleet front-end serves over the wire
    (fleet/frontend.py). ``hists`` maps name → :meth:`~sharetrade_tpu.
    obs.hist.Histogram.snapshot` dicts; buckets export CUMULATIVE with
    ``le`` labels ending in ``+Inf`` (the merge contract
    :func:`parse_prom_text` validates on the scrape side)."""
    lines = []
    for name, value in sorted(gauges.items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    for name, value in sorted(counters.items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value}")
    for name, snap in sorted((hists or {}).items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for bound, c in zip(snap["bounds"], snap["counts"]):
            cum += c
            lines.append(f'{pname}_bucket{{le="{_le(bound)}"}} {cum}')
        cum += snap["counts"][len(snap["bounds"])]
        lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pname}_sum {snap['sum']}")
        lines.append(f"{pname}_count {snap['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsExporter:
    def __init__(self, registry: MetricsRegistry, run_dir: str, *,
                 interval_s: float = 2.0, prefix: str = "sharetrade"):
        self._registry = registry
        self._jsonl_path = os.path.join(run_dir, "metrics.jsonl")
        self._prom_path = os.path.join(run_dir, "metrics.prom")
        self._interval_s = max(0.05, float(interval_s))
        self._prefix = prefix
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: tuple[dict, dict] | None = None
        self._io_lock = threading.Lock()   # drain() callable off-thread too

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-exporter", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.drain()
            except Exception:   # exporter IO must never kill anything
                log.exception("metrics export failed; will retry")

    def drain(self) -> bool:
        """One export pass; returns True when something was written."""
        gauges = self._registry.snapshot()
        counters = self._registry.counters()
        hists = self._registry.histograms()
        with self._io_lock:
            if (gauges, counters, hists) == self._last:
                return False
            self._last = (gauges, counters, hists)
            record = {"ts": time.time(), "gauges": gauges,
                      "counters": counters}
            if hists:
                record["histograms"] = hists
            with open(self._jsonl_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
            self._write_prom(gauges, counters, hists)
        return True

    def _write_prom(self, gauges: dict, counters: dict,
                    hists: dict | None = None) -> None:
        text = render_prom_text(gauges, counters, hists,
                                prefix=self._prefix)
        tmp = f"{self._prom_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, self._prom_path)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        try:
            self.drain()        # final snapshot always lands on disk
        except Exception:
            log.exception("final metrics export failed")


# ---- strict exposition reader -----------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'^(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>[^"]*)"$')


class PromParseError(ValueError):
    """``metrics.prom`` violated the exposition format or a histogram
    invariant — the validity test's failure type."""


def parse_prom_text(text: str) -> dict:
    """STRICT parser for the exporter's Prometheus textfile output.

    Validates, line by line: every sample is ``name[{labels}] value`` with
    a legal metric name and float value; every sample's base name was
    declared by a preceding ``# TYPE`` line; histogram series carry the
    full ``_bucket``(cumulative, nondecreasing, ``le``-labeled, ending in
    ``+Inf``)/``_sum``/``_count`` triple with ``+Inf == _count``; counter
    values are non-negative. Raises :class:`PromParseError` on any
    violation; returns ``{"gauges", "counters", "histograms"}`` where each
    histogram is ``{"buckets": [(le, cumulative)], "sum", "count"}``.
    """
    types: dict[str, str] = {}
    gauges: dict[str, float] = {}
    counters: dict[str, float] = {}
    hists: dict[str, dict] = {}

    def base_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                return name[:-len(suffix)]
        return name

    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "gauge", "counter", "histogram", "summary",
                        "untyped"):
                    raise PromParseError(f"line {ln}: malformed TYPE: {raw!r}")
                if not _NAME_RE.match(parts[2]):
                    raise PromParseError(
                        f"line {ln}: illegal metric name {parts[2]!r}")
                if parts[2] in types:
                    raise PromParseError(
                        f"line {ln}: duplicate TYPE for {parts[2]!r}")
                types[parts[2]] = parts[3]
            continue            # other comments / HELP: legal, ignored
        m = _SAMPLE_RE.match(line)
        if not m:
            raise PromParseError(f"line {ln}: malformed sample: {raw!r}")
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError as exc:
            raise PromParseError(
                f"line {ln}: non-float value {m.group('value')!r}") from exc
        labels: dict[str, str] = {}
        if m.group("labels") is not None:
            for part in filter(None, m.group("labels").split(",")):
                lm = _LABEL_RE.match(part.strip())
                if not lm:
                    raise PromParseError(
                        f"line {ln}: malformed label {part!r}")
                labels[lm.group("key")] = lm.group("val")
        base = base_of(name)
        kind = types.get(base)
        if kind is None:
            raise PromParseError(
                f"line {ln}: sample {name!r} has no preceding TYPE")
        if kind == "gauge":
            gauges[name] = value
        elif kind == "counter":
            if value < 0:
                raise PromParseError(
                    f"line {ln}: negative counter {name}={value}")
            counters[name] = value
        elif kind == "histogram":
            h = hists.setdefault(base, {"buckets": [], "sum": None,
                                        "count": None})
            if name == f"{base}_bucket":
                le = labels.get("le")
                if le is None:
                    raise PromParseError(
                        f"line {ln}: histogram bucket without le label")
                if le != "+Inf":
                    try:
                        float(le)
                    except ValueError as exc:
                        raise PromParseError(
                            f"line {ln}: non-float le {le!r}") from exc
                if value != int(value) or value < 0:
                    raise PromParseError(
                        f"line {ln}: bucket count {value} is not a "
                        "non-negative integer")
                if h["buckets"] and value < h["buckets"][-1][1]:
                    raise PromParseError(
                        f"line {ln}: bucket counts not cumulative at "
                        f"le={le}")
                h["buckets"].append((le, int(value)))
            elif name == f"{base}_sum":
                h["sum"] = value
            elif name == f"{base}_count":
                h["count"] = value
            else:
                raise PromParseError(
                    f"line {ln}: unexpected histogram sample {name!r}")
        else:
            raise PromParseError(
                f"line {ln}: unsupported TYPE {kind!r} emitted by this "
                "exporter")
    for base, h in hists.items():
        if not h["buckets"] or h["buckets"][-1][0] != "+Inf":
            raise PromParseError(
                f"histogram {base!r} missing its +Inf bucket")
        if h["sum"] is None or h["count"] is None:
            raise PromParseError(
                f"histogram {base!r} missing _sum/_count")
        if h["buckets"][-1][1] != h["count"]:
            raise PromParseError(
                f"histogram {base!r}: +Inf bucket {h['buckets'][-1][1]} "
                f"!= _count {h['count']}")
    return {"gauges": gauges, "counters": counters, "histograms": hists}
