"""Self-tuning runtime: the knob registry and the per-host tuned profile.

Every performance lever in this stack used to be a hand-set constant —
``runtime.megachunk_factor``, ``runtime.pipeline_depth``,
``serve.batch_timeout_ms``, ``serve.max_batch``, ``serve.max_queue``,
``distrib.ingest_every_updates`` — while every signal needed to SET them
is already a live gauge (roofline MFU/AI, dispatch-gap spans,
``serve_overload``/occupancy/windowed p99 histograms, actor-ingest
rows/s). This module is the seam that closes that loop (ROADMAP item 5):

- **KNOBS** — the registry of tunable performance knobs: dotted config
  path, tier (``train``/``serve``/``distrib``), and bounds metadata. A
  knob not in this registry is a constant; a knob IN it must be read
  through this layer (tools/lint_hot_loop.py check 13 guards serve/ and
  runtime/ against fresh hard-coded shadows).
- **tuned profile** — ``tools/autotune.py`` sweeps the registry's knobs
  with a seeded successive-halving search over short measured windows and
  writes a schema-versioned, per-host ``tuned_profile.json`` (atomic
  rename; host fingerprint: cores, backend, device count). ``config.py``
  loads it through the ``tuning.profile`` knob.
- **precedence** — EXPLICIT config always wins over the profile, the
  profile wins over defaults (:func:`apply_profile`); a field counts as
  explicit when its value differs from the dataclass default, so a
  profile can never silently override an operator's decision. Provenance
  (:func:`describe`) is stamped into the run manifest and surfaced by
  ``cli obs``.
- **fingerprint contract** — a profile measured on a different host
  shape (cores/backend/device count) is refused LOUDLY
  (:class:`ProfileError`), never silently applied; the escape hatch is
  the explicit ``tuning.allow_fingerprint_mismatch`` knob.

The ONLINE half of the loop lives next door: ``serve/controller.py``
adapts ``serve.batch_timeout_ms``/``serve.max_queue`` against the
engine's own windowed latency histogram, and the orchestrator adapts the
learner-ingest cadence (``runtime/orchestrator.py`` — the
``tuning.adaptive_ingest`` knob). Both treat the CONFIGURED values as
ceilings: the online controllers only ever tighten below what the
operator (or the offline profile) allowed, so the PR-10/PR-12 safety
rails (queue bounds, shed accounting, supervision) are never fought, only
tracked.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("tuning")

#: Version of the tuned-profile schema. Bump on layout changes; a
#: mismatched profile is refused loudly (never best-effort-parsed: a
#: half-understood profile silently mis-tunes every run that loads it).
PROFILE_SCHEMA_VERSION = 1


class ProfileError(ConfigError):
    """A tuned profile that must not be applied: unreadable, wrong
    schema version, unknown knobs, or a host-fingerprint mismatch.
    Subclasses :class:`ConfigError` so the supervision decider maps it to
    STOP — re-running cannot make a foreign profile fit this host."""


@dataclass(frozen=True)
class Knob:
    """One registered tunable: the dotted config path is its identity
    (the profile file's key, the bench envelope's knob-vector key, and
    the lint's shadow-detection leaf)."""

    path: str           # dotted config path, e.g. "serve.batch_timeout_ms"
    tier: str           # "train" | "serve" | "distrib"
    kind: type          # int | float
    description: str


#: THE registry. Order is presentation order (cli obs, profiles).
KNOBS: tuple[Knob, ...] = (
    Knob("runtime.megachunk_factor", "train", int,
         "chunks fused into one jitted program (dispatch-floor lever)"),
    Knob("runtime.pipeline_depth", "train", int,
         "async-readback boundaries in flight (HBM vs stall tradeoff)"),
    Knob("serve.max_batch", "serve", int,
         "padded device batch per serving tick"),
    Knob("serve.batch_timeout_ms", "serve", float,
         "partial-batch coalescing deadline"),
    Knob("serve.max_queue", "serve", int,
         "bounded ingress depth (queueing-delay vs shed-rate tradeoff)"),
    Knob("distrib.ingest_every_updates", "distrib", int,
         "learner-ingest cadence over the actor feeds"),
    Knob("distrib.ingest_max_rows", "distrib", int,
         "per-tick per-actor ingest row bound (0 = replay capacity)"),
)

_KNOBS_BY_PATH = {k.path: k for k in KNOBS}

#: Fingerprint fields that must MATCH for a profile to apply: a sweep
#: tuned for 2 cores or a TPU backend is wrong (not just stale) on any
#: other shape. Informational fields (hostname, jax version) ride along
#: in the profile but never gate.
_FINGERPRINT_MATCH_KEYS = ("cpu_count", "backend", "device_count")


def host_fingerprint() -> dict:
    """This host's identity as the autotuner sees it. Backend probing is
    best-effort (a profile written where jax could not initialize carries
    ``None`` and only matches hosts in the same state)."""
    try:
        import jax
        backend = jax.default_backend()
        device_count = jax.device_count()
    except Exception:       # fingerprinting must never block a run
        backend = device_count = None
    import platform
    return {
        "cpu_count": os.cpu_count(),
        "backend": backend,
        "device_count": device_count,
        "machine": platform.machine(),
        "hostname": platform.node(),
    }


def get_knob(cfg: FrameworkConfig, path: str) -> Any:
    """Read a dotted knob off a config tree."""
    target: Any = cfg
    for part in path.split("."):
        target = getattr(target, part)
    return target


def set_knob(cfg: FrameworkConfig, path: str, value: Any) -> None:
    """Write a dotted knob into a config tree (in place)."""
    *sections, leaf = path.split(".")
    target: Any = cfg
    for part in sections:
        target = getattr(target, part)
    setattr(target, leaf, value)


def knob_vector(cfg: FrameworkConfig) -> dict[str, Any]:
    """The RESOLVED value of every registered knob — what a run/bench
    actually executed under. Stamped into every bench row
    (``bench._result_envelope``) so autotune trials and BENCH history
    join on actual knob values, not just ``config_hash``."""
    return {k.path: get_knob(cfg, k.path) for k in KNOBS}


_DEFAULTS: dict[str, Any] | None = None


def default_knob_values() -> dict[str, Any]:
    """Registry knob values of a pristine :class:`FrameworkConfig` — the
    baseline the explicit-vs-default precedence test compares against."""
    global _DEFAULTS
    if _DEFAULTS is None:
        _DEFAULTS = knob_vector(FrameworkConfig())
    return dict(_DEFAULTS)


# ---------------------------------------------------------------------------
# profile file IO
# ---------------------------------------------------------------------------


def build_profile(knobs: dict[str, Any], *, objectives: dict | None = None,
                  trials: list | None = None, seed: int | None = None,
                  config_hash: str | None = None,
                  notes: str | None = None) -> dict:
    """Assemble a profile document (the autotuner's output). ``knobs``
    keys must be registered dotted paths — a typo'd knob must fail at
    WRITE time, where the author is watching, not at every later load."""
    unknown = sorted(set(knobs) - set(_KNOBS_BY_PATH))
    if unknown:
        raise ProfileError(
            f"unregistered knob(s) {unknown}; the registry "
            f"(sharetrade_tpu/tuning.py KNOBS) is the contract")
    coerced = {}
    for path, value in knobs.items():
        coerced[path] = _KNOBS_BY_PATH[path].kind(value)
    doc = {
        "schema_version": PROFILE_SCHEMA_VERSION,
        "fingerprint": host_fingerprint(),
        "knobs": coerced,
    }
    if objectives:
        doc["objectives"] = objectives
    if trials:
        doc["trials"] = trials
    if seed is not None:
        doc["seed"] = seed
    if config_hash:
        doc["config_hash"] = config_hash
    if notes:
        doc["notes"] = notes
    return doc


def write_profile(path: str, profile: dict) -> dict:
    """Atomically publish a profile document (tmp + rename — a crashed
    autotune run must never leave a torn profile a later training run
    would half-parse). Durability-fsync is deliberately NOT needed here:
    a lost profile after power loss re-tunes; a torn one mis-tunes."""
    if profile.get("schema_version") != PROFILE_SCHEMA_VERSION:
        raise ProfileError(
            f"refusing to write schema_version="
            f"{profile.get('schema_version')!r} (writer is "
            f"{PROFILE_SCHEMA_VERSION})")
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(profile, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return profile


def load_profile(path: str) -> dict:
    """Read + validate a tuned profile. Loud on every failure mode: a
    missing/torn/mis-versioned/unknown-knob profile raises
    :class:`ProfileError` instead of degrading to defaults silently —
    an operator who POINTED at a profile wants to know it didn't load."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise ProfileError(f"tuned profile not found: {path}") from None
    except (OSError, json.JSONDecodeError) as exc:
        raise ProfileError(f"tuned profile {path} unreadable: {exc}") from exc
    if not isinstance(doc, dict) or "knobs" not in doc:
        raise ProfileError(f"tuned profile {path} has no 'knobs' object")
    if doc.get("schema_version") != PROFILE_SCHEMA_VERSION:
        raise ProfileError(
            f"tuned profile {path} schema_version="
            f"{doc.get('schema_version')!r} != {PROFILE_SCHEMA_VERSION}; "
            "re-run tools/autotune.py")
    unknown = sorted(set(doc["knobs"]) - set(_KNOBS_BY_PATH))
    if unknown:
        raise ProfileError(
            f"tuned profile {path} carries unregistered knob(s) {unknown}")
    return doc


def fingerprint_mismatches(profile_fp: dict | None,
                           fp: dict | None = None) -> list[str]:
    """Which gating fingerprint fields disagree between a profile and
    this host (empty = the profile applies here)."""
    if not isinstance(profile_fp, dict):
        return list(_FINGERPRINT_MATCH_KEYS)
    fp = fp or host_fingerprint()
    return [k for k in _FINGERPRINT_MATCH_KEYS
            if profile_fp.get(k) != fp.get(k)]


# ---------------------------------------------------------------------------
# precedence: explicit config > profile > default
# ---------------------------------------------------------------------------


def apply_profile(cfg: FrameworkConfig, *, path: str | None = None
                  ) -> FrameworkConfig:
    """Resolve the config's registered knobs against its tuned profile.

    No-op (returns ``cfg`` unchanged) when ``tuning.profile`` is unset.
    Otherwise returns a NEW config where every registry knob still at its
    dataclass default takes the profile's value; knobs the operator set
    explicitly are untouched — explicit config always wins. "Explicit"
    means: the value differs from the dataclass default, OR the dotted
    path was applied through ``apply_overrides`` (its
    ``_explicit_overrides`` memo — so ``--set serve.max_queue=1024``
    pins the knob even when 1024 IS the default). The one remaining
    blind spot: a config FILE carrying a knob at its default value reads
    as default (file loading keeps no explicitness memo). Idempotent:
    re-applying sees the profile values as "explicit" and changes
    nothing, so cli bootstrap and the Orchestrator can both call it
    safely.

    Raises :class:`ProfileError` on a missing/invalid profile or a
    host-fingerprint mismatch (``tuning.allow_fingerprint_mismatch``
    downgrades the mismatch to a warning — for deliberately shipping one
    host's profile to a fleet of identical-enough machines)."""
    path = path if path is not None else getattr(cfg.tuning, "profile", None)
    if not path:
        return cfg
    profile = load_profile(path)
    mismatches = fingerprint_mismatches(profile.get("fingerprint"))
    if mismatches:
        fp = host_fingerprint()
        detail = ", ".join(
            f"{k}: profile={profile.get('fingerprint', {}).get(k)!r} "
            f"host={fp.get(k)!r}" for k in mismatches)
        if not cfg.tuning.allow_fingerprint_mismatch:
            raise ProfileError(
                f"tuned profile {path} was measured on a different host "
                f"shape ({detail}); re-run tools/autotune.py here, or set "
                "tuning.allow_fingerprint_mismatch=true to apply it "
                "anyway")
        log.warning("applying tuned profile %s despite fingerprint "
                    "mismatch (%s): tuning.allow_fingerprint_mismatch",
                    path, detail)
    defaults = default_knob_values()
    explicit = frozenset(getattr(cfg, "_explicit_overrides", ()))
    new = FrameworkConfig.from_dict(cfg.to_dict())
    new._explicit_overrides = explicit      # survives re-application
    applied: dict[str, Any] = {}
    for kpath, value in profile["knobs"].items():
        if kpath in explicit or get_knob(cfg, kpath) != defaults[kpath]:
            continue            # explicit config wins
        value = _KNOBS_BY_PATH[kpath].kind(value)
        set_knob(new, kpath, value)
        applied[kpath] = value
    if applied:
        log.info("tuned profile %s applied: %s", path,
                 ", ".join(f"{k}={v}" for k, v in sorted(applied.items())))
    return new


def describe(cfg: FrameworkConfig) -> dict:
    """Provenance of every registered knob under ``cfg`` — the run
    manifest's ``tuning`` block and the ``cli obs`` tuning section.

    Deterministic re-derivation (no hidden state): re-loads the profile
    named by the config and recomputes the same precedence
    :func:`apply_profile` used. Best-effort on the profile read — a
    manifest write must never fail because a profile went missing after
    bring-up; the error is recorded instead."""
    defaults = default_knob_values()
    path = getattr(cfg.tuning, "profile", None)
    profile_knobs: dict[str, Any] = {}
    out: dict[str, Any] = {
        "profile": path,
        "fingerprint": host_fingerprint(),
    }
    if path:
        try:
            profile = load_profile(path)
            profile_knobs = profile["knobs"]
            out["profile_fingerprint"] = profile.get("fingerprint")
            out["profile_mismatches"] = fingerprint_mismatches(
                profile.get("fingerprint"))
        except ProfileError as exc:
            out["profile_error"] = str(exc)
    explicit = frozenset(getattr(cfg, "_explicit_overrides", ()))
    knobs: dict[str, dict] = {}
    for knob in KNOBS:
        value = get_knob(cfg, knob.path)
        if knob.path in explicit:
            source = "explicit"     # a --set pin, even at default value
        elif value != defaults[knob.path]:
            source = ("profile"
                      if (knob.path in profile_knobs
                          and knob.kind(profile_knobs[knob.path]) == value)
                      else "explicit")
        else:
            source = "default"
        knobs[knob.path] = {
            "value": value,
            "default": defaults[knob.path],
            "source": source,
            "tier": knob.tier,
        }
    out["knobs"] = knobs
    return out
