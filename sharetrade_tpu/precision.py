"""Precision policy: bf16 compute with fp32 master weights.

The old low-precision story was ``model.dtype="bfloat16"`` — a whole-model
cast where params, gradients, AND optimizer accumulators silently followed
the compute dtype (models/__init__.py now rejects it with a migration
error). This module replaces it with the standard mixed-precision contract
the TPU RL stacks run (RLAX arxiv 2512.06392, Podracer arxiv 2104.06272):

- **Masters**: ``TrainState.params`` (and optimizer state) stay float32,
  always. Checkpoints therefore always hold fp32 master weights.
- **Compute**: at each update boundary inside the jitted (mega)chunk the
  policy casts ONE bf16 copy (:meth:`PrecisionPolicy.cast_compute`); every
  forward/backward runs on that copy, with f32 matmul accumulation
  (``preferred_element_type`` — models/core.py ``dense``,
  ops/attention.py ``_dot``).
- **Gradients**: differentiate w.r.t. the bf16 copy, upcast to f32
  (:meth:`PrecisionPolicy.grads_to_master`), apply the update in f32.
- **Recurrent carry**: cast once at TrainState construction
  (:meth:`PrecisionPolicy.cast_carry`) so the scan-carried K/V caches ride
  bf16 with a stable pytree dtype (a carry whose dtype flips mid-scan is a
  trace error, not a slowdown).

Everything here is a STRUCTURAL identity in fp32 mode — the helpers return
their argument object untouched, so the default mode's traced program is
bit-for-bit the pre-policy program (pinned by tests/test_precision.py's
golden trajectory). Casts anywhere near params/grads must route through
these helpers: tools/lint_hot_loop.py check 7 flags bare ``.astype(`` on
params/grads in the hot paths (``precision-cast-ok`` escape hatch).

fp8 note: the compute tier is this one dtype seam; when a backend supports
fp8 matmuls, an ``fp8_mixed`` mode is a new ``compute_dtype`` plus a
scaling strategy — the accumulation seams are already in place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from sharetrade_tpu.config import ConfigError, PrecisionConfig

MODES = ("fp32", "bf16_mixed")


def _cast_float_leaves(tree: Any, dtype) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype`` (integer leaves —
    counters, cursors, replay indices — pass through untouched)."""
    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)  # precision-cast-ok: THE policy cast site
        return x
    return jax.tree.map(leaf, tree)


@dataclass(frozen=True)
class PrecisionPolicy:
    """The resolved precision contract every learner/runtime path consults.

    ``mixed`` is False for fp32 mode, and then every helper is an object
    identity (returns its argument) — the structural bit-identity guarantee
    of the default mode."""

    mode: str = "fp32"
    fused_update: str = "auto"

    def __post_init__(self):
        if self.mode not in MODES:
            raise ConfigError(
                f"unknown precision.mode {self.mode!r}; choose from {MODES}")
        if self.fused_update not in ("auto", "on", "off"):
            raise ConfigError(
                f"precision.fused_update must be 'auto', 'on' or 'off', "
                f"got {self.fused_update!r}")

    @property
    def mixed(self) -> bool:
        return self.mode == "bf16_mixed"

    @property
    def compute_dtype(self):
        """The dtype model forwards run in (activations + compute copy of
        the weights); matmul ACCUMULATION stays f32 either way."""
        return jnp.bfloat16 if self.mixed else jnp.float32

    @property
    def use_fused_update(self) -> bool:
        """Resolve the fused-update tri-state: 'auto' engages it exactly
        when the mode is mixed (fp32 default keeps the literal optax
        call pair — the bit-identity contract)."""
        if self.fused_update == "auto":
            return self.mixed
        return self.fused_update == "on"

    # ---- the three cast seams ----------------------------------------

    def cast_compute(self, params: Any) -> Any:
        """fp32 masters -> the compute copy the forwards/backwards see.
        Called ONCE per update boundary inside the traced step (XLA CSEs
        any duplicate). Identity in fp32 mode."""
        if not self.mixed:
            return params
        return _cast_float_leaves(params, self.compute_dtype)

    def grads_to_master(self, grads: Any) -> Any:
        """Gradients of the compute copy -> f32 master-space gradients.
        Identity in fp32 mode."""
        if not self.mixed:
            return grads
        return _cast_float_leaves(grads, jnp.float32)

    def cast_carry(self, carry: Any, model: Any = None) -> Any:
        """Model recurrent state (K/V caches, LSTM cells) -> the compute
        dtype, applied at TrainState CONSTRUCTION (init / heal / episode
        re-arm) so the scan-carried dtype is stable across chunks.
        Models that produce a MIXED-dtype carry (the episode transformer's
        f32 ``hist`` beside its compute-dtype K/V cache) provide
        ``Model.cast_carry`` and the hook decides per leaf; otherwise
        every floating leaf follows the compute dtype. Identity in fp32
        mode."""
        if not self.mixed:
            return carry
        hook = getattr(model, "cast_carry", None)
        if hook is not None:
            return hook(carry, self.compute_dtype)
        return _cast_float_leaves(carry, self.compute_dtype)


#: The default policy every path without an explicit config resolves to —
#: fp32, structurally identical to the pre-policy code.
FP32 = PrecisionPolicy()


def policy_from_config(cfg: PrecisionConfig | None) -> PrecisionPolicy:
    """Validate + freeze a PrecisionConfig into the policy object (the
    constructor raises ConfigError on unknown modes — STOP territory, a bad
    precision config can never heal by restarting)."""
    if cfg is None:
        return FP32
    return PrecisionPolicy(mode=cfg.mode, fused_update=cfg.fused_update)
