"""sharetrade_tpu — a TPU-native RL framework for share-trading agents.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
``cosmir17/Scala-akka-tensorflow-sharetrade-helper`` (an Akka/TensorFlow-Scala
parameter-server RL trader; see /root/reference and SURVEY.md):

- ``data``       event-sourced market-data ingestion + durable journal
                 (reference: SharePriceGetter.scala — PersistentActor + LevelDB)
- ``env``        pure-JAX windowed trading environment, vmap/scan-friendly
                 (reference: TrainerChildActor.scala:82-146 — the fold loop)
- ``models``     policy networks: MLP Q-net, LSTM, Transformer (Pallas attention)
                 (reference: QDecisionPolicyActor.scala:38-50 — the TF graph)
- ``agents``     learners: Q-learning, REINFORCE, DQN, A2C, PPO
                 (reference: QDecisionPolicyActor.scala:54-77 — epsilon-greedy + TD)
- ``train``      fused jit training loops: select + env-step + TD + optimizer
                 update in one compiled program (replacing ~230k Session.run calls
                 serialized through one actor mailbox, SURVEY.md §3.3)
- ``parallel``   device meshes, shard_map collectives, sharding rules
                 (replacing the Akka broadcast Router + mailbox parameter server)
- ``runtime``    lifecycle FSM, orchestrator, supervision/backoff, metrics
                 (reference: TrainerRouterActor.scala — Router + BackoffSupervisor)
- ``checkpoint`` real model/optimizer/RNG/cursor checkpointing
                 (reference intent: QDecisionPolicyActor.scala:74,91-93 — empty stub)
"""

__version__ = "0.1.0"

from sharetrade_tpu.config import FrameworkConfig  # noqa: F401
