"""Tracing/profiling — the subsystem the reference lacks entirely.

Reference status (SURVEY.md §5): no tracing of any kind; TF's SummarySaver is
imported but never used (QDecisionPolicyActor.scala:8); the only timing
signal is a progress log every 200 fold steps. Here:

- :class:`Tracer` wraps ``jax.profiler`` device traces (XPlane output,
  viewable in TensorBoard/XProf) gated by config, with annotated host-side
  ``TraceAnnotation`` spans so chunk boundaries show up in the timeline;
- :class:`StepTimer` measures per-chunk wall time and derives steps/sec,
  feeding the metrics registry (the throughput series BASELINE.md needs).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from dataclasses import dataclass, field

import jax

from sharetrade_tpu.utils.logging import get_logger

log = get_logger("utils.profiling")


class Tracer:
    """Device + host tracing around training chunks.

    ``profile_dir=None`` disables everything at zero cost (the config
    default, RuntimeConfig.profile_dir).
    """

    def __init__(self, profile_dir: str | None = None):
        self.profile_dir = profile_dir
        self._active = False

    def start(self) -> None:
        if self.profile_dir and not self._active:
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
            log.info("profiler trace started -> %s", self.profile_dir)

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            log.info("profiler trace written to %s", self.profile_dir)

    @contextlib.contextmanager
    def trace(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()

    @contextlib.contextmanager
    def span(self, name: str):
        """Named host annotation visible in the device timeline."""
        if self.profile_dir:
            with jax.profiler.TraceAnnotation(name):
                yield
        else:
            yield


@dataclass
class StepTimer:
    """Per-chunk wall-clock accounting → steps/sec metrics."""

    chunk_steps: int
    num_agents: int
    _last: float | None = None
    # (elapsed seconds, chunks covered) per tick: the orchestrator's sampled
    # metrics cadence ticks once per SAMPLE, covering several dispatched
    # chunks, so each entry carries its own chunk count. Bounded by
    # ``max_history`` (a ring; soak runs previously grew this without
    # limit) — summary() stays EXACT under eviction via the running totals.
    history: list[tuple[float, int]] = field(default_factory=list)
    max_history: int | None = None
    _total_seconds: float = 0.0
    _total_chunks: int = 0

    def __post_init__(self) -> None:
        if self.max_history:
            self.history = deque(self.history, maxlen=int(self.max_history))

    def tick(self, chunks: int = 1) -> dict[str, float]:
        """Call once per completed chunk — or once per metrics sample with
        ``chunks`` = the number of chunks dispatched since the last tick;
        returns throughput metrics averaged over that span."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return {}
        dt = now - self._last
        self._last = now
        self.history.append((dt, chunks))
        self._total_seconds += dt
        self._total_chunks += chunks
        agent_steps = self.chunk_steps * self.num_agents * chunks
        return {
            "chunk_seconds": dt / chunks,
            "env_steps_per_sec":
                self.chunk_steps * chunks / dt if dt > 0 else 0.0,
            "agent_steps_per_sec": agent_steps / dt if dt > 0 else 0.0,
        }

    def rebase(self) -> None:
        """Restart the interval clock without recording anything — called
        after a supervision recovery so the failed chunk, the backoff
        sleep, and the checkpoint restore don't pollute the next sample's
        throughput metrics."""
        self._last = time.perf_counter()

    def summary(self) -> dict[str, float]:
        if not self._total_chunks:
            return {}
        # Running totals, not the (possibly ring-evicted) history: the
        # whole-run aggregates stay exact no matter how long the soak.
        total = self._total_seconds
        chunks = self._total_chunks
        return {
            "chunks_timed": float(chunks),
            "total_seconds": total,
            "mean_chunk_seconds": total / chunks,
            "mean_agent_steps_per_sec":
                self.chunk_steps * self.num_agents * chunks / total,
        }
