from sharetrade_tpu.utils.logging import get_logger  # noqa: F401
from sharetrade_tpu.utils.metrics import MetricsRegistry  # noqa: F401
