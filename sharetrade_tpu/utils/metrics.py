"""Run metrics registry.

The reference's only "metrics" are the final avg/std portfolio aggregations
(TrainerRouterActor.scala:89-94,148-151). This registry generalizes that:
thread-safe scalar series with snapshot reads, so the orchestrator can answer
status queries mid-run without stopping the device loop (the reference answers
GetAvg mid-run from trained workers, TrainerRouterActorSpec.scala:81-95).
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Any


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, list[tuple[float, float]]] = defaultdict(list)
        self._latest: dict[str, float] = {}

    def record(self, name: str, value: float, *, ts: float | None = None) -> None:
        ts = time.time() if ts is None else ts
        value = float(value)
        with self._lock:
            self._series[name].append((ts, value))
            self._latest[name] = value

    def record_many(self, values: dict[str, float]) -> None:
        ts = time.time()
        for name, value in values.items():
            self.record(name, value, ts=ts)

    def latest(self, name: str, default: float | None = None) -> float | None:
        with self._lock:
            return self._latest.get(name, default)

    def series(self, name: str) -> list[tuple[float, float]]:
        with self._lock:
            return list(self._series.get(name, ()))

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._latest)

    def summary(self, name: str) -> dict[str, float]:
        """Mean/std/min/max/count over a series — the avg/std aggregation the
        reference computes over worker portfolios, generalized."""
        values = [v for _, v in self.series(name)]
        if not values:
            return {"count": 0.0}
        n = len(values)
        mean = sum(values) / n
        var = sum((v - mean) ** 2 for v in values) / n
        return {
            "count": float(n),
            "mean": mean,
            "std": math.sqrt(var),
            "min": min(values),
            "max": max(values),
        }


def mean_std(values: Any) -> tuple[float, float]:
    """Population mean/std, matching the reference's aggregation
    (TrainerRouterActor.scala:148-151: variance = E[(x-mean)^2], std = sqrt)."""
    vals = [float(v) for v in values]
    if not vals:
        raise ValueError("mean_std of empty sequence")
    m = sum(vals) / len(vals)
    var = sum((v - m) ** 2 for v in vals) / len(vals)
    return m, math.sqrt(var)
