"""Run metrics registry.

The reference's only "metrics" are the final avg/std portfolio aggregations
(TrainerRouterActor.scala:89-94,148-151). This registry generalizes that:
thread-safe scalar series with snapshot reads, so the orchestrator can answer
status queries mid-run without stopping the device loop (the reference answers
GetAvg mid-run from trained workers, TrainerRouterActorSpec.scala:81-95).

Two kinds of values:

- **gauges** (``record``/``record_many``) — point-in-time series, each
  bounded by a per-series ring (``max_points``; soak runs can no longer grow
  the host heap without limit, short runs never reach the cap);
- **counters** (``inc``/``counters``) — monotonic totals (``restarts_total``,
  ``heals_total``, ...), the Prometheus-counter half of the obs exporter's
  output;
- **histograms** (``attach_histogram``/``histograms``) — fixed-bucket
  mergeable distributions (obs/hist.py) owned and observed by their
  producers (the serve engine's per-stage latencies, the orchestrator's
  chunk timings); the registry only registers them for export, so the
  per-sample hot path never takes the registry lock. Duck-typed (anything
  with ``snapshot()``) so this module needs no obs import.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict, deque
from typing import Any

#: Default per-series ring size: far beyond any short run (a full
#: reference-shape episode samples ~30 rows), small enough that a week-long
#: soak holds megabytes, not the run's whole history, in memory.
DEFAULT_MAX_POINTS = 65536


class MetricsRegistry:
    def __init__(self, *, max_points: int | None = DEFAULT_MAX_POINTS) -> None:
        self._lock = threading.Lock()
        # None/0 = unbounded (the pre-cap behavior, opt-in via config).
        self._maxlen = int(max_points) if max_points else None
        self._series: dict[str, deque[tuple[float, float]]] = defaultdict(
            self._new_series)
        self._latest: dict[str, float] = {}
        self._counters: dict[str, float] = {}
        self._histograms: dict[str, Any] = {}

    def _new_series(self) -> deque:
        return deque(maxlen=self._maxlen)

    def record(self, name: str, value: float, *, ts: float | None = None) -> None:
        ts = time.time() if ts is None else ts
        value = float(value)
        with self._lock:
            self._series[name].append((ts, value))
            self._latest[name] = value

    def record_many(self, values: dict[str, float]) -> None:
        """Record a whole metrics row under ONE lock acquisition (the
        per-sample hot-loop write path: a lock round-trip per key showed up
        once rows grew to ~10 keys x K megachunk rows per sample)."""
        ts = time.time()
        with self._lock:
            for name, value in values.items():
                value = float(value)
                self._series[name].append((ts, value))
                self._latest[name] = value

    # ---- counters (monotonic) ----

    def inc(self, name: str, amount: float = 1.0) -> float:
        """Increment a monotonic counter; returns the new total."""
        with self._lock:
            total = self._counters.get(name, 0.0) + float(amount)
            self._counters[name] = total
            return total

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    # ---- histograms (obs/hist.py, duck-typed) ----

    def attach_histogram(self, name: str, hist: Any) -> Any:
        """Register a histogram for export under ``name`` (idempotent for
        the same object; re-attaching a DIFFERENT object replaces it — the
        supervised-rebuild path). The producer keeps the reference and
        observes into it directly, off the registry lock."""
        with self._lock:
            self._histograms[name] = hist
        return hist

    def histogram(self, name: str) -> Any | None:
        """The live attached histogram object (None when absent)."""
        with self._lock:
            return self._histograms.get(name)

    def histograms(self) -> dict[str, dict]:
        """{name: snapshot} over every attached histogram — the exporter's
        drain unit (snapshots are consistent copies; see obs/hist.py)."""
        with self._lock:
            items = list(self._histograms.items())
        return {name: h.snapshot() for name, h in items}

    # ---- reads ----

    def latest(self, name: str, default: float | None = None) -> float | None:
        with self._lock:
            return self._latest.get(name, default)

    def series(self, name: str) -> list[tuple[float, float]]:
        with self._lock:
            return list(self._series.get(name, ()))

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._latest)

    def summary(self, name: str) -> dict[str, float]:
        """Mean/std/min/max/count over a series — the avg/std aggregation the
        reference computes over worker portfolios, generalized. (Over the
        RETAINED ring when the series has been capped.)"""
        values = [v for _, v in self.series(name)]
        if not values:
            return {"count": 0.0}
        n = len(values)
        mean = sum(values) / n
        var = sum((v - mean) ** 2 for v in values) / n
        return {
            "count": float(n),
            "mean": mean,
            "std": math.sqrt(var),
            "min": min(values),
            "max": max(values),
        }


def mean_std(values: Any) -> tuple[float, float]:
    """Population mean/std, matching the reference's aggregation
    (TrainerRouterActor.scala:148-151: variance = E[(x-mean)^2], std = sqrt)."""
    vals = [float(v) for v in values]
    if not vals:
        raise ValueError("mean_std of empty sequence")
    m = sum(vals) / len(vals)
    var = sum((v - m) ** 2 for v in vals) / len(vals)
    return m, math.sqrt(var)
