"""Structured logging.

The reference uses logback + akka-slf4j with `ActorLogging` mixed into every
actor (reference build.sbt:15-16, application.conf:1-3). Here: stdlib logging
with one consistent formatter, plus an optional JSONL event stream for machine
consumption (the observability surface the reference lacks, SURVEY.md §5).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any

_FORMAT = "%(asctime)s %(levelname)-7s [%(name)s] %(message)s"
_configured = False
_lock = threading.Lock()


def configure(level: int | None = None, stream=None) -> None:
    """Idempotent setup; explicit re-calls update level/stream (imports latch
    the handler early via get_logger, so this must not be first-call-wins).
    ``level=None`` means "leave as-is" (INFO on first call)."""
    global _configured
    with _lock:
        root = logging.getLogger("sharetrade")
        if not _configured:
            handler = logging.StreamHandler(stream or sys.stderr)
            handler.setFormatter(logging.Formatter(_FORMAT))
            root.addHandler(handler)
            root.propagate = False
            root.setLevel(logging.INFO if level is None else level)
            _configured = True
            return
        if stream is not None:
            for h in list(root.handlers):
                root.removeHandler(h)
            handler = logging.StreamHandler(stream)
            handler.setFormatter(logging.Formatter(_FORMAT))
            root.addHandler(handler)
        if level is not None:
            root.setLevel(level)


def get_logger(name: str) -> logging.Logger:
    configure()
    return logging.getLogger(f"sharetrade.{name}")


class EventLog:
    """Append-only JSONL event stream for structured run events.

    Used by the runtime for lifecycle transitions, restarts, checkpoints —
    the machine-readable counterpart of the reference's lifecycle log lines
    (e.g. TrainerRouterActor.scala:70,87,128).

    ``mirror`` (settable post-construction) receives every emitted event as
    ``mirror(kind, payload)`` even when no file is attached — the tap the
    obs flight recorder rides so supervision/lifecycle events land in the
    crash ring without a second emit call at every site.
    """

    def __init__(self, path: str | None):
        self._path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1) if path else None
        self.mirror: Any | None = None

    def emit(self, kind: str, **payload: Any) -> None:
        if self.mirror is not None:
            try:
                self.mirror(kind, payload)
            except Exception:
                pass        # a broken tap must never block the event log
        if self._fh is None:
            return
        record = {"ts": time.time(), "kind": kind, **payload}
        with self._lock:
            self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
