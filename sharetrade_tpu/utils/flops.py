"""Analytic model-FLOPs accounting and MFU for the benchmark harness.

Round 1 reported only agent-steps/s against a derived CPU ceiling, which
flatters without informing (a 3,440x multiplier on a 41k-param MLP is ~10
MFLOP/s of useful math). These helpers put model FLOPs/step and MFU — the
fraction of the chip's peak matmul throughput the workload achieves — next to
every throughput number so chip utilization is visible in our own tables.

Counting rules (standard MFU conventions, stated explicitly):
- A dense layer in->out over N rows costs 2*N*in*out FLOPs.
- Causal attention is counted at its *useful* cost, ~half the full score
  matrix: 2*seq^2*d per attention matmul pair member (the Pallas kernel skips
  fully-masked blocks, so this reflects work actually scheduled).
- A backward pass costs 2x the forward it differentiates.
- Env-step arithmetic, optimizer updates, layernorms, and softmaxes are
  ignored (orders of magnitude below the matmuls).

Peak numbers are per-chip dense bf16 matmul peaks. f32 inputs at JAX's
default matmul precision also run single-pass bf16 on the MXU, so one peak
serves both dtypes; "highest"-precision runs (parity tests) are not what we
benchmark.
"""

from __future__ import annotations

import jax

from sharetrade_tpu.config import FrameworkConfig, LearnerConfig, ModelConfig

# device_kind substrings -> dense bf16 peak FLOP/s per chip.
_PEAK_BY_KIND = (
    ("v6 lite", 918e12),   # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),   # v5e
    ("v4", 275e12),
)
_DEFAULT_PEAK = 197e12

# device_kind substrings -> HBM bandwidth bytes/s per chip — the other
# roofline axis (obs/roofline.py): achieved HBM GB/s and the ridge point
# peak_flops / peak_bw that splits compute-bound from memory-bound.
_HBM_BW_BY_KIND = (
    ("v6 lite", 1640e9),   # Trillium
    ("v5p", 2765e9),
    ("v5 lite", 819e9),    # v5e
    ("v4", 1228e9),
)
_DEFAULT_HBM_BW = 819e9


def chip_peak_flops(device=None) -> float:
    """Dense bf16 peak for the attached chip (fallback: v5e)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_BY_KIND:
        if sub in kind:
            return peak
    return _DEFAULT_PEAK


def chip_peak_hbm_bw(device=None) -> float:
    """Peak HBM bytes/s for the attached chip (fallback: v5e). On the CPU
    backend this — like :func:`chip_peak_flops` — reports the v5e default,
    so CPU-measured MFU/roofline rows are comparable placeholders for the
    TPU numbers that slot in later (the BASELINE.md convention)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for sub, bw in _HBM_BW_BY_KIND:
        if sub in kind:
            return bw
    return _DEFAULT_HBM_BW


def forward_flops_per_obs(model: ModelConfig, obs_dim: int,
                          algo: str = "qlearn") -> float:
    """Matmul FLOPs for ONE observation's policy forward pass.

    The MLP family has two distinct architectures (models/mlp.py): value-based
    algos (qlearn/dqn) use ``q_mlp`` — obs->h->acts, no value head — while
    pg/a2c/ppo use ``ac_mlp`` — obs->h, h->h torso, policy AND value heads.
    """
    acts = model.num_actions
    if model.kind == "mlp":
        h = model.hidden_dim
        if algo in ("qlearn", "dqn"):
            return 2.0 * h * (obs_dim + acts)           # q_mlp: two denses
        return 2.0 * h * (obs_dim + h + acts + 1)       # ac_mlp: torso2 + heads
    if model.kind == "lstm":
        # lstm_policy (models/lstm.py): obs->h input dense, fused [x;h]->4h
        # gate matmul (16*h^2), then policy + value heads.
        h = model.hidden_dim
        return 2.0 * h * obs_dim + 16.0 * h * h + 2.0 * h * (acts + 1)
    if model.kind == "tcn":
        # models/tcn.py: per block a K-tap dilated conv (2*W*K*C^2) plus a
        # 1x1 mix (2*W*C^2); block count auto-sized to cover the window
        # (kernel width and sizing imported so the accounting can't drift
        # from the model).
        from sharetrade_tpu.models.tcn import KERNEL, default_num_blocks
        w = obs_dim - 2
        c = model.hidden_dim
        per_block = 2.0 * w * KERNEL * c * c + 2.0 * w * c * c
        return (default_num_blocks(w) * per_block
                + 2.0 * w * 3 * c + 2.0 * c * (acts + 1 + 3))
    if model.kind == "transformer":
        seq = obs_dim - 1                               # window + summary token
        d = model.num_heads * model.head_dim
        ffn = 16.0 * seq * d * d                        # MLP in/out at ratio 4
        if model.moe_experts:
            # Dense-mask MoE evaluates every expert on every token (E x the
            # dense FFN); top-k capacity dispatch evaluates ~k experts per
            # token (drops make this a slight overcount; the dispatch/combine
            # one-hot matmuls are routing overhead, not model FLOPs).
            ffn *= (model.moe_top_k if model.moe_top_k else model.moe_experts)
        per_layer = (
            6.0 * seq * d * d        # qkv projection
            + 2.0 * seq * seq * d    # causal QK^T + PV (useful half of 4*s^2*d)
            + 2.0 * seq * d * d      # output projection
            + ffn
        )
        return model.num_layers * per_layer + 2.0 * seq * 3 * d  # + embed
    raise ValueError(f"unknown model kind {model.kind!r}")


def forward_equivalents_per_agent_step(cfg: LearnerConfig,
                                       num_agents: int) -> float:
    """How many single-observation forward passes one agent-step of TRAINING
    costs under each algorithm (backward = 2x the differentiated forward)."""
    if cfg.algo == "qlearn":
        # select fwd + stacked TD fwd over (s, s') + backward of that stack
        # (stop_gradient zeroes the s' cotangents but the matmul grads still
        # run full-size).
        return 1.0 + 2.0 + 2.0 * 2.0
    if cfg.algo in ("pg", "a2c"):
        # rollout fwd + replay fwd + backward
        return 1.0 + 1.0 + 2.0
    if cfg.algo == "ppo":
        # rollout fwd + ppo_epochs x (replay fwd + backward); minibatching
        # repartitions the same totals.
        return 1.0 + cfg.ppo_epochs * 3.0
    if cfg.algo == "dqn":
        # select fwd; per env-step the learner trains on replay_batch
        # observations (online fwd + target fwd + backward), amortized over
        # the agent batch.
        per_replay = (cfg.replay_batch / max(num_agents, 1))
        return 1.0 + per_replay * (1.0 + 1.0 + 2.0)
    raise ValueError(f"unknown algo {cfg.algo!r}")


def _episode_mode_flops_per_agent_step(cfg: FrameworkConfig,
                                       obs_dim: int) -> float:
    """Episode-mode transformer (models/transformer_episode.py), counting
    FLOPs actually EXECUTED. Both halves of the chunk exploit the same
    agent-invariance (every lockstep agent replays one shared price series),
    so the banded trunk runs for ONE representative row and amortizes over
    the B agents in BOTH places:

        rollout trunk:  (S+1)/T tokens / B agents (agents/rollout.py
                        precomputed path)
        rollout head:   FACTORED (round 5, rollout_head_factored): the
                        d-sized policy/value projections run ONCE over the
                        representative's T+1 trunk rows (shared /B), and
                        the per-agent-step residue is the 3-wide portfolio
                        contraction
        replay trunk:   epochs x minibatches x 3 (fwd+bwd) x S/T tokens / B
                        (apply_unroll_shared: one trunk per minibatch PASS,
                        not per agent — each pass re-runs it because the
                        params just changed)
        replay heads:   ALSO factored (round 5): d-sized base projections
                        once per pass over the shared trunk rows, 3-wide
                        portfolio term per agent-step, x3 for fwd+bwd

    MFU computed from this is hardware utilization of the executed matmuls;
    the pre-round-4 convention counted the per-agent replay trunks the
    shared path no longer runs, which would overstate MFU by ~B/minibatches.
    """
    model, learner = cfg.model, cfg.learner
    w = obs_dim - 2
    d = model.num_heads * model.head_dim
    per_token = (model.num_layers * (24.0 * d * d + 4.0 * w * d)
                 + 2.0 * 3 * d        # tick embed
                 + 2.0 * d * (model.num_actions + 1 + 3))  # heads + port
    t = max(learner.unroll_len, 1)
    b = max(cfg.parallel.num_workers, 1)
    s = model.num_layers * (w - 1) + t
    if learner.algo == "ppo":
        epochs = learner.ppo_epochs
        # Mirror ppo.py's divisor fallback: the actual minibatch count is
        # the largest divisor of the agent count not exceeding the request.
        requested = max(1, min(learner.ppo_minibatches, b))
        mb_count = max(d for d in range(1, requested + 1) if b % d == 0)
        passes = epochs * mb_count
    else:
        epochs, passes = 1, 1
    # Factored heads: shared base projections over the trunk rows plus the
    # per-step 3-wide portfolio term (policy+value: A+1 outputs).
    head_base = 2.0 * d * (model.num_actions + 1) * (t + 1) / t / b
    head_pf_step = 2.0 * 3 * (model.num_actions + 1)
    replay_heads = (2.0 * d * (model.num_actions + 1) * passes * 3.0 / b
                    + head_pf_step * epochs * 3.0)
    return (per_token * (s + 1) / t / b           # rollout trunk (shared)
            + head_base + head_pf_step             # factored rollout head
            + per_token * passes * 3.0 * s / t / b  # replay trunks (shared)
            + replay_heads)                        # factored replay heads


def train_flops_per_agent_step(cfg: FrameworkConfig, obs_dim: int) -> float:
    if (cfg.model.kind == "transformer" and cfg.model.seq_mode == "episode"
            and cfg.learner.algo in ("pg", "a2c", "ppo")):
        return _episode_mode_flops_per_agent_step(cfg, obs_dim)
    return (forward_flops_per_obs(cfg.model, obs_dim, cfg.learner.algo)
            * forward_equivalents_per_agent_step(
                cfg.learner, cfg.parallel.num_workers))


def mfu(agent_steps_per_sec: float, cfg: FrameworkConfig, obs_dim: int,
        device=None) -> float:
    """Model FLOPs utilization in [0, 1]."""
    achieved = agent_steps_per_sec * train_flops_per_agent_step(cfg, obs_dim)
    return achieved / chip_peak_flops(device)
