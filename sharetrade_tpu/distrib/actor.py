"""The rollout-actor process body (``cli actor``) — one failure domain.

The reference's worker actors roll out episodes against a shared learner
(TrainerRouterActor broadcasts StartTraining to ten TrainerChildActors);
here each worker is a whole OS process that:

- restores its policy weights from the learner's ``tag_best`` through the
  VERIFIED restore path (checkpoint/manager.py checksums + finite check +
  precision-mode check) and keeps them fresh via the serve tier's
  :class:`~sharetrade_tpu.serve.swap.WeightSwapWatcher` — a corrupt
  candidate is refused-not-fatal and the actor keeps rolling out on its
  current weights;
- rolls out epsilon-greedy episodes with EXACTLY the DQN agent's rollout
  semantics (quarantine mask, horizon freeze, epsilon ramp over the
  actor's cumulative env-step count) but NO updates — the learner owns
  the gradient;
- appends its transitions to its OWN journal through the PR-9 data plane
  (CRC-framed records via data/transitions.py, segment rotation +
  retirement, flock'd writer lock — one journal per actor, so a
  concurrent-writer torn record is impossible by construction);
- stamps a heartbeat file the supervising :class:`ActorPool` reads for
  liveness/ages, and drains on SIGTERM the way ``cli train`` does
  (journal flush + final heartbeat, exit 75).

Stamps are the actor's cumulative env-step counter, recovered from its
journal's high-water mark at boot so they stay MONOTONE across actor
restarts — the property the learner's per-actor ingest cursor
(``read_new_transitions``) and the soak's lost-row checks rely on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("distrib.actor")

HEARTBEAT_FILE = "heartbeat.json"
TRANSITIONS_FILE = "transitions.journal"


def write_heartbeat(path: str, **fields: Any) -> None:
    """Atomically rewrite the actor's heartbeat stamp (wall time + rollout
    progress). A transient health stamp, not durable state: no fsync —
    the pool tolerates a lost-on-power-loss heartbeat (the actor process
    is gone too and the reap path owns that case)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"time": time.time(), **fields}, f)
    os.replace(tmp, path)


def read_heartbeat(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def make_rollout_chunk(model, env, lcfg, num_agents: int,
                       chunk_steps: int, precision):
    """One jitted rollout chunk: ``chunk_steps`` epsilon-greedy env steps
    over ``num_agents`` vectorized rows, transitions stacked ``(T, B)``.
    Mirrors the DQN agent's ``one_step`` rollout half verbatim (quarantine
    mask, horizon freeze, epsilon ramp over env_steps) minus the update —
    an actor-produced transition is distributionally the transition the
    integrated agent would have journaled."""
    from sharetrade_tpu.agents.base import epsilon_greedy, quarantine_mask
    from sharetrade_tpu.models.core import apply_batched
    horizon = env.num_steps

    def chunk(params, env_state, rng, env_steps):
        params_c = precision.cast_compute(params)

        def one(carry, _):
            env_state, rng, env_steps = carry
            rng, k_act = jax.random.split(rng)
            act_keys = jax.random.split(k_act, num_agents)
            obs_raw = jax.vmap(env.observe)(env_state)
            healthy = quarantine_mask(obs_raw, env_state)
            active = (env_state.t < horizon) & healthy
            obs = jnp.where(healthy[:, None], obs_raw, 0.0)
            outs, _ = apply_batched(model, params_c, obs, ())
            actions = jax.vmap(
                lambda k, q: epsilon_greedy(k, q, env_steps, lcfg))(
                    act_keys, outs.logits)
            stepped, rewards = jax.vmap(env.step)(env_state, actions)
            env_state = jax.tree.map(
                lambda new, old: jnp.where(
                    active.reshape((-1,) + (1,) * (new.ndim - 1)),
                    new, old),
                stepped, env_state)
            rewards = jnp.where(active, rewards, 0.0)
            next_obs = jnp.where(
                healthy[:, None], jax.vmap(env.observe)(env_state), 0.0)
            env_steps = env_steps + jnp.where(jnp.any(active), 1, 0)
            return ((env_state, rng, env_steps),
                    (obs, actions, rewards, next_obs, active))

        (env_state, rng, env_steps), tr = jax.lax.scan(
            one, (env_state, rng, env_steps), None, length=chunk_steps)
        # min cursor over rows: horizon-complete detection without a
        # second readback (== horizon means every row finished its
        # episode and the host re-arms a fresh one).
        return env_state, rng, env_steps, jnp.min(env_state.t), tr

    return jax.jit(chunk)


class RolloutActor:
    """One rollout actor: policy forwards only, transitions out, weights
    in. Built from the same config the learner runs so env/model/precision
    agree with the checkpoints it restores."""

    def __init__(self, cfg: FrameworkConfig, prices, *, actor_id: str,
                 workdir: str):
        if not actor_id or not all(
                c.isalnum() or c in "-_" for c in actor_id):
            raise ConfigError(f"bad actor id {actor_id!r} "
                              "(alphanumeric/-/_ only)")
        self.cfg = cfg
        self.actor_id = actor_id
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.heartbeat_path = os.path.join(workdir, HEARTBEAT_FILE)

        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.env import trading
        from sharetrade_tpu.env.portfolio import make_portfolio_env
        from sharetrade_tpu.precision import policy_from_config
        prices = np.asarray(prices)
        if prices.ndim == 2 and prices.shape[0] > 1:
            self.env = make_portfolio_env(
                prices, window=cfg.env.window,
                initial_budget=cfg.env.initial_budget,
                initial_shares=cfg.env.initial_shares)
        else:
            self.env = trading.make_trading_env(
                prices.reshape(-1), window=cfg.env.window,
                initial_budget=cfg.env.initial_budget,
                initial_shares=cfg.env.initial_shares)
        # The FULL agent is built only for its model + checkpoint template
        # (the TrainState pytree tag_best deserializes into — the same
        # template a --resume or cli serve uses); the agent's step/update
        # machinery is never called here.
        self._agent = build_agent(cfg, self.env)
        self._precision = policy_from_config(cfg.precision)
        # Per-actor seed: distinct exploration streams per actor, stable
        # across restarts of the same actor id.
        import zlib
        self._seed = cfg.seed + 101 + (
            zlib.crc32(actor_id.encode()) % 100003)
        self._template = self._agent.init(jax.random.PRNGKey(self._seed))

        # Per-actor transitions journal (Python backend: writer lock +
        # segment rotation; the native single-file writer has neither).
        from sharetrade_tpu.data.journal import Journal
        self.journal_path = os.path.join(workdir, TRANSITIONS_FILE)
        self._journal = Journal(
            self.journal_path,
            fsync_every_records=cfg.data.journal_fsync_every_records,
            fsync_interval_s=cfg.data.journal_fsync_interval_s,
            segment_records=cfg.data.journal_segment_records)
        # Monotone-stamp recovery: continue the env-step counter from the
        # journal's high-water so a respawned actor never reuses a stamp
        # (the learner's ingest cursor and the epsilon ramp both ride it).
        from sharetrade_tpu.data.transitions import read_tail_transitions
        tail = read_tail_transitions(self.journal_path, 1,
                                     journal=self._journal)
        self._env_steps0 = int(tail[4]) if tail is not None else 0
        self._rows_since_retire = 0

        # Weight flow: boot from tag_best -> latest step -> fresh init
        # (loud), then keep fresh via the verified-restore swap watcher.
        from sharetrade_tpu.checkpoint.manager import CheckpointManager
        self._manager = CheckpointManager(
            cfg.runtime.checkpoint_dir, keep=cfg.runtime.keep_checkpoints,
            fsync=cfg.checkpoint.fsync, precision_mode=cfg.precision.mode)
        self.registry = None        # duck-typed for WeightSwapWatcher
        self._params_lock = threading.Lock()
        self._pending: tuple[Any, int] | None = None
        self.params, self.params_step, self._boot_meta = self._boot_params()
        self._watcher = None
        self.episodes = 0
        self.chunks = 0
        self.rows_journaled = 0
        self.swaps_applied = 0

        chunk_steps = (cfg.distrib.actor_chunk_steps
                       or cfg.runtime.chunk_steps)
        self._chunk_fn = make_rollout_chunk(
            self._agent.model, self.env, cfg.learner,
            cfg.parallel.num_workers, chunk_steps, self._precision)

    # -- WeightSwapWatcher engine surface ------------------------------

    def swap_params(self, params, step: int) -> None:
        """Stage freshly-verified weights; the rollout loop installs them
        at its next chunk boundary (no mid-chunk weight mix — the chunk's
        program closed over its params argument when it dispatched)."""
        with self._params_lock:
            self._pending = (params, int(step))

    def _boot_params(self):
        tag = "best"
        try:
            state, meta = self._manager.restore_tagged(self._template, tag)
            return (state.params,
                    int(meta.get("updates", meta.get("step", 0)) or 0),
                    meta)
        except FileNotFoundError:
            pass
        except Exception as exc:        # refusal-not-fatal, like serve
            log.warning("actor %s: tag_%s boot restore refused (%s: %s); "
                        "falling back", self.actor_id, tag,
                        type(exc).__name__, exc)
        try:
            state, step = self._manager.restore(self._template)
            return state.params, int(step), None
        except FileNotFoundError:
            log.warning("actor %s: no checkpoint under %s; rolling out a "
                        "fresh-initialized (UNTRAINED) policy",
                        self.actor_id, self._manager.directory)
            return self._template.params, 0, None

    # ------------------------------------------------------------------

    def run(self, stop: threading.Event, *,
            max_chunks: int = 0) -> dict[str, Any]:
        """The actor loop: rollout chunk -> journal append -> heartbeat,
        until ``stop`` is set (or ``max_chunks`` chunks for tests).
        Returns a summary dict. Never raises out of a single bad poll of
        the weight watcher (its thread catches); a rollout/journal fault
        does propagate — the POOL is the supervisor that restarts this
        process, exactly the contract under test."""
        cfg = self.cfg
        from sharetrade_tpu.agents.base import batched_reset
        from sharetrade_tpu.data.transitions import append_transitions
        from sharetrade_tpu.serve.swap import WeightSwapWatcher
        if cfg.distrib.weight_poll_s > 0:
            self._watcher = WeightSwapWatcher(
                self, self._manager, self._template, tag="best",
                poll_s=cfg.distrib.weight_poll_s,
                seen_meta=self._boot_meta,
                breaker_failures=cfg.serve.swap_breaker_failures,
                breaker_cooldown_s=cfg.serve.swap_breaker_cooldown_s,
            ).start()
        num_agents = cfg.parallel.num_workers
        horizon = self.env.num_steps
        env_state = batched_reset(self.env, num_agents)
        rng = jax.random.PRNGKey(self._seed + 1)
        env_steps = jnp.int32(self._env_steps0)
        hb_every = max(cfg.distrib.heartbeat_interval_s, 0.05)
        last_hb = 0.0
        self._heartbeat(env_steps=self._env_steps0, phase="starting")
        try:
            while not stop.is_set():
                with self._params_lock:
                    if self._pending is not None:
                        self.params, self.params_step = self._pending
                        self._pending = None
                        self.swaps_applied += 1
                env_state, rng, env_steps, min_t, tr = self._chunk_fn(
                    self.params, env_state, rng, env_steps)
                stamp = int(env_steps)
                self._journal_chunk(tr, stamp, append_transitions)
                self.chunks += 1
                if int(min_t) >= horizon:
                    # Every row finished its episode: re-arm a fresh one
                    # (cumulative env_steps keeps the epsilon ramp — the
                    # Initialise->Train cycle at actor granularity).
                    self.episodes += 1
                    env_state = batched_reset(self.env, num_agents)
                now = time.monotonic()
                if now - last_hb >= hb_every:
                    last_hb = now
                    self._heartbeat(env_steps=stamp, phase="rolling")
                if max_chunks and self.chunks >= max_chunks:
                    break
        finally:
            if self._watcher is not None:
                self._watcher.stop()
            # Drain: every acked append durable, then the terminal stamp.
            self._journal.flush()
            self._journal.close()
            self._heartbeat(env_steps=int(env_steps), phase="drained")
        return self.summary(int(env_steps))

    def _journal_chunk(self, tr, stamp: int, append_transitions) -> None:
        """Host side of one chunk: ONE batched readback of the stacked
        (T, B) transition buffers, valid rows flattened and appended as a
        single packed record stamped with the chunk-end env-step count."""
        obs, actions, rewards, next_obs, active = jax.device_get(tr)
        valid = np.asarray(active).reshape(-1)
        if not valid.any():
            return
        flat = lambda a: np.asarray(a).reshape(  # noqa: E731
            (-1,) + np.asarray(a).shape[2:])
        append_transitions(
            self._journal, flat(obs)[valid], flat(actions)[valid],
            flat(rewards)[valid], flat(next_obs)[valid], env_steps=stamp)
        n = int(valid.sum())
        self.rows_journaled += n
        self._rows_since_retire += n
        capacity = self.cfg.learner.replay_capacity
        if (self.cfg.data.journal_segment_records > 0
                and self._rows_since_retire >= capacity):
            # Bounded per-actor disk: same 2x-capacity horizon as the
            # learner's own journal (PR-9 retirement).
            from sharetrade_tpu.data.transitions import (
                retire_transition_segments)
            retire_transition_segments(self._journal, 2 * capacity)
            self._rows_since_retire = 0

    def _heartbeat(self, *, env_steps: int, phase: str) -> None:
        write_heartbeat(
            self.heartbeat_path, pid=os.getpid(), actor_id=self.actor_id,
            env_steps=env_steps, episodes=self.episodes,
            chunks=self.chunks, rows=self.rows_journaled,
            params_step=self.params_step, phase=phase)

    def summary(self, env_steps: int) -> dict[str, Any]:
        return {
            "actor_id": self.actor_id,
            "env_steps": env_steps,
            "episodes": self.episodes,
            "chunks": self.chunks,
            "rows_journaled": self.rows_journaled,
            "params_step": self.params_step,
            "swaps_applied": self.swaps_applied,
            "swaps_rejected": (self._watcher.rejected
                               if self._watcher is not None else 0),
        }
