"""The process-supervision LADDER shared by every pool of child
processes — one definition of the PR-5/PR-10 contract at process
granularity.

Two supervisors apply it today: :class:`~sharetrade_tpu.distrib.pool.
ActorPool` (rollout-actor subprocesses under a live learner, PR 12) and
:class:`~sharetrade_tpu.fleet.pool.EnginePool` (whole serve-engine
worker processes behind the fleet router). Both classify every child
exit the same way — a retiring/quiesced child retires quietly, anything
else is a CRASH feeding seeded exponential backoff, and a consecutive-
crash streak past the restart budget is a TERMINAL failure the pool
degrades around instead of respawning forever. Factoring the ladder here
(ISSUE 15 satellite) means a contract fix lands in both pools instead of
drifting between copies; everything pool-SPECIFIC — what "healthy"
means (heartbeat file vs HTTP healthz), how a child spawns, what state
file gets written — stays with the pool that owns it.

The states and the crash arithmetic are EXACTLY the ActorPool's
pre-factor behavior (its kill-test and unit suite pin them): the jitter
draw is one ``rng.uniform(-jitter, +jitter)`` per crash, so a seeded
pool replays the same backoff schedule it always did.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

#: Child lifecycle states (the status.json vocabulary, shared verbatim).
STARTING, ALIVE, BACKOFF, FAILED, RETIRING, RETIRED = (
    "starting", "alive", "backoff", "failed", "retiring", "retired")

#: States that count as LIVE membership (toward a pool's scale target).
LIVE_STATES = (STARTING, ALIVE, BACKOFF)


@dataclass(frozen=True)
class LadderPolicy:
    """The supervision knobs, pool-agnostic: how many consecutive crashes
    a child may burn before it is terminally FAILED, and the seeded
    exponential-backoff schedule between respawns."""

    max_restarts: int
    backoff_initial_s: float
    backoff_max_s: float
    backoff_jitter: float

    def validate(self, *, section: str) -> None:
        from sharetrade_tpu.config import ConfigError
        if self.max_restarts < 0:
            raise ConfigError(
                f"{section} max restarts must be >= 0, got "
                f"{self.max_restarts}")
        if self.backoff_initial_s <= 0 or self.backoff_max_s <= 0:
            raise ConfigError(
                f"{section} backoff seconds must be > 0, got "
                f"{self.backoff_initial_s}/{self.backoff_max_s}")


def crash_step(streak: int, policy: LadderPolicy,
               rng: random.Random) -> tuple[str, float]:
    """One rung of the ladder, applied AFTER a crash bumped the child's
    consecutive-crash ``streak``: returns ``(next_state, respawn_delay_s)``
    — :data:`FAILED` (delay 0, the pool degrades onto survivors) once the
    streak exceeds the budget, else :data:`BACKOFF` with the seeded
    jittered exponential delay. Draws exactly one jitter sample from
    ``rng`` on the BACKOFF arm (the replayable-schedule contract)."""
    if streak > policy.max_restarts:
        return FAILED, 0.0
    delay = min(policy.backoff_initial_s * 2 ** (streak - 1),
                policy.backoff_max_s)
    delay *= 1.0 + rng.uniform(-policy.backoff_jitter,
                               policy.backoff_jitter)
    return BACKOFF, max(delay, 0.0)
