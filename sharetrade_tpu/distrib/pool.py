"""The :class:`ActorPool` supervisor — the PR-5/PR-10 supervision
contract applied at PROCESS granularity.

The reference wraps each worker in a BackoffSupervisor envelope
(TrainerRouterActor.scala:46-52) inside one JVM; here each worker is a
whole OS process (``cli actor``) and the pool is its supervisor:

- **spawn/reap**: ``start()`` launches N rollout-actor subprocesses; the
  supervise thread polls (``_reap``) and classifies every exit — a
  retiring actor (scale-down / shutdown) retires quietly, anything else
  is a CRASH;
- **seeded exponential backoff**: a crashed actor respawns after
  ``distrib.actor_backoff_initial_s * 2^(streak-1)`` (capped, jittered
  from the run's seed — reproducible kill schedules stay reproducible);
- **terminal failure**: a consecutive-crash streak past
  ``distrib.max_actor_restarts`` marks the actor FAILED and the pool
  degrades gracefully onto the survivors (the Escalate arm, scoped to one
  failure domain). The streak resets once a respawned actor proves
  healthy — its heartbeat reaches the ``rolling`` phase, i.e. bring-up
  plus at least one journaled chunk survived;
- **heartbeats**: every actor's heartbeat age is read each tick
  (``_heartbeat_ages``), exported as gauges, and — with
  ``distrib.heartbeat_timeout_s`` set — a silent actor is presumed wedged
  and killed (counts as a crash, so the backoff/terminal ladder applies);
- **elastic membership**: ``scale(n)`` adds fresh actors or retires the
  newest ones against a LIVE learner (a retiring actor gets SIGTERM and
  drains like ``cli train``); the ``scale`` control file in the pool dir
  drives the same call from outside the process (the soak's mid-run
  join);
- **observability**: gauges ``actors_alive`` / ``actors_failed``,
  counter ``actor_restarts_total``, per-actor heartbeat-age gauges, and
  an atomically-rewritten ``status.json`` naming every member's pid /
  state / restarts / heartbeat age — what the kill-test reconciles
  against its injection log.

Retired/failed handles are RETAINED in the roster by design: the
kill-test's counter reconciliation (``restarts_total`` == the sum over
every member ever spawned) and the operator's post-mortem both need the
full membership history, and corpses cost nothing per tick (their
heartbeat files are not re-read and their journals stop growing). A
pathological churn rate grows status.json linearly with total spawns —
acceptable at one small dict entry per actor ever spawned.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.distrib.actor import HEARTBEAT_FILE, read_heartbeat
from sharetrade_tpu.distrib.ladder import (
    ALIVE,
    BACKOFF,
    FAILED,
    RETIRED,
    RETIRING,
    STARTING,
    LadderPolicy,
    crash_step,
)
from sharetrade_tpu.utils.logging import get_logger

log = get_logger("distrib.pool")

STATUS_FILE = "status.json"
SCALE_FILE = "scale"
CONFIG_FILE = "actor_config.json"


def read_status(pool_dir: str) -> dict | None:
    """Read the pool's status.json (None when absent/torn — the write is
    atomic, so torn means 'not written yet')."""
    try:
        with open(os.path.join(pool_dir, STATUS_FILE),
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@dataclass
class _ActorHandle:
    actor_id: str
    proc: subprocess.Popen | None = None
    state: str = STARTING
    restarts: int = 0
    streak: int = 0
    spawned_at: float = 0.0
    respawn_at: float = 0.0
    last_rc: int | None = None
    heartbeat: dict = field(default_factory=dict)
    heartbeat_age_s: float | None = None

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None


class ActorPool:
    """Supervisor for ``cli actor`` subprocesses (see module docstring).

    ``spawn_fn(actor_id, workdir) -> Popen`` overrides the spawn command —
    the supervision tests drive the reap/backoff/terminal ladder with a
    cheap stub child instead of a full jax bring-up."""

    def __init__(self, cfg: FrameworkConfig, *, workdir: str | None = None,
                 registry: Any = None, symbol: str = "MSFT",
                 start: str | None = None, end: str | None = None,
                 spawn_fn: Callable[[str, str], subprocess.Popen]
                 | None = None):
        dc = cfg.distrib
        if dc.max_actor_restarts < 0:
            raise ConfigError("distrib.max_actor_restarts must be >= 0, "
                              f"got {dc.max_actor_restarts}")
        self.cfg = cfg
        self.dir = workdir or dc.actor_dir
        os.makedirs(self.dir, exist_ok=True)
        self.registry = registry
        self._symbol, self._start, self._end = symbol, start, end
        self._spawn_fn = spawn_fn
        self._rng = random.Random(cfg.seed ^ 0xAC7)
        self._actors: dict[str, _ActorHandle] = {}
        self._next_index = 0
        self._scale_file_applied: int | None = None
        self.target = 0
        self.restarts_total = 0
        self.scale_events = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._quiesced = threading.Event()
        self._thread: threading.Thread | None = None
        self._config_path: str | None = None
        self.started_at = time.time()

    # ---- membership -------------------------------------------------

    def start(self, n: int | None = None) -> "ActorPool":
        """Spawn the initial membership and the supervise thread."""
        n = self.cfg.distrib.num_actors if n is None else n
        with self._lock:
            self.target = n
            for _ in range(n):
                self._spawn_new_locked()
            self._write_status_locked()
        self._thread = threading.Thread(target=self._supervise,
                                        name="actor-pool", daemon=True)
        self._thread.start()
        return self

    def scale(self, n: int) -> None:
        """Elastic membership against a LIVE learner: grow by spawning
        fresh actors, shrink by retiring the newest non-failed ones
        (SIGTERM -> graceful drain -> retired). Terminally-failed actors
        do not count toward the target — scaling past a failure is
        exactly how an operator replaces a dead member."""
        if self._quiesced.is_set():
            # The learner is draining: a scale request now would spawn
            # fresh actors into a dying run (the respawn path already
            # refuses for the same reason).
            log.warning("pool is quiescing; ignoring scale(%d)", n)
            return
        with self._lock:
            if n < 0:
                raise ConfigError(f"cannot scale to {n} actors")
            self.target = n
            self.scale_events += 1
            live = [h for h in self._actors.values()
                    if h.state in (STARTING, ALIVE, BACKOFF)]
            if n > len(live):
                for _ in range(n - len(live)):
                    self._spawn_new_locked()
            elif n < len(live):
                # Retire the newest members: NUMERIC spawn order, not
                # lexical actor_id order ("a9" > "a10" lexically).
                for h in sorted(live, key=lambda h: int(h.actor_id[1:]),
                                reverse=True)[:len(live) - n]:
                    self._retire_locked(h)
            self._write_status_locked()
            membership = {h.actor_id: h.state
                          for h in self._actors.values()}
        log.info("actor pool scaled to %d (membership now %s)", n,
                 membership)

    def _spawn_new_locked(self) -> _ActorHandle:
        actor_id = f"a{self._next_index}"
        self._next_index += 1
        handle = _ActorHandle(actor_id=actor_id)
        self._actors[actor_id] = handle
        self._spawn_locked(handle)
        return handle

    def _spawn_locked(self, handle: _ActorHandle) -> None:
        workdir = os.path.join(self.dir, handle.actor_id)
        os.makedirs(workdir, exist_ok=True)
        # A stale heartbeat from the previous incarnation must not make a
        # just-respawned actor look instantly healthy (the streak-reset
        # and timeout logic key off phase/pid below, but age math does
        # not need a dead process's stamp).
        try:
            os.remove(os.path.join(workdir, HEARTBEAT_FILE))
        except FileNotFoundError:
            pass
        if self._spawn_fn is not None:
            handle.proc = self._spawn_fn(handle.actor_id, workdir)
        else:
            if self._config_path is None:
                self._config_path = os.path.join(self.dir, CONFIG_FILE)
                self.cfg.save(self._config_path)
            cmd = [sys.executable, "-m", "sharetrade_tpu.cli", "actor",
                   "--config", self._config_path,
                   "--actor-id", handle.actor_id,
                   "--symbol", self._symbol]
            if self._start:
                cmd += ["--start", self._start]
            if self._end:
                cmd += ["--end", self._end]
            # Merged child output to a per-actor FILE (a pipe nobody
            # drains wedges the child at ~64 KB — the crash-soak lesson).
            log_f = open(os.path.join(self.dir,
                                      f"{handle.actor_id}.log"), "ab")
            try:
                handle.proc = subprocess.Popen(
                    cmd, stdout=log_f, stderr=subprocess.STDOUT)
            finally:
                log_f.close()
        handle.state = STARTING
        handle.spawned_at = time.monotonic()
        handle.respawn_at = 0.0
        handle.heartbeat = {}           # predecessor's stamp is not ours
        handle.heartbeat_age_s = None
        log.info("actor %s spawned (pid %s)", handle.actor_id, handle.pid)

    def _retire_locked(self, handle: _ActorHandle) -> None:
        if handle.proc is not None and handle.proc.poll() is None:
            handle.state = RETIRING
            try:
                handle.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        else:
            handle.state = RETIRED

    # ---- supervision ------------------------------------------------

    def _supervise(self) -> None:
        interval = max(self.cfg.distrib.supervise_interval_s, 0.05)
        while not self._stop.wait(interval):
            try:
                self.poll_once()
            except Exception:   # noqa: BLE001 — the supervisor outlives
                log.exception("actor-pool supervise tick failed")

    def poll_once(self) -> None:
        """One supervise tick (public so tests and a synchronous driver
        can step the pool deterministically): reap exits, age heartbeats,
        enforce the heartbeat timeout, respawn due backoffs, apply the
        scale control file, publish status + gauges."""
        with self._lock:
            self._reap()
            ages = self._heartbeat_ages()
            self._enforce_heartbeat_timeout(ages)
            self._respawn_due()
            self._apply_scale_file()
            self._write_status_locked()
            self._export_gauges(ages)

    def quiesce(self) -> None:
        """Stop respawning: the LEARNER is preempting (SIGTERM to the
        whole process group — a fleet preemption TERMs every member at
        once), so an actor exiting from here on is draining, not
        crashing. Without this, the pool reaps the concurrently-TERM'd
        actors' graceful exits as crashes and respawns fresh actors into
        a dying run (observed: pid storm during the drain window)."""
        self._quiesced.set()

    def _reap(self) -> None:
        """Classify every exited child: retiring -> retired; anything
        else is a crash feeding the backoff/terminal ladder."""
        dc = self.cfg.distrib
        for h in self._actors.values():
            if h.proc is None or h.state in (FAILED, RETIRED, BACKOFF):
                continue
            rc = h.proc.poll()
            if rc is None:
                continue
            h.last_rc = rc
            if h.state == RETIRING or self._quiesced.is_set():
                h.state = RETIRED
                log.info("actor %s retired (rc=%s)", h.actor_id, rc)
                continue
            h.streak += 1
            h.restarts += 1
            self.restarts_total += 1
            if self.registry is not None:
                self.registry.inc("actor_restarts_total")
            # The shared supervision ladder (distrib/ladder.py): one
            # definition of terminal-vs-backoff and the seeded jittered
            # exponential schedule, shared with the fleet's EnginePool.
            state, delay = crash_step(
                h.streak,
                LadderPolicy(max_restarts=dc.max_actor_restarts,
                             backoff_initial_s=dc.actor_backoff_initial_s,
                             backoff_max_s=dc.actor_backoff_max_s,
                             backoff_jitter=dc.actor_backoff_jitter),
                self._rng)
            h.state = state
            if state == FAILED:
                log.error(
                    "actor %s FAILED terminally: %d consecutive crashes "
                    "past distrib.max_actor_restarts=%d (last rc=%s); "
                    "pool degrades onto the survivors",
                    h.actor_id, h.streak, dc.max_actor_restarts, rc)
                continue
            h.respawn_at = time.monotonic() + delay
            log.warning("actor %s crashed (rc=%s); restart %d "
                        "(streak %d/%d) in %.2fs", h.actor_id, rc,
                        h.restarts, h.streak, dc.max_actor_restarts, delay)

    def _heartbeat_ages(self) -> dict[str, float | None]:
        """Read every member's heartbeat stamp; a ``rolling``-phase
        heartbeat from the CURRENT incarnation proves the respawn healthy
        and resets its crash streak."""
        now = time.time()
        ages: dict[str, float | None] = {}
        for h in self._actors.values():
            if h.state in (RETIRED, FAILED):
                # A corpse's heartbeat file lingers on disk: re-reading
                # it every tick exports an ever-climbing age gauge that
                # reads as a wedged actor (and costs one file read per
                # dead member forever under elastic churn).
                continue
            hb = read_heartbeat(os.path.join(self.dir, h.actor_id,
                                             HEARTBEAT_FILE))
            if hb is None:
                h.heartbeat_age_s = None
                ages[h.actor_id] = None
                continue
            h.heartbeat = hb
            h.heartbeat_age_s = max(0.0, now - float(hb.get("time", 0.0)))
            ages[h.actor_id] = h.heartbeat_age_s
            if (h.state == STARTING and hb.get("pid") == h.pid
                    and hb.get("phase") == "rolling"):
                h.state = ALIVE
                h.streak = 0
        return ages

    def _enforce_heartbeat_timeout(
            self, ages: dict[str, float | None]) -> None:
        timeout = self.cfg.distrib.heartbeat_timeout_s
        if timeout <= 0:
            return
        for h in self._actors.values():
            # ALIVE actors, and STARTING ones that have stamped at least
            # once from the CURRENT incarnation (a wedge during bring-up
            # must not escape the contract; before the first stamp there
            # is no age to enforce — the spawn wiped the predecessor's).
            if h.state not in (ALIVE, STARTING) or h.proc is None \
                    or h.proc.poll() is not None:
                continue
            if h.state == STARTING and h.heartbeat.get("pid") != h.pid:
                continue
            age = ages.get(h.actor_id)
            if age is not None and age > timeout:
                log.error("actor %s heartbeat stale (%.1fs > %.1fs); "
                          "killing the presumed-wedged process",
                          h.actor_id, age, timeout)
                try:
                    h.proc.kill()    # the next _reap classifies the crash
                except ProcessLookupError:
                    pass

    def _respawn_due(self) -> None:
        if self._quiesced.is_set():
            return
        now = time.monotonic()
        for h in self._actors.values():
            if h.state == BACKOFF and now >= h.respawn_at:
                self._spawn_locked(h)

    def _apply_scale_file(self) -> None:
        """The out-of-process elastic-membership lever: an operator (or
        the kill-test) writes a target count into ``<dir>/scale`` and the
        live pool converges to it — no learner restart, no IPC beyond a
        file the status already lives next to."""
        try:
            with open(os.path.join(self.dir, SCALE_FILE),
                      encoding="utf-8") as f:
                n = int(f.read().strip())
        except (OSError, ValueError):
            return
        if n < 0:
            # Validated here, not in scale(): a ConfigError out of the
            # supervise tick would re-raise every interval for as long
            # as the file holds the bad value.
            return
        if n != self._scale_file_applied:
            # Compare against the last APPLIED file value, not the
            # target: a lingering file must not silently re-undo a later
            # programmatic scale() call on every supervise tick.
            self._scale_file_applied = n
            if n != self.target:
                # scale() re-enters the lock (RLock), rewrites status.
                self.scale(n)

    # ---- observability ----------------------------------------------

    def counts(self) -> dict[str, int]:
        with self._lock:
            states = [h.state for h in self._actors.values()]
        return {
            "alive": sum(s in (STARTING, ALIVE, RETIRING) for s in states),
            "backoff": sum(s == BACKOFF for s in states),
            "failed": sum(s == FAILED for s in states),
            "retired": sum(s == RETIRED for s in states),
        }

    def _export_gauges(self, ages: dict[str, float | None]) -> None:
        if self.registry is None:
            return
        c = self.counts()
        self.registry.record("actors_alive", float(c["alive"]))
        self.registry.record("actors_failed", float(c["failed"]))
        self.registry.record("actors_backoff", float(c["backoff"]))
        for actor_id, age in ages.items():
            if age is not None:
                self.registry.record(
                    f"actor_heartbeat_age_s_{actor_id}", age)

    def _write_status_locked(self) -> None:
        status = {
            "pid": os.getpid(),
            "started_at": self.started_at,
            "target": self.target,
            "restarts_total": self.restarts_total,
            "scale_events": self.scale_events,
            **self.counts(),
            "actors": {
                h.actor_id: {
                    "pid": h.pid, "state": h.state,
                    "restarts": h.restarts, "streak": h.streak,
                    "last_rc": h.last_rc,
                    "heartbeat_age_s": h.heartbeat_age_s,
                    "env_steps": h.heartbeat.get("env_steps"),
                    "rows": h.heartbeat.get("rows"),
                    "params_step": h.heartbeat.get("params_step"),
                } for h in self._actors.values()},
        }
        tmp = os.path.join(self.dir, f".{STATUS_FILE}.tmp-{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(status, f, indent=2)
        os.replace(tmp, os.path.join(self.dir, STATUS_FILE))

    def journal_paths(self) -> dict[str, str]:
        """Per-actor transitions-journal paths (the learner's ingest set)."""
        from sharetrade_tpu.distrib.actor import TRANSITIONS_FILE
        with self._lock:
            return {aid: os.path.join(self.dir, aid, TRANSITIONS_FILE)
                    for aid in self._actors}

    # ---- shutdown ---------------------------------------------------

    def kill_all(self) -> None:
        """Last-resort fleet teardown for the learner's HARD-exit paths
        (drain grace expired, second signal): ``os._exit`` skips every
        finally block, so anything not killed here is an orphaned actor
        process rolling out forever with no supervisor. SIGKILL — there
        is no time left to drain."""
        self._quiesced.set()
        with self._lock:
            for h in self._actors.values():
                if h.proc is not None and h.proc.poll() is None:
                    try:
                        h.proc.kill()
                    except ProcessLookupError:
                        pass

    def stop(self, grace_s: float = 15.0) -> None:
        """Drain the fleet: SIGTERM every live actor (they drain their
        journals and exit 75 like ``cli train``), SIGKILL stragglers past
        the grace, stop the supervise thread, publish a final status."""
        self._quiesced.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=grace_s)
        with self._lock:
            live = [h for h in self._actors.values()
                    if h.proc is not None and h.proc.poll() is None]
            for h in live:
                h.state = RETIRING
                try:
                    h.proc.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + grace_s
        for h in live:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                log.warning("actor %s did not drain in %.1fs; SIGKILL",
                            h.actor_id, grace_s)
                h.proc.kill()
                h.proc.wait(timeout=10)
            h.last_rc = h.proc.returncode
            h.state = RETIRED
        with self._lock:
            self._write_status_locked()
