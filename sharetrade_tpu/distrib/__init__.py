"""Disaggregated actor/learner topology — the reference's ten-worker
actor system (TrainerRouterActor.scala:36) run as separate OS-process
failure domains (ROADMAP item 1; MSRL's per-fragment restart property,
arxiv 2210.00882; Podracer's Sebulba actor/learner split, arxiv
2104.06272).

- :mod:`sharetrade_tpu.distrib.actor` — the rollout-actor process body
  (``cli actor``): verified-restore weights from ``tag_best``, epsilon-
  greedy episode rollouts, per-actor transitions journal, heartbeat.
- :mod:`sharetrade_tpu.distrib.pool` — the :class:`ActorPool` supervisor:
  spawns/reaps/respawns actor subprocesses under the PR-5/PR-10
  supervision contract at process granularity, with elastic membership
  (``scale``) against a live learner.

The learner side lives in ``runtime/orchestrator.py``
(``ingest_actor_feeds``): the training loop tails every actor journal
between megachunks and splices the new rows into its device replay
buffer — actors die and rejoin without the learner ever restarting.
"""

from sharetrade_tpu.distrib.actor import RolloutActor, write_heartbeat  # noqa: F401
from sharetrade_tpu.distrib.pool import ActorPool, read_status  # noqa: F401
