"""Command-line driver — the ShareTradeHelper entry point, with flags.

Reference: ``object ShareTradeHelper extends App`` wires the system with
hard-coded constants and polls ``IsEverythingDone`` every 5 s
(ShareTradeHelper.scala:14-48). Here the same flow takes a config file +
``--set section.key=value`` overrides (the flag surface the reference lacks,
SURVEY.md §5), runs the compiled training loop, and reports the avg/std
portfolio aggregation plus throughput.

    python -m sharetrade_tpu.cli train [--config cfg.json] [--set k=v ...]
    python -m sharetrade_tpu.cli query --config cfg.json   # inspect data layer
    python -m sharetrade_tpu.cli obs --dir obs             # summarize a run dir
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.data.service import PriceDataService
from sharetrade_tpu.utils.logging import configure, get_logger

log = get_logger("cli")

#: Exit code of a run that was preempted (SIGTERM/SIGINT) and wrote its
#: ``tag_preempt`` emergency checkpoint path — EX_TEMPFAIL from sysexits.h:
#: "temporary failure; the user is invited to retry", which is exactly what
#: a fleet scheduler should do (relaunch with ``--resume``). Distinct from
#: 0 (completed) and 1 (failed) so supervisors can tell the three apart.
EXIT_PREEMPTED = 75


def _load_config(args) -> FrameworkConfig:
    cfg = (FrameworkConfig.from_file(args.config) if args.config
           else FrameworkConfig())
    if args.set:
        cfg = cfg.apply_overrides(args.set)
    # Tuned-profile resolution (tuning.py): file/--set values are the
    # EXPLICIT tier and win; registered knobs still at their defaults
    # take the per-host profile's values. Resolved here once so every
    # subcommand (train/serve/learner/actor) runs the same knobs the
    # manifest will report.
    from sharetrade_tpu.tuning import apply_profile
    return apply_profile(cfg)


def cmd_train(args) -> int:
    from sharetrade_tpu.runtime import Orchestrator, ReplyState
    from sharetrade_tpu.parallel import build_mesh

    cfg = _load_config(args)
    service = PriceDataService(config=cfg.data)
    orch = None

    # Preemption handling: a TERM (fleet/TPU-pod preemption notice) or INT
    # asks the orchestrator to drain at its next megachunk boundary and
    # write the tag_preempt emergency checkpoint; the poll loop below
    # enforces runtime.preempt_grace_s and exits EXIT_PREEMPTED. Installed
    # BEFORE the (slow) data/orchestrator/compile bring-up so a preemption
    # notice during startup is never lost to the default signal disposition
    # — it is replayed onto the orchestrator the moment one exists.
    # Installed here (not in the Orchestrator) because signal handlers
    # belong to the process entry point — library users wire
    # orch.request_preempt() to whatever notification their fleet uses.
    preempt_at: list[float] = []

    def _on_signal(signum, frame):
        if not preempt_at:
            log.warning("received %s; requesting preemption drain",
                        signal.Signals(signum).name)
            preempt_at.append(time.monotonic())
        else:
            # Second signal escalates: an interactive Ctrl-C on a wedged
            # drain must not have to wait out the grace+5s hard-exit
            # timer. Whatever the drain already made durable is the
            # resume point.
            log.warning("received %s during the drain; hard exit",
                        signal.Signals(signum).name)
            os._exit(EXIT_PREEMPTED)
        if orch is not None:
            orch.request_preempt()

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)}

    try:
        symbols = [s.strip() for s in args.symbol.split(",") if s.strip()]
        if len(symbols) > 1:
            # Multi-asset portfolio: align the symbols on common dates.
            from sharetrade_tpu.data.ingest import align_series
            series = [service.request(s, args.start, args.end).series
                      for s in symbols]
            prices = align_series(series)
            log.info("loaded %s prices for %d assets %s",
                     prices.shape, len(symbols), symbols)
        else:
            response = service.request(symbols[0], args.start, args.end)
            prices = response.series.prices
            log.info("loaded %d prices for %s", len(prices), symbols[0])

        mesh = build_mesh(cfg.parallel) if args.mesh else None
        if mesh is not None:
            # The agent batch shards over dp; round workers up to a multiple
            # so the default 10 workers still run on an 8-chip mesh.
            dp = mesh.shape.get(cfg.parallel.data_axis, 1)
            if cfg.parallel.num_workers % dp:
                adjusted = ((cfg.parallel.num_workers + dp - 1) // dp) * dp
                log.warning("num_workers=%d not divisible by dp=%d; using %d",
                            cfg.parallel.num_workers, dp, adjusted)
                cfg.parallel.num_workers = adjusted
        orch = Orchestrator(cfg, mesh=mesh)
        if preempt_at:
            # A notice arrived during bring-up: replay it — the run will
            # drain at its first boundary and exit EXIT_PREEMPTED. The
            # grace clock re-anchors HERE so the hard-exit timer below and
            # the orchestrator's drain deadline (anchored inside
            # request_preempt) agree — otherwise a long bring-up would let
            # the hard exit kill the emergency save inside its own budget.
            preempt_at[0] = time.monotonic()
            orch.request_preempt()

        t0 = time.perf_counter()
        try:
            orch.send_training_data(prices, resume=args.resume)
        except FileNotFoundError as exc:
            log.error("--resume: %s (train without --resume first)", exc)
            return 1
        orch.start_training(background=True)

        # Driver poll loop (ShareTradeHelper.scala:32-48), with a sane cadence.
        poll_s = cfg.runtime.poll_interval_s
        grace = cfg.runtime.preempt_grace_s
        while not orch.wait(timeout=poll_s):
            if preempt_at:
                if time.monotonic() - preempt_at[0] > grace + 5.0:
                    # The drain overran its budget (a wedged device call, a
                    # hung disk): hard-exit with the preemption code — the
                    # fleet's KILL follows the TERM regardless, and whatever
                    # the drain already made durable is what --resume gets.
                    # os._exit on purpose: a graceful stop() here would
                    # block on the very threads that overran the budget.
                    log.error("preemption grace (%.1fs) expired before the "
                              "drain finished; hard exit", grace)
                    os._exit(EXIT_PREEMPTED)
                continue    # draining: don't stack snapshot barriers on it
            snap = orch.snapshot()
            if snap and args.verbose:
                log.info("progress: env_steps=%s portfolio_mean=%.2f",
                         snap.get("env_steps"), snap.get("portfolio_mean", 0.0))
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        elapsed = time.perf_counter() - t0

        done = orch.is_everything_done()
        if orch.preempted or (preempt_at
                              and done.state is not ReplyState.COMPLETED):
            # A signal that lands in the same poll window as normal
            # completion does NOT preempt-label a finished run: completed
            # results are served below (the fleet must not --resume a run
            # that already delivered its answer).
            log.warning("run preempted; resume with --resume "
                        "(emergency checkpoint: %s)",
                        "written" if orch.preempt_saved
                        else "not confirmed — latest cadence checkpoint "
                             "is the resume point")
            return EXIT_PREEMPTED

        avg, std = orch.get_avg(), orch.get_std()
        if done.state is not ReplyState.COMPLETED or not avg.ok:
            log.error("training did not complete: %s (last error: %r)",
                      done, orch.last_error)
            return 1
        snap = orch.snapshot()
        total_agent_steps = snap.get("env_steps", 0.0) * cfg.parallel.num_workers
        # The reference's final log line (ShareTradeHelper.scala:46), plus rate.
        log.info("The average of the portfolios: %.4f, the standard deviation: %.4f",
                 avg.value, std.value)
        result = {
            "avg_portfolio": avg.value,
            "std_portfolio": std.value,
            "env_steps": snap.get("env_steps"),
            "updates": snap.get("updates"),
            "agent_steps_per_sec": total_agent_steps / max(elapsed, 1e-9),
            "elapsed_s": elapsed,
            "restarts": orch.restarts,
        }
        if args.eval:
            result.update(orch.evaluate())
        if args.eval_best:
            try:
                best = orch.evaluate_best()
            except FileNotFoundError:
                log.warning("--eval-best: no retained best checkpoint "
                            "(enable runtime.keep_best_eval and run --eval)")
            else:
                result.update({f"best_{k}": v for k, v in best.items()})
        print(json.dumps(result))
        return 0
    finally:
        if orch is not None:
            orch.stop()
        service.close()


def _serve_boot_params(manager, template, tag: str):
    """Initial serving weights: the tagged best policy when one exists,
    else the latest step checkpoint, else a fresh init (loud — an
    untrained policy serves finite garbage, not answers). Returns
    ``(params, step, boot_meta)``; ``boot_meta`` seeds the swap watcher's
    already-applied stamp."""
    try:
        state, meta = manager.restore_tagged(template, tag)
        return (state.params,
                int(meta.get("updates", meta.get("step", 0)) or 0), meta)
    except FileNotFoundError:
        pass
    try:
        state, step = manager.restore(template)
        return state.params, int(step), None
    except FileNotFoundError:
        log.warning("no checkpoint under %s; serving a fresh-initialized "
                    "(UNTRAINED) policy", manager.directory)
        return template.params, 0, None


def cmd_serve(args) -> int:
    """Continuous-batching inference service (serve/engine.py): coalesce
    per-session queries into padded device batches over the session slot
    pool, hot-swap weights from the training run's ``tag_best`` checkpoint,
    and export SLO gauges through obs/. Driven here by the synthetic
    session replayer (serve/driver.py) — a network front-end would sit on
    ``ServeEngine.submit`` the same way.

    Preemption-safe from day one: SIGTERM/SIGINT drains in-flight requests,
    flushes metrics, and exits ``EXIT_PREEMPTED`` (75) — the same contract
    as ``cli train``."""
    import jax

    from sharetrade_tpu.agents import build_agent
    from sharetrade_tpu.checkpoint.manager import CheckpointManager
    from sharetrade_tpu.env import trading
    from sharetrade_tpu.obs import build_obs
    from sharetrade_tpu.precision import policy_from_config
    from sharetrade_tpu.serve import ServeEngine, WeightSwapWatcher
    from sharetrade_tpu.serve.driver import (
        make_sessions,
        run_closed_loop,
        run_open_loop,
    )
    from sharetrade_tpu.utils.metrics import MetricsRegistry

    cfg = _load_config(args)
    service = PriceDataService(config=cfg.data)
    engine = watcher = obs_bundle = controller = None
    stop_evt = threading.Event()
    preempt_at: list[float] = []

    def _on_signal(signum, frame):
        if not preempt_at:
            log.warning("received %s; draining in-flight requests",
                        signal.Signals(signum).name)
            preempt_at.append(time.monotonic())
            stop_evt.set()
        else:
            log.warning("received %s during the drain; hard exit",
                        signal.Signals(signum).name)
            os._exit(EXIT_PREEMPTED)

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        response = service.request(args.symbol.split(",")[0].strip(),
                                   args.start, args.end)
        prices = response.series.prices
        env_params = trading.env_from_prices(
            prices, window=cfg.env.window,
            initial_budget=cfg.env.initial_budget,
            initial_shares=cfg.env.initial_shares)
        agent = build_agent(cfg, env_params)
        template = agent.init(jax.random.PRNGKey(cfg.seed))
        manager = CheckpointManager(
            cfg.runtime.checkpoint_dir, keep=cfg.runtime.keep_checkpoints,
            fsync=cfg.checkpoint.fsync, precision_mode=cfg.precision.mode)
        params, step, boot_meta = _serve_boot_params(
            manager, template, cfg.serve.swap_tag)

        registry = MetricsRegistry(
            max_points=cfg.obs.max_metric_points or None)
        obs_bundle = build_obs(cfg, registry)
        engine = ServeEngine(agent.model, cfg.serve, params,
                             params_step=step,
                             precision=policy_from_config(cfg.precision),
                             registry=registry, obs=obs_bundle,
                             obs_cfg=cfg.obs)
        engine.warmup()
        if cfg.tuning.serve_controller:
            # Online self-tuning (serve/controller.py): hold
            # tuning.target_p99_ms by adapting batch_timeout_ms/max_queue
            # below their configured ceilings — every adjustment lands as
            # gauges + flight-ring events.
            from sharetrade_tpu.serve import ServeController
            controller = ServeController(
                engine, target_p99_ms=cfg.tuning.target_p99_ms,
                interval_s=cfg.tuning.controller_interval_s,
                obs=obs_bundle).start()
        if cfg.serve.swap_poll_s > 0:
            watcher = WeightSwapWatcher(
                engine, manager, template, tag=cfg.serve.swap_tag,
                poll_s=cfg.serve.swap_poll_s, seen_meta=boot_meta,
                breaker_failures=cfg.serve.swap_breaker_failures,
                breaker_cooldown_s=cfg.serve.swap_breaker_cooldown_s,
            ).start()
        # Readiness line (machine-readable: the soak/tests wait on it).
        print(json.dumps({"event": "serving_ready", "params_step": step,
                          "model": agent.model.name,
                          "max_batch": cfg.serve.max_batch,
                          "slots": cfg.serve.slots}), flush=True)

        if args.listen:
            # Fleet worker mode (fleet/frontend.py): expose submit over
            # the wire instead of driving synthetic load. The client's
            # X-Deadline-Ms header flows into submit(deadline_ms=);
            # SIGTERM drains in-flight requests and exits 75 — the same
            # contract as the synthetic-driver mode, over a socket.
            from sharetrade_tpu.fleet import EngineBackend, ServeFrontend
            from sharetrade_tpu.fleet import proto as fleet_proto
            from sharetrade_tpu.fleet.wire import WireTracer
            # Pick the HTTP parse/render implementation BEFORE the
            # front-end spins up ("native" degrades loudly to "py"
            # when the extension isn't built — proto.set_backend).
            fleet_proto.set_backend(cfg.fleet.proto_backend)
            host, _, port_s = args.listen.rpartition(":")
            # Span journaling (ISSUE 17): a worker spawned by a tracing
            # fleet carries obs.span_dir/span_proc (fleet/pool.py) and
            # journals its engine spans there even with obs.enabled
            # false; the sink-less tracer parses inbound headers so
            # those spans parent under the router's attempt span.
            frontend = ServeFrontend(
                EngineBackend(
                    engine,
                    request_timeout_s=cfg.fleet.request_timeout_s,
                    spans=obs_bundle.spans),
                registry, host=host or "127.0.0.1",
                port=int(port_s or 0),
                wire_backend=cfg.fleet.wire_backend,
                tracer=(WireTracer() if obs_bundle.spans is not None
                        else None)).start()
            # The pool tails the worker's log for this line to learn the
            # ephemeral port (fleet/pool.py LISTENING_EVENT).
            print(json.dumps({"event": "engine_listening",
                              "host": frontend.host,
                              "port": frontend.port,
                              "pid": os.getpid(),
                              "proto_backend": fleet_proto.proto_backend,
                              "params_step": step}), flush=True)
            deadline = (time.monotonic() + args.duration
                        if args.duration > 0 else None)
            while not stop_evt.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                stop_evt.wait(0.2)
            frontend.drain(
                timeout_s=cfg.runtime.preempt_grace_s * 0.25)
            frontend.stop()
            stats = {"mode": "listen", "host": frontend.host,
                     "port": frontend.port}
        else:
            sessions = make_sessions(prices, cfg.env.window,
                                     args.sessions, seed=cfg.seed)
            if args.rate > 0:
                stats = run_open_loop(engine, sessions,
                                      rate_qps=args.rate,
                                      duration_s=args.duration,
                                      stop=stop_evt)
            else:
                stats = run_closed_loop(
                    engine, sessions, concurrency=cfg.serve.max_batch,
                    duration_s=args.duration, stop=stop_evt)

        # Drain + stop INSIDE the preemption grace budget (the hung-
        # thread check must run BEFORE the summary so the exit code
        # can't report a clean shutdown the threads didn't deliver).
        # The budget is subdivided: stop() waits on up to three seams
        # sequentially (dispatcher join, shutdown sentinel, consumer
        # join), so handing it the full grace each time could spend ~4x
        # grace with a hung consumer — past the point a fleet SIGKILLs
        # us, losing the summary entirely.
        grace = cfg.runtime.preempt_grace_s
        drained = engine.drain(timeout_s=grace * 0.5)
        if controller is not None:
            controller.stop()
        if watcher is not None:
            watcher.stop()
        # Per-seam timeout: the 1 s floor keeps healthy shutdowns from
        # flaking on a briefly-busy thread, but it must never push the
        # three sequential seams past the half of the grace budget left
        # after the drain — grace/6 caps the floor so a small
        # preempt_grace_s still beats the fleet's SIGKILL.
        stopped_clean = engine.stop(
            drain=False,
            timeout_s=min(max(grace / 8.0, 1.0), grace / 6.0))
        # Warm handoff (ISSUE 20): with the worker threads stopped, seal
        # every surviving carry into the spill arena so the engines this
        # one's sessions land on adopt them warm. Strictly AFTER stop()
        # (page_out_all refuses otherwise) and never allowed to sink a
        # clean shutdown — a failed page-out only costs adoptions.
        spill_pageout = None
        if stopped_clean:
            try:
                spill_pageout = engine.page_out_all()
            except Exception:   # noqa: BLE001 — degraded, not dead
                log.exception("drain page-out failed; this engine's "
                              "sessions will cold-restart elsewhere")
        engine_failed = engine.failed is not None
        obs_bundle.flush()
        counters = registry.counters()
        summary = {
            **stats,
            "params_step": engine.params_step,
            "swaps": int(counters.get("serve_swaps_total", 0)),
            "swap_rejected": int(
                counters.get("serve_swap_rejected_total", 0)),
            "swap_breaker_opens": int(
                counters.get("serve_swap_breaker_opens_total", 0)),
            "evictions": int(counters.get("serve_evictions_total", 0)),
            "prefills": int(counters.get("serve_prefills_total", 0)),
            "requests": int(counters.get("serve_requests_total", 0)),
            "shed": int(counters.get("serve_shed_total", 0)),
            "queue_rejected": int(
                counters.get("serve_queue_rejected_total", 0)),
            "deadline_expired": int(
                counters.get("serve_deadline_expired_total", 0)),
            "restarts": int(counters.get("serve_restarts_total", 0)),
            "controller_adjustments": int(
                counters.get("serve_controller_adjustments_total", 0)),
            "drained": drained,
            "stopped_clean": stopped_clean,
            "engine_failed": engine_failed,
        }
        # Session-tier counters (ISSUE 18): only meaningful when the
        # warm tier is on (serve.warm_bytes > 0), so gate on activity.
        warm_parks = int(counters.get("serve_warm_parks_total", 0))
        warm_hits = int(counters.get("serve_warm_hits_total", 0))
        warm_misses = int(counters.get("serve_warm_misses_total", 0))
        if warm_parks or warm_hits or warm_misses:
            summary["warm_parks"] = warm_parks
            summary["warm_hits"] = warm_hits
            summary["warm_misses"] = warm_misses
            summary["warm_demotions"] = int(
                counters.get("serve_warm_demotions_total", 0))
        # Spill-tier counters (ISSUE 20): gated the same way — only
        # meaningful with a spill arena configured.
        if spill_pageout is not None and any(spill_pageout.values()):
            summary["spill_pageout"] = spill_pageout
        spill_puts = int(counters.get("serve_spill_puts_total", 0))
        spill_hits = int(counters.get("serve_spill_hits_total", 0))
        if spill_puts or spill_hits:
            summary["spill_puts"] = spill_puts
            summary["spill_hits"] = spill_hits
            summary["adopt_warm"] = int(
                counters.get("serve_adopt_warm_total", 0))
            summary["adopt_cold"] = int(
                counters.get("serve_adopt_cold_total", 0))
            summary["spill_corrupt"] = int(
                counters.get("serve_spill_corrupt_total", 0))
        # Stage-decomposition tail (the ISSUE-11 observability surface):
        # histogram-derived per-stage p99s plus the slowest exemplars —
        # the "which stage owns the tail" answer in the run summary.
        from sharetrade_tpu.obs import serve_stage_p99s
        stage_p99 = serve_stage_p99s(registry)
        if stage_p99:
            summary["stage_p99_ms"] = stage_p99
        slowest = engine.exemplars()[:3]
        if slowest:
            summary["slowest"] = slowest
        for key, gauge in (("slo_availability_burn",
                            "serve_slo_availability_burn"),
                           ("slo_latency_burn", "serve_slo_latency_burn")):
            value = registry.latest(gauge)
            if value is not None:
                summary[key] = round(value, 4)
        if preempt_at:
            summary["preempted"] = True
            log.warning("serve run preempted; in-flight requests %s",
                        "drained" if drained else "NOT fully drained")
        if engine_failed:
            log.error("serve engine ended in the TERMINAL FAILED state "
                      "(restart storm past serve.max_restarts): %r",
                      engine.failed)
        print(json.dumps(summary))
        if preempt_at:
            return EXIT_PREEMPTED
        if not stopped_clean or engine_failed:
            # A hung dispatcher/consumer thread — or an engine that died
            # in its terminal failed state mid-run — must surface as a
            # failed run, not a quiet success.
            return 1
        return 0
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        if controller is not None:
            controller.stop()
        if watcher is not None:
            watcher.stop()
        if engine is not None:
            engine.stop(drain=False)
        if obs_bundle is not None:
            obs_bundle.close()
        service.close()


def cmd_actor(args) -> int:
    """One rollout-actor process (distrib/actor.py) — a separate failure
    domain of the disaggregated actor/learner topology: verified-restore
    weights from ``tag_best``, epsilon-greedy rollouts, transitions
    appended to this actor's OWN journal under
    ``<distrib.actor_dir>/<actor-id>/``, heartbeat stamps for the
    supervising :class:`ActorPool`. Normally spawned BY the pool
    (``cli learner``), but runnable by hand for debugging.

    Preemption contract matches ``cli train``: SIGTERM/SIGINT drains
    (journal flush + final heartbeat) and exits 75; a second signal hard-
    exits."""
    from sharetrade_tpu.distrib.actor import RolloutActor

    cfg = _load_config(args)
    if not args.actor_id:
        log.error("--actor-id is required")
        return 1
    workdir = os.path.join(cfg.distrib.actor_dir, args.actor_id)
    # The actor's data layer is scoped to ITS directory: sharing the
    # learner's journal_dir would contend for the price-event journal's
    # writer lock (and worse, interleave transition records — the exact
    # torn-record scenario the per-actor layout exists to prevent).
    cfg.data.journal_dir = workdir
    # Telemetry stays with the learner: an actor writing the shared obs
    # run dir would fight the learner's manifest/exporter; actor health
    # flows through heartbeats -> pool gauges instead.
    cfg.obs.enabled = False

    stop_evt = threading.Event()
    preempted: list[float] = []

    def _on_signal(signum, frame):
        if not preempted:
            log.warning("actor %s received %s; draining", args.actor_id,
                        signal.Signals(signum).name)
            preempted.append(time.monotonic())
            stop_evt.set()
        else:
            os._exit(EXIT_PREEMPTED)

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)}
    service = PriceDataService(config=cfg.data)
    try:
        response = service.request(args.symbol.split(",")[0].strip(),
                                   args.start, args.end)
        actor = RolloutActor(cfg, response.series.prices,
                             actor_id=args.actor_id, workdir=workdir)
        print(json.dumps({"event": "actor_ready",
                          "actor_id": args.actor_id,
                          "pid": os.getpid(),
                          "params_step": actor.params_step,
                          "journal": actor.journal_path}), flush=True)
        summary = actor.run(stop_evt, max_chunks=args.max_chunks)
        print(json.dumps(summary), flush=True)
        return EXIT_PREEMPTED if preempted else 0
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        service.close()


def cmd_learner(args) -> int:
    """The learner process of the disaggregated topology: hosts the
    :class:`ActorPool` supervisor (N ``cli actor`` subprocesses under the
    process-granular supervision contract) AND the training loop, which
    tails every actor's journal between megachunks
    (``Orchestrator.ingest_actor_feeds``), trains, and republishes
    ``tag_best`` for the actors to hot-swap — the closed loop.

    The learner is its own failure domain: actors dying (and being
    respawned, or failing terminally) never restarts this process — the
    property the kill-test (tools/actor_soak.py) asserts after every
    injection. SIGTERM drains BOTH tiers (pool SIGTERMs its actors, the
    orchestrator writes ``tag_preempt``) and exits 75."""
    from sharetrade_tpu.distrib.pool import ActorPool
    from sharetrade_tpu.runtime import Orchestrator, ReplyState

    cfg = _load_config(args)
    if cfg.distrib.num_actors < 1:
        log.error("cli learner needs distrib.num_actors >= 1 "
                  "(got %d); use cli train for the single-process loop",
                  cfg.distrib.num_actors)
        return 1
    if cfg.learner.algo != "dqn" and cfg.distrib.ingest_every_updates > 0:
        log.error("actor-feed ingest requires learner.algo=dqn (replay "
                  "buffer); got %r", cfg.learner.algo)
        return 1
    if cfg.data.journal_segment_records <= 0:
        # Single-file actor journals would grow without bound (the
        # actor-side retirement only runs with rotation on) and make
        # every ingest tick re-decode the whole rollout history; the
        # saved config flows to the spawned actors, so defaulting here
        # covers the fleet.
        cfg.data.journal_segment_records = 256
        log.info("distrib: defaulting data.journal_segment_records=256 "
                 "(rotation is required for bounded actor journals and "
                 "bounded ingest reads)")
    service = PriceDataService(config=cfg.data)
    orch = None
    pool = None
    preempt_at: list[float] = []

    def _on_signal(signum, frame):
        if not preempt_at:
            log.warning("received %s; draining learner + actor pool",
                        signal.Signals(signum).name)
            preempt_at.append(time.monotonic())
        else:
            log.warning("received %s during the drain; hard exit",
                        signal.Signals(signum).name)
            # os._exit skips every finally: anything not killed NOW is an
            # orphaned actor rolling out forever with no supervisor.
            if pool is not None:
                pool.kill_all()
            os._exit(EXIT_PREEMPTED)
        if pool is not None:
            # A fleet preemption TERMs the whole process group: the
            # actors are draining alongside us, and the pool must stop
            # classifying their graceful exits as crashes (respawning
            # fresh actors into a dying run).
            pool.quiesce()
        if orch is not None:
            orch.request_preempt()

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        response = service.request(args.symbol.split(",")[0].strip(),
                                   args.start, args.end)
        prices = response.series.prices
        orch = Orchestrator(cfg)
        if preempt_at:
            orch.request_preempt()
        pool = ActorPool(cfg, registry=orch.metrics, symbol=args.symbol,
                         start=args.start, end=args.end).start()
        if preempt_at:
            # SIGTERM landed during orchestrator bring-up, before the
            # handler had a pool to quiesce: re-apply it here or the pool
            # respawns group-TERM'd actors into the dying run.
            pool.quiesce()
        print(json.dumps({"event": "learner_ready", "pid": os.getpid(),
                          "actors": cfg.distrib.num_actors,
                          "pool_dir": pool.dir}), flush=True)
        t0 = time.perf_counter()
        try:
            orch.send_training_data(prices, resume=args.resume)
        except FileNotFoundError as exc:
            log.error("--resume: %s (train without --resume first)", exc)
            return 1
        orch.start_training(background=True)
        grace = cfg.runtime.preempt_grace_s
        while not orch.wait(timeout=cfg.runtime.poll_interval_s):
            if preempt_at and (time.monotonic() - preempt_at[0]
                               > grace + 5.0):
                log.error("preemption grace (%.1fs) expired before the "
                          "drain finished; hard exit", grace)
                pool.kill_all()     # os._exit skips the finally teardown
                os._exit(EXIT_PREEMPTED)
        elapsed = time.perf_counter() - t0

        done = orch.is_everything_done()
        pool.stop(grace_s=grace)
        counters = orch.metrics.counters()
        snap = orch.snapshot()
        summary = {
            "env_steps": snap.get("env_steps"),
            "updates": snap.get("updates"),
            "elapsed_s": elapsed,
            "learner_restarts": orch.restarts,
            "actor_restarts": pool.restarts_total,
            "rows_ingested": int(
                counters.get("distrib_rows_ingested_total", 0)),
            **{f"actors_{k}": v for k, v in pool.counts().items()},
        }
        if orch.preempted or (preempt_at
                              and done.state is not ReplyState.COMPLETED):
            summary["preempted"] = True
            print(json.dumps(summary))
            return EXIT_PREEMPTED
        if done.state is not ReplyState.COMPLETED:
            log.error("learner did not complete: %s (last error: %r)",
                      done, orch.last_error)
            print(json.dumps(summary))
            return 1
        avg, std = orch.get_avg(), orch.get_std()
        if avg.ok:
            summary["avg_portfolio"] = avg.value
            summary["std_portfolio"] = std.value
        print(json.dumps(summary))
        return 0
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        if pool is not None:
            pool.stop(grace_s=10.0)
        if orch is not None:
            orch.stop()
        service.close()


def cmd_fleet(args) -> int:
    """The whole serving fleet in one command (fleet/): N supervised
    ``cli serve --listen`` engine workers (EnginePool), the telemetry-
    driven router behind one public front-end port, and — with
    ``--learner`` — a live in-process learner closing the
    train→serve→train flywheel: served sessions journal transitions
    under ``distrib.actor_dir`` (fleet/flywheel.py), the learner tails
    them between megachunks (``distrib.ingest_without_pool``),
    republishes ``tag_best``, and every engine's swap watcher hot-swaps
    it in.

    Machine-readable ``fleet_ready`` line once the router port is bound
    and every engine reported listening; SIGTERM drains the front-end,
    the engines (their own drain → 75 contract) and the learner, then
    exits 75."""
    from sharetrade_tpu.fleet import EnginePool, FleetRouter, ServeFrontend
    from sharetrade_tpu.utils.metrics import MetricsRegistry
    from sharetrade_tpu.obs import build_obs

    cfg = _load_config(args)
    if args.engines:
        cfg.fleet.num_engines = args.engines
    if getattr(args, "autoscale", False):
        cfg.fleet.autoscale = True
    if args.learner:
        # The flywheel's learner half: ingest session journals with no
        # ActorPool in this process, and evaluate often enough that
        # tag_best republishes while the fleet is live.
        cfg.distrib.ingest_without_pool = True
        if cfg.learner.algo != "dqn":
            log.error("--learner requires learner.algo=dqn (replay "
                      "ingest); got %r", cfg.learner.algo)
            return 1
        if cfg.data.journal_segment_records <= 0:
            cfg.data.journal_segment_records = 256
    service = orch = None
    pool = router = frontend = obs_bundle = autoscaler = None
    stop_evt = threading.Event()
    preempt_at: list[float] = []

    def _on_signal(signum, frame):
        if not preempt_at:
            log.warning("received %s; draining the fleet",
                        signal.Signals(signum).name)
            preempt_at.append(time.monotonic())
            stop_evt.set()
            if pool is not None:
                pool.quiesce()
            if orch is not None:
                orch.request_preempt()
        else:
            log.warning("received %s during the drain; hard exit",
                        signal.Signals(signum).name)
            if pool is not None:
                pool.kill_all()     # os._exit skips every finally
            os._exit(EXIT_PREEMPTED)

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        registry = MetricsRegistry(
            max_points=cfg.obs.max_metric_points or None)
        if cfg.obs.enabled and cfg.obs.trace and not cfg.obs.span_dir:
            # Fleet-wide distributed tracing (ISSUE 17): one shared
            # spans dir; this process journals as "fleet", each worker
            # as "engine-<id>" (fleet/pool.py injects the same dir).
            cfg.obs.span_dir = os.path.join(cfg.obs.dir, "spans")
            cfg.obs.span_proc = cfg.obs.span_proc or "fleet"
        obs_bundle = build_obs(cfg, registry)
        # Pick the HTTP parse/render implementation for the router's
        # own front-end and FleetClient relay legs before anything
        # touches the wire; workers pick theirs from the same config.
        from sharetrade_tpu.fleet import proto as fleet_proto
        fleet_proto.set_backend(cfg.fleet.proto_backend)
        pool = EnginePool(cfg, registry=registry, symbol=args.symbol,
                          start=args.start, end=args.end).start()
        if preempt_at:
            pool.quiesce()
        router = FleetRouter(pool, cfg.fleet, registry,
                             workdir=cfg.fleet.dir, obs_cfg=cfg.obs,
                             obs=obs_bundle).start()
        from sharetrade_tpu.fleet.wire import WireTracer
        frontend = ServeFrontend(
            router, registry, host=cfg.fleet.host, port=cfg.fleet.port,
            wire_backend=cfg.fleet.wire_backend,
            tracer=(WireTracer(obs_bundle.spans, mint=True)
                    if obs_bundle.spans is not None else None)).start()
        if cfg.fleet.autoscale:
            # Membership control loop (ISSUE 18): reads the router's
            # telemetry history ring, drives EnginePool.scale within
            # [min_engines, max_engines].
            from sharetrade_tpu.fleet.autoscale import EngineAutoscaler
            autoscaler = EngineAutoscaler(
                pool, cfg.fleet, workdir=cfg.fleet.dir,
                registry=registry, obs=obs_bundle).start()

        if args.learner:
            from sharetrade_tpu.config import FrameworkConfig
            from sharetrade_tpu.runtime import Orchestrator
            service = PriceDataService(config=cfg.data)
            response = service.request(args.symbol.split(",")[0].strip(),
                                       args.start, args.end)
            # The orchestrator owns its OWN obs bundle; scope it to a
            # subdir so two exporters never fight over one run dir's
            # manifest/metrics files (learner telemetry lands in
            # <obs.dir>/learner, fleet telemetry in <obs.dir>).
            learner_cfg = FrameworkConfig.from_dict(cfg.to_dict())
            learner_cfg.distrib.ingest_without_pool = True
            if learner_cfg.obs.enabled:
                learner_cfg.obs.dir = os.path.join(cfg.obs.dir,
                                                   "learner")
            orch = Orchestrator(learner_cfg)
            if preempt_at:
                orch.request_preempt()
            orch.send_training_data(response.series.prices,
                                    resume=args.resume)
            orch.start_training(background=True)

        # Readiness: every engine reported its port (or hit its
        # bring-up budget — surface what came up either way).
        deadline = time.monotonic() + cfg.fleet.startup_timeout_s + 10.0
        while (time.monotonic() < deadline and not stop_evt.is_set()
               and len(pool.endpoints()) < cfg.fleet.num_engines):
            stop_evt.wait(0.25)
        router.poll_once()
        print(json.dumps({"event": "fleet_ready",
                          "host": frontend.host, "port": frontend.port,
                          "engines": len(pool.endpoints()),
                          "target_engines": cfg.fleet.num_engines,
                          "dir": cfg.fleet.dir,
                          "wire_backend": cfg.fleet.wire_backend,
                          "proto_backend": fleet_proto.proto_backend,
                          "learner": bool(args.learner),
                          "pid": os.getpid()}), flush=True)

        run_deadline = (time.monotonic() + args.duration
                        if args.duration > 0 else None)
        while not stop_evt.is_set():
            if (run_deadline is not None
                    and time.monotonic() >= run_deadline):
                break
            stop_evt.wait(0.25)

        grace = cfg.fleet.drain_grace_s
        if autoscaler is not None:
            autoscaler.stop()   # membership frozen before the drain
        frontend.drain(timeout_s=grace * 0.5)
        frontend.stop()
        router.stop()
        pool.stop(grace_s=grace)
        if orch is not None:
            orch.stop()
        obs_bundle.flush()
        counters = registry.counters()
        summary = {
            "requests": int(counters.get("fleet_requests_total", 0)),
            "completed": int(counters.get("fleet_completed_total", 0)),
            "refused": int(counters.get("fleet_refused_total", 0)),
            "migrations": int(
                counters.get("fleet_migrations_total", 0)),
            "engine_restarts": pool.restarts_total,
            **{f"engines_{k}": v for k, v in pool.counts().items()},
        }
        if autoscaler is not None:
            summary["scale_events"] = pool.scale_events
            summary["autoscale_up"] = int(
                counters.get("fleet_autoscale_up_total", 0))
            summary["autoscale_down"] = int(
                counters.get("fleet_autoscale_down_total", 0))
        if orch is not None:
            snap = orch.snapshot() or {}
            summary["learner_updates"] = snap.get("updates")
            summary["rows_ingested"] = int(orch.metrics.counters().get(
                "distrib_rows_ingested_total", 0))
        if preempt_at:
            summary["preempted"] = True
        print(json.dumps(summary))
        return EXIT_PREEMPTED if preempt_at else 0
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        if autoscaler is not None:
            autoscaler.stop()
        if frontend is not None:
            frontend.stop()
        if router is not None:
            router.stop()
        if pool is not None:
            pool.stop(grace_s=10.0)
        if orch is not None:
            orch.stop()
        if obs_bundle is not None:
            obs_bundle.close()
        if service is not None:
            service.close()


def cmd_obs(args) -> int:
    """Summarize a telemetry run dir (obs.enabled=true output): manifest
    identity, span aggregates from the Chrome trace, metrics tail, and the
    flight-recorder verdict when a bundle was dumped.

    ``--trace <id>`` (or ``--trace list``) switches to the ISSUE-17
    cross-process collector: stitch the span journals under
    ``<dir>/spans`` into one trace (``--out`` renders it for Perfetto).
    ``--history N`` reads the fleet router's per-poll gauge ring
    (``fleet_history.jsonl`` under ``--dir``, the fleet WORKDIR for this
    flag) and prints the last-N-windows summary."""
    import os

    from sharetrade_tpu.obs import summarize_run_dir

    if args.trace:
        from sharetrade_tpu.obs import collect
        spans_dir = os.path.join(args.dir, "spans")
        if not os.path.isdir(spans_dir):
            log.error("no span journals under %s (run `cli fleet` with "
                      "obs.enabled=true)", spans_dir)
            return 1
        if args.trace == "list":
            ids = collect.trace_ids(collect.read_span_dir(spans_dir))
            print(json.dumps({"spans_dir": spans_dir, "traces": ids},
                             indent=2))
            return 0
        stitched = collect.collect_trace(spans_dir, args.trace,
                                         out=args.out)
        if not stitched["spans"]:
            log.error("trace %s not found under %s (try --trace list)",
                      args.trace, spans_dir)
            return 1
        view = {"trace_id": stitched["trace_id"],
                "procs": stitched["procs"],
                "errors": stitched["errors"],
                "spans": [{k: s.get(k) for k in
                           ("name", "proc", "span", "parent", "ts_us",
                            "dur_us", "note") if k in s}
                          for s in stitched["spans"]]}
        if "perfetto" in stitched:
            view["perfetto"] = stitched["perfetto"]
        print(json.dumps(view, indent=2))
        return 0 if not stitched["errors"] else 1
    if args.history is not None:
        from sharetrade_tpu.obs.tsdb import (FLEET_HISTORY_FILE,
                                             read_history,
                                             summarize_history)
        path = os.path.join(args.dir, FLEET_HISTORY_FILE)
        rows = read_history(path, last_n=max(0, args.history))
        if not rows:
            log.error("no telemetry history at %s (the fleet router "
                      "writes it next to fleet_status.json)", path)
            return 1
        print(json.dumps({"path": path,
                          **summarize_history(rows)}, indent=2))
        return 0
    if not os.path.isdir(args.dir):
        log.error("no run dir at %s (train with --set obs.enabled=true "
                  "--set obs.dir=%s first)", args.dir, args.dir)
        return 1
    summary = summarize_run_dir(args.dir)
    if len(summary) <= 1:   # only {"run_dir": ...}: nothing telemetric inside
        log.error("%s contains no telemetry artifacts "
                  "(manifest.json/trace.jsonl/metrics.jsonl)", args.dir)
        return 1
    print(json.dumps(summary, indent=2))
    return 0


def cmd_query(args) -> int:
    cfg = _load_config(args)
    service = PriceDataService(config=cfg.data)
    response = service.request(args.symbol, args.start, args.end)
    series = response.series
    print(json.dumps({
        "symbol": response.symbol,
        "rows": len(series),
        "first": str(series.dates[0]) if len(series) else None,
        "last": str(series.dates[-1]) if len(series) else None,
    }))
    service.close()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="sharetrade_tpu")
    parser.add_argument("--verbose", action="store_true")
    sub = parser.add_subparsers(dest="command", required=True)

    for name, fn in [("train", cmd_train), ("query", cmd_query),
                     ("serve", cmd_serve), ("actor", cmd_actor),
                     ("learner", cmd_learner), ("fleet", cmd_fleet)]:
        p = sub.add_parser(name)
        p.add_argument("--config", default=None, help="JSON config file")
        p.add_argument("--set", action="append", default=[],
                       metavar="SECTION.KEY=VALUE", help="config override")
        p.add_argument("--symbol", default="MSFT")
        # The reference asks for 1992-01-01..2015-01-01 (ShareTradeHelper.scala:23)
        p.add_argument("--start", default=None)
        p.add_argument("--end", default=None)
        p.add_argument("--verbose", action="store_true")
        if name == "train":
            p.add_argument("--mesh", action="store_true",
                           help="shard over all visible devices")
            p.add_argument("--resume", action="store_true",
                           help="restore the latest checkpoint and continue")
            p.add_argument("--eval", action="store_true",
                           help="greedy-policy evaluation after training")
            p.add_argument("--eval-best", action="store_true",
                           help="also evaluate the retained best-eval "
                                "checkpoint (runtime.keep_best_eval)")
        if name == "actor":
            p.add_argument("--actor-id", default=None,
                           help="this actor's id (its per-actor dir under "
                                "distrib.actor_dir)")
            p.add_argument("--max-chunks", type=int, default=0,
                           help="stop after this many rollout chunks "
                                "(0 = until SIGTERM)")
        if name == "learner":
            p.add_argument("--resume", action="store_true",
                           help="restore the latest checkpoint and "
                                "continue")
        if name == "serve":
            p.add_argument("--duration", type=float, default=10.0,
                           help="seconds to serve the synthetic load "
                                "(SIGTERM drains and exits 75 earlier; "
                                "with --listen, 0 = until SIGTERM)")
            p.add_argument("--sessions", type=int, default=512,
                           help="synthetic user sessions to replay")
            p.add_argument("--rate", type=float, default=0.0,
                           help="open-loop offered QPS; 0 = closed loop "
                                "at serve.max_batch concurrency")
            p.add_argument("--listen", default=None, metavar="HOST:PORT",
                           help="fleet worker mode: expose submit over "
                                "the wire (fleet/frontend.py) instead "
                                "of driving synthetic load; port 0 = "
                                "ephemeral, reported in the "
                                "engine_listening line")
        if name == "fleet":
            p.add_argument("--engines", type=int, default=0,
                           help="engine workers (0 = fleet.num_engines)")
            p.add_argument("--duration", type=float, default=0.0,
                           help="seconds to run (0 = until SIGTERM)")
            p.add_argument("--learner", action="store_true",
                           help="run the flywheel's live learner in-"
                                "process (ingest session journals, "
                                "republish tag_best)")
            p.add_argument("--resume", action="store_true",
                           help="learner resumes the latest checkpoint")
            p.add_argument("--autoscale", action="store_true",
                           help="drive EnginePool.scale from the "
                                "telemetry history ring (fleet/"
                                "autoscale.py; implies fleet.autoscale)")
        p.set_defaults(fn=fn)

    p = sub.add_parser("obs", help="summarize a telemetry run dir")
    p.add_argument("--dir", default="obs",
                   help="run dir written by a train run with obs.enabled "
                        "(for --history: the fleet workdir holding "
                        "fleet_history.jsonl)")
    p.add_argument("--trace", default=None, metavar="TRACE_ID",
                   help="stitch one cross-process trace from the span "
                        "journals under <dir>/spans ('list' enumerates "
                        "trace ids)")
    p.add_argument("--out", default=None,
                   help="with --trace: write the stitched trace as "
                        "Perfetto/Chrome trace-event JSON here")
    p.add_argument("--history", type=int, default=None, metavar="N",
                   help="summarize the newest N fleet telemetry-history "
                        "rows (0 = all retained)")
    p.set_defaults(fn=cmd_obs)

    args = parser.parse_args(argv)
    configure()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
