"""Command-line driver — the ShareTradeHelper entry point, with flags.

Reference: ``object ShareTradeHelper extends App`` wires the system with
hard-coded constants and polls ``IsEverythingDone`` every 5 s
(ShareTradeHelper.scala:14-48). Here the same flow takes a config file +
``--set section.key=value`` overrides (the flag surface the reference lacks,
SURVEY.md §5), runs the compiled training loop, and reports the avg/std
portfolio aggregation plus throughput.

    python -m sharetrade_tpu.cli train [--config cfg.json] [--set k=v ...]
    python -m sharetrade_tpu.cli query --config cfg.json   # inspect data layer
    python -m sharetrade_tpu.cli obs --dir obs             # summarize a run dir
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.data.service import PriceDataService
from sharetrade_tpu.utils.logging import configure, get_logger

log = get_logger("cli")


def _load_config(args) -> FrameworkConfig:
    cfg = (FrameworkConfig.from_file(args.config) if args.config
           else FrameworkConfig())
    if args.set:
        cfg = cfg.apply_overrides(args.set)
    return cfg


def cmd_train(args) -> int:
    from sharetrade_tpu.runtime import Orchestrator, ReplyState
    from sharetrade_tpu.parallel import build_mesh

    cfg = _load_config(args)
    service = PriceDataService(config=cfg.data)
    orch = None
    try:
        symbols = [s.strip() for s in args.symbol.split(",") if s.strip()]
        if len(symbols) > 1:
            # Multi-asset portfolio: align the symbols on common dates.
            from sharetrade_tpu.data.ingest import align_series
            series = [service.request(s, args.start, args.end).series
                      for s in symbols]
            prices = align_series(series)
            log.info("loaded %s prices for %d assets %s",
                     prices.shape, len(symbols), symbols)
        else:
            response = service.request(symbols[0], args.start, args.end)
            prices = response.series.prices
            log.info("loaded %d prices for %s", len(prices), symbols[0])

        mesh = build_mesh(cfg.parallel) if args.mesh else None
        if mesh is not None:
            # The agent batch shards over dp; round workers up to a multiple
            # so the default 10 workers still run on an 8-chip mesh.
            dp = mesh.shape.get(cfg.parallel.data_axis, 1)
            if cfg.parallel.num_workers % dp:
                adjusted = ((cfg.parallel.num_workers + dp - 1) // dp) * dp
                log.warning("num_workers=%d not divisible by dp=%d; using %d",
                            cfg.parallel.num_workers, dp, adjusted)
                cfg.parallel.num_workers = adjusted
        orch = Orchestrator(cfg, mesh=mesh)
        t0 = time.perf_counter()
        try:
            orch.send_training_data(prices, resume=args.resume)
        except FileNotFoundError as exc:
            log.error("--resume: %s (train without --resume first)", exc)
            return 1
        orch.start_training(background=True)

        # Driver poll loop (ShareTradeHelper.scala:32-48), with a sane cadence.
        poll_s = cfg.runtime.poll_interval_s
        while not orch.wait(timeout=poll_s):
            snap = orch.snapshot()
            if snap and args.verbose:
                log.info("progress: env_steps=%s portfolio_mean=%.2f",
                         snap.get("env_steps"), snap.get("portfolio_mean", 0.0))
        elapsed = time.perf_counter() - t0

        done = orch.is_everything_done()
        avg, std = orch.get_avg(), orch.get_std()
        if done.state is not ReplyState.COMPLETED or not avg.ok:
            log.error("training did not complete: %s (last error: %r)",
                      done, orch.last_error)
            return 1
        snap = orch.snapshot()
        total_agent_steps = snap.get("env_steps", 0.0) * cfg.parallel.num_workers
        # The reference's final log line (ShareTradeHelper.scala:46), plus rate.
        log.info("The average of the portfolios: %.4f, the standard deviation: %.4f",
                 avg.value, std.value)
        result = {
            "avg_portfolio": avg.value,
            "std_portfolio": std.value,
            "env_steps": snap.get("env_steps"),
            "updates": snap.get("updates"),
            "agent_steps_per_sec": total_agent_steps / max(elapsed, 1e-9),
            "elapsed_s": elapsed,
            "restarts": orch.restarts,
        }
        if args.eval:
            result.update(orch.evaluate())
        if args.eval_best:
            try:
                best = orch.evaluate_best()
            except FileNotFoundError:
                log.warning("--eval-best: no retained best checkpoint "
                            "(enable runtime.keep_best_eval and run --eval)")
            else:
                result.update({f"best_{k}": v for k, v in best.items()})
        print(json.dumps(result))
        return 0
    finally:
        if orch is not None:
            orch.stop()
        service.close()


def cmd_obs(args) -> int:
    """Summarize a telemetry run dir (obs.enabled=true output): manifest
    identity, span aggregates from the Chrome trace, metrics tail, and the
    flight-recorder verdict when a bundle was dumped."""
    import os

    from sharetrade_tpu.obs import summarize_run_dir

    if not os.path.isdir(args.dir):
        log.error("no run dir at %s (train with --set obs.enabled=true "
                  "--set obs.dir=%s first)", args.dir, args.dir)
        return 1
    summary = summarize_run_dir(args.dir)
    if len(summary) <= 1:   # only {"run_dir": ...}: nothing telemetric inside
        log.error("%s contains no telemetry artifacts "
                  "(manifest.json/trace.jsonl/metrics.jsonl)", args.dir)
        return 1
    print(json.dumps(summary, indent=2))
    return 0


def cmd_query(args) -> int:
    cfg = _load_config(args)
    service = PriceDataService(config=cfg.data)
    response = service.request(args.symbol, args.start, args.end)
    series = response.series
    print(json.dumps({
        "symbol": response.symbol,
        "rows": len(series),
        "first": str(series.dates[0]) if len(series) else None,
        "last": str(series.dates[-1]) if len(series) else None,
    }))
    service.close()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="sharetrade_tpu")
    parser.add_argument("--verbose", action="store_true")
    sub = parser.add_subparsers(dest="command", required=True)

    for name, fn in [("train", cmd_train), ("query", cmd_query)]:
        p = sub.add_parser(name)
        p.add_argument("--config", default=None, help="JSON config file")
        p.add_argument("--set", action="append", default=[],
                       metavar="SECTION.KEY=VALUE", help="config override")
        p.add_argument("--symbol", default="MSFT")
        # The reference asks for 1992-01-01..2015-01-01 (ShareTradeHelper.scala:23)
        p.add_argument("--start", default=None)
        p.add_argument("--end", default=None)
        p.add_argument("--verbose", action="store_true")
        if name == "train":
            p.add_argument("--mesh", action="store_true",
                           help="shard over all visible devices")
            p.add_argument("--resume", action="store_true",
                           help="restore the latest checkpoint and continue")
            p.add_argument("--eval", action="store_true",
                           help="greedy-policy evaluation after training")
            p.add_argument("--eval-best", action="store_true",
                           help="also evaluate the retained best-eval "
                                "checkpoint (runtime.keep_best_eval)")
        p.set_defaults(fn=fn)

    p = sub.add_parser("obs", help="summarize a telemetry run dir")
    p.add_argument("--dir", default="obs",
                   help="run dir written by a train run with obs.enabled")
    p.set_defaults(fn=cmd_obs)

    args = parser.parse_args(argv)
    configure()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
