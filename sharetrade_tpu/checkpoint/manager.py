"""Atomic, retained, resumable checkpoints of the full TrainState.

Layout (one directory per checkpoint, like an orbax step dir):

    <dir>/ckpt_0000000500/state.msgpack   flax-serialized TrainState pytree
    <dir>/ckpt_0000000500/meta.json       step, wall time, user metadata

Write protocol: serialize into ``<dir>/tmp-<step>-<pid>`` then ``os.replace``
to the final name — a torn write can never look like a complete checkpoint
(the same crash-safety contract as the framed journal, data/journal.py). The
newest ``keep`` checkpoints are retained; older ones are pruned after a
successful save, never before.

Host-side Python is the right tool here (checkpointing is host IO —
SURVEY.md §2.4); arrays are fetched with ``jax.device_get`` and restored with
the caller's template TrainState, so sharded states come back placed however
the caller's ``device_put``/shardings dictate.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import shutil
import threading
import time
from typing import Any

import jax
import numpy as np
from flax import serialization

from sharetrade_tpu.utils.logging import get_logger

log = get_logger("checkpoint")

_PREFIX = "ckpt_"


class CheckpointManager:
    def __init__(self, directory: str, *, keep: int = 3, tracer: Any = None):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        self._worker: threading.Thread | None = None
        self._queue: queue.Queue | None = None
        self._inflight = 0                   # queued + mid-write async saves
        self._cv = threading.Condition()
        # Optional obs SpanTracer (settable post-construction): save/restore
        # phases land in the host trace timeline — including writes on the
        # async worker thread (the tracer is thread-safe).
        self.tracer = tracer

    def _span(self, name: str, **args: Any):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **args)

    # ---- save ----

    def save(self, step: int, train_state: Any,
             metadata: dict[str, Any] | None = None) -> str:
        with self._span("checkpoint_save", step=int(step)):
            return self._save(step, train_state, metadata)

    def _save(self, step: int, train_state: Any,
              metadata: dict[str, Any] | None = None) -> str:
        host_state = jax.device_get(train_state)
        payload = serialization.to_bytes(host_state)
        meta = {"step": int(step), "saved_at": time.time(),
                **(metadata or {})}

        tmp = os.path.join(self.directory, f"tmp-{step}-{os.getpid()}")
        final = os.path.join(self.directory, f"{_PREFIX}{step:010d}")
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(payload)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(final):  # re-saving the same step: replace wholesale
            shutil.rmtree(final)
        os.replace(tmp, final)
        log.info("saved checkpoint step=%d (%d bytes)", step, len(payload))
        self._prune()
        return final

    def save_tagged(self, tag: str, train_state: Any,
                    metadata: dict[str, Any] | None = None) -> str:
        """Save under a NAME instead of a step — e.g. the best-greedy-eval
        policy (``runtime.keep_best_eval``). Tagged checkpoints live in
        ``<dir>/tag_<tag>`` outside the ``ckpt_`` namespace, so retention
        pruning never collects them and ``latest_step`` resume never picks
        them by accident; same atomic tmp+rename write protocol."""
        host_state = jax.device_get(train_state)
        payload = serialization.to_bytes(host_state)
        meta = {"tag": tag, "saved_at": time.time(), **(metadata or {})}
        tmp = os.path.join(self.directory, f"tmp-{tag}-{os.getpid()}")
        final = os.path.join(self.directory, f"tag_{tag}")
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(payload)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(final):
            # Unlike step saves, overwriting a tag is the ROUTINE path
            # (every best-eval improvement), so the old copy is renamed
            # aside — never deleted — until the swap lands: a crash at any
            # point leaves either the old or the new checkpoint readable
            # (restore_tagged falls back to the .old dir).
            old = final + ".old"
            shutil.rmtree(old, ignore_errors=True)
            os.replace(final, old)
            os.replace(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, final)
        log.info("saved tagged checkpoint %r (%d bytes)", tag, len(payload))
        return final

    def restore_tagged(self, template: Any, tag: str) -> tuple[Any, dict]:
        """Restore a tagged checkpoint; returns ``(state, metadata)``."""
        path = os.path.join(self.directory, f"tag_{tag}")
        if not os.path.isdir(path):
            # Crash window fallback: save_tagged renames the previous copy
            # aside before swapping the new one in.
            if os.path.isdir(path + ".old"):
                path = path + ".old"
            else:
                raise FileNotFoundError(
                    f"no {tag!r}-tagged checkpoint under {self.directory}")
        with open(os.path.join(path, "state.msgpack"), "rb") as f:
            payload = f.read()
        state = serialization.from_bytes(jax.device_get(template), payload)
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        log.info("restored tagged checkpoint %r", tag)
        return state, meta

    def tagged_metadata(self, tag: str) -> dict[str, Any] | None:
        """Metadata of a tagged checkpoint, or None if absent."""
        for name in (f"tag_{tag}", f"tag_{tag}.old"):
            path = os.path.join(self.directory, name, "meta.json")
            if os.path.isfile(path):
                with open(path) as f:
                    return json.load(f)
        return None

    def save_async(self, step: int, train_state: Any,
                   metadata: dict[str, Any] | None = None) -> None:
        """Minimal-stall save: all device→host DMAs are primed at once
        (``copy_to_host_async``), the caller blocks only until they land —
        mandatory, because donated-input steps will free these buffers on
        the next chunk — then serialization + disk IO run on a worker
        thread. Call :meth:`wait_pending` before reading the directory."""
        with self._span("checkpoint_snapshot", step=int(step)):
            for leaf in jax.tree.leaves(train_state):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            host_state = jax.device_get(train_state)  # fast: DMAs in flight
        # device_get can return ZERO-COPY views of the runtime's buffers
        # (owndata=False on the CPU backend). The caller's next donated-input
        # step frees/reuses those buffers while the writer thread is still
        # serializing — a use-after-free, not just a torn checkpoint — so the
        # handoff must own its memory. Copy ONLY the non-owning views:
        # accelerator backends already materialize owning host arrays, and
        # re-copying the whole parameter tree on the training thread would
        # double the save stall the async DMAs above exist to hide.
        host_state = jax.tree.map(
            lambda a: np.array(a, copy=True)
            if isinstance(a, np.ndarray) and not a.flags.owndata
            else a, host_state)
        if self._worker is None:
            self._queue = queue.Queue()
            self._worker = threading.Thread(
                target=self._drain, name="ckpt-writer", daemon=True)
            self._worker.start()
        with self._cv:
            self._inflight += 1  # counted BEFORE enqueue: no set/clear race
        self._queue.put((step, host_state, metadata))

    def _drain(self) -> None:
        while True:
            step, state, metadata = self._queue.get()
            try:
                self.save(step, state, metadata)
            except Exception:  # never kill the writer thread
                log.exception("async checkpoint save failed (step=%d)", step)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def wait_pending(self, timeout: float | None = None) -> bool:
        """Block until every queued/mid-write async save hit disk."""
        with self._cv:
            return self._cv.wait_for(lambda: self._inflight == 0, timeout)

    # ---- restore ----

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_PREFIX) and os.path.isfile(
                    os.path.join(self.directory, name, "meta.json")):
                try:
                    out.append(int(name[len(_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: int | None = None) -> tuple[Any, int]:
        """Restore into the structure of ``template`` (an uninitialized or
        freshly-initialized TrainState). Returns ``(state, step)``."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        with self._span("checkpoint_restore", step=int(step)):
            path = os.path.join(self.directory, f"{_PREFIX}{step:010d}")
            with open(os.path.join(path, "state.msgpack"), "rb") as f:
                payload = f.read()
            state = serialization.from_bytes(
                jax.device_get(template), payload)
        log.info("restored checkpoint step=%d", step)
        return state, step

    def metadata(self, step: int) -> dict[str, Any]:
        path = os.path.join(self.directory, f"{_PREFIX}{step:010d}", "meta.json")
        with open(path) as f:
            return json.load(f)

    # ---- retention ----

    def _prune(self) -> None:
        steps = self.steps()
        for old in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(
                self.directory, f"{_PREFIX}{old:010d}"), ignore_errors=True)
            log.debug("pruned checkpoint step=%d", old)
        # Abandoned tmp dirs from crashed writers are garbage-collected too.
        for name in os.listdir(self.directory):
            if name.startswith("tmp-"):
                full = os.path.join(self.directory, name)
                if time.time() - os.path.getmtime(full) > 3600:
                    shutil.rmtree(full, ignore_errors=True)
